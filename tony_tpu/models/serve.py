"""Continuous batching for the serving path.

Static-batch serving (one :func:`~tony_tpu.models.decode.generate` call
per request batch) leaves rows idle from the moment they finish until the
LAST row finishes — at mixed request lengths most of the batch is dead
weight. Continuous batching retires a row the step it completes and
admits the next queued request into its cache slot while the other rows
keep decoding; utilization follows the OFFERED load, not the slowest
request. (The industry-standard serving pattern; green-field here —
SURVEY.md §2.3, the reference delegates all compute and has no serving
path.)

The round-5 per-row decode machinery is exactly what makes this cheap
(models/decode.py): cache ``length`` is a [B] vector, RoPE positions,
causal masks, and K/V writes all take per-row frontiers, and the
length-aware block-wise attention reads only each batch's LIVE rows of a
shared padded cache. On top of that, the device programs:

- :func:`admit_rows` — a BUCKETED, BATCHED admission: K prompts padded
  to one power-of-two length bucket prefill in a single dispatch and
  land in K freed cache slots (one scatter per buffer), compiling once
  per bucket instead of once per distinct prompt length and paying one
  transport dispatch however many slots freed in the chunk;
- :func:`admit_row` — the single-slot admission the batched path
  replaced, kept for direct API use and the ``bucketed_admission=False``
  A/B arm; it pads to the same buckets (one program per bucket, not per
  length). Rolling (ring) caches, whose wrapped writes cannot take
  padded prompts, keep the exact-length :func:`admit_row_ring`;
- :func:`step_rows` — a ``lax.scan`` of ``n`` per-row decode steps over
  the whole batch (one dispatch per chunk, not per token; greedy by
  default, or sampled through the same top-k/temperature/nucleus stack
  as ``decode.generate`` — from PER-REQUEST key streams, see below);
- :func:`retire_rows` — zero the freed rows' frontiers so idle slots
  never walk off the end of the cache.

Correctness argument for slot reuse: a row's queries attend positions
``<= pos_r`` only. A new occupant's prefill rewrites positions
``[0, S_prompt)`` and its decode steps write exactly at ``pos_r`` before
reading it, so every position a query can reach was written by the
CURRENT occupant — the previous request's stale K/V beyond the frontier
is unreachable by construction (the same argument the speculative
decoder makes for rejected-draft entries). Bucketed admission extends it
one step: the padding tail's K/V (positions [len, bucket)) sits beyond
the frontier and every decode step overwrites position ``pos_r`` before
reading it, so padding rows are unreachable too.

The admission loop itself (:class:`ContinuousBatcher`) is host-driven —
admission is inherently data-dependent control flow (which request, into
which slot, at what length) and runs at human/request rate, while the
token loop stays on device in ``step_rows`` chunks. The loop is
PIPELINED (double-buffered dispatch): chunk N+1 is issued *before* chunk
N's tokens are fetched, so the host-side EOS/budget bookkeeping and the
transport round trip (~100 ms per sync on a tunneled chip) overlap
device compute instead of serializing with it. Nothing on the host feeds
the device between chunks — per-request rng streams are derivable ahead
of time — EXCEPT retirement/admission, which the loop handles two ways:
completions the host can PREDICT (budget exhaustion with requests still
queued) process their chunk synchronously so the admission lands before
the next dispatch, exactly as the sequential loop would; unpredictable
completions (an eos mid-chunk) are caught up AFTER the speculatively
issued chunk — the freed row ran one chunk of garbage that the host
discards exactly as idle-slot garbage is discarded, and the late
admission overwrites the slot before anything reads it.

Sampling uses PER-REQUEST key streams: request ``q``'s draw at its
``t``-th generated token comes from ``fold_in(fold_in(seed_key, q), t)``
— a function of the workload seed, the request index, and the step
alone. A request's sampled output is therefore independent of admission
timing and batch composition (the pre-pipelining loop's shared stream
made samples depend on WHEN a request was admitted), which is also what
lets the pipelined loop shift an admission by a chunk without changing
any output: pipelined and sequential (``pipeline=False``) serving are
token-identical in every mode — greedy, sampled, speculative, and
shared-prefix (test-enforced on CPU).

:class:`SpeculativeContinuousBatcher` composes the two serving features:
every slot runs draft-propose/target-verify rounds at its own frontier
(:func:`spec_step_rows`) while admission/retirement reuse slots exactly
as in the greedy batcher — vLLM-style continuous batching with
speculative decoding, token-identical to per-request greedy decode.

Shared-prefix caching (``shared_prefix=``, both batchers): a system
prompt every request continues from prefills ONCE into a K/V template;
admission copies the template into the slot and runs only the request's
own tokens through the model (:func:`prefix_admit_rows` — a chunked
``extend_step`` against the copied prefix history), token-identical to
serving prefix+prompt in full.

The host loop itself is OPEN-LOOP (:class:`ServeEngine`): the
issue/fetch/consume/settle cycle runs against a LIVE admission queue —
requests are submitted (and cancelled) at any time, from any thread, and
each request's newly generated tokens are emitted as a DELTA the moment
the chunk that produced them is consumed, not when the request retires.
That is what a streaming serving data plane needs: time-to-first-token
and inter-token latency are properties of delta emission, and a
persistent-connection server (``tony_tpu/serving/``) pushes each delta
to its client while the next chunk is still computing.
:meth:`ContinuousBatcher.serve` is a thin CLOSED-BATCH wrapper over the
engine — submit everything, drain, collect — and remains token-identical
(and ``steps_executed``-identical) to the pre-engine loop in every mode
(test-enforced).

DISAGGREGATED serving splits the two device workloads above across two
GANGS: a prefill gang runs :func:`prefill_ship_rows` on admitted
prompts and ships each row's K/V + last-real logits + rng stream state
as a :class:`KVPackage` (``tony_tpu/serving/kvship.py`` is the wire
codec, ``tony_tpu/serving/disagg.py`` the servers); the decode gang's
engine adopts packages through :meth:`ServeEngine.submit_prefilled`,
lands them with :func:`land_kv_rows` (a
:func:`~tony_tpu.models.decode.place_rows` scatter — no model forward),
and runs pure :func:`step_rows` chunks that are never preempted by
prefill compute. Token-identity argument: the shipped buffers are the
SAME mini cache ``admit_rows`` would have landed (truncated at the true
length — the padding tail beyond each frontier is unreachable by
construction, see the slot-reuse argument above), the logits are the
same last-real-position logits, and the shipped per-request rng key +
stream position reproduce the sampled stream exactly, so disaggregated
outputs match the colocated engine bit-for-bit (greedy AND sampled;
test-pinned end-to-end across two processes).

``TRACE_COUNTS`` records one entry per (program, static shape) TRACE —
a Python side effect inside the jitted bodies, executed at trace time
only — so tests (and the conftest retrace guard) can pin "bucketed
admission compiles once per bucket" as a regression invariant.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import (_check_draft_vocab, _check_no_ring,
                                    _filter_logits, _kv_bufs,
                                    _propose_and_verify,
                                    _propose_and_verify_sampled,
                                    decode_step, extend_step,
                                    init_kv_cache, place_rows, prefill,
                                    prefill_rows)
from tony_tpu.runtime import goodput as goodput_mod
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.runtime import tracing
from tony_tpu.runtime.profiler import PhaseTimes

#: Trace-time program counters keyed by (program name, static shape):
#: incremented when a serving device program is TRACED (compiled), not
#: when it is called. The bucketed-admission tests and the conftest
#: ``retrace_guard`` fixture assert on deltas of this counter.
TRACE_COUNTS: collections.Counter = collections.Counter()

#: smallest bucketed-admission pad length — prompts shorter than this
#: share one program rather than compiling 16 tiny variants
_MIN_ADMIT_BUCKET = 16

#: rng-stream id for rows with no occupant (their draws are garbage the
#: host discards; any fixed stream works)
_IDLE_STREAM = 0x7FFFFFFF


def _count_trace(name: str, shape) -> None:
    TRACE_COUNTS[(name, tuple(shape))] += 1


def bucket_for(n: int, cap: int,
               ladder: Sequence[int] | None = None) -> int:
    """Padded admission length for an ``n``-token prompt: the smallest
    power-of-two (or custom ``ladder``) bucket >= n, clamped to ``cap``
    (the cache's admissible length). Powers of two are
    flash-block-aligned at every size, so TPU prefill never re-pads a
    bucket. THE bucket ladder — shared by the batcher's admission, the
    batch-1 legacy path, the disaggregated prefill gang, and the decode
    gang's KV landing, so two gangs padding independently agree on the
    compiled-program set."""
    if ladder is not None:
        for b in ladder:
            if b >= n:
                return min(b, cap)
        return cap
    b = _MIN_ADMIT_BUCKET
    while b < n:
        b <<= 1
    return min(b, cap)


def _row_samples(logits, keys, temperature, top_k, top_p):
    """One sampling decision per row from PER-ROW keys [B, 2] — argmax
    at ``temperature == 0`` (keys unused; pass None), otherwise the same
    filter stack as :func:`decode.generate` followed by a vmapped
    per-row categorical. The SINGLE implementation behind
    :func:`step_rows`' scan body and the batched speculative admitters'
    seed draws, so the "same filter stack as generate" contract cannot
    drift between the admission seed and the step/round draws."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    f = _filter_logits(logits.astype(jnp.float32), temperature, top_k,
                       top_p)
    return jax.vmap(jax.random.categorical)(keys, f)


def _place_prefill(cache, mini, row, s_p):
    """Land a batch-1 prefill's K/V into cache slot ``row`` (one
    contiguous ``dynamic_update_slice`` per buffer — k/v plus int8
    scales when the cache is quantized) and set the row's frontier to
    the prompt length."""
    placed = {n: jax.lax.dynamic_update_slice(cache[n], mini[n],
                                              (0, row, 0, 0, 0))
              for n in _kv_bufs(mini)}
    return dict(placed, length=cache["length"].at[row].set(s_p))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def admit_row(params, cache, logits, row, prompt, length, cfg):
    """Admit ONE request into cache slot ``row`` with the prompt padded
    to an admission bucket.

    prompt: [1, S_b] right-padded to a :func:`bucket_for` rung;
    ``length`` the TRACED true prompt length — the batch-1 counterpart
    of :func:`admit_rows`, compiling once per bucket instead of once
    per distinct prompt length (the old monolithic-``prefill`` body
    retraced per length, which made the legacy/batch-1 admission path a
    compile sink on mixed-length workloads). The padding-tail K/V land
    beyond the frontier and are unreachable (the bucketed-admission
    argument). Rolling (ring) caches cannot take padded prompts —
    :func:`admit_row_ring` keeps the exact-length program for them.
    Returns (cache, logits) with the row's K/V filled, its frontier at
    ``length``, and its next-step logits seeded from the true last
    position."""
    _count_trace("admit_row", prompt.shape)
    lengths = jnp.reshape(jnp.asarray(length, jnp.int32), (1,))
    lg, mini = prefill_rows(params, prompt, lengths, cfg)
    return (_place_prefill(cache, mini, row, lengths[0]),
            logits.at[row].set(lg[0]))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def admit_row_ring(params, cache, logits, row, prompt, cfg):
    """Admit a request into cache slot ``row`` at its EXACT length —
    the rolling (ring) cache admission: wrapped writes cannot take
    padded prompts (padding at position p would land on ring row
    ``p % capacity``, clobbering real history), so ring admission keeps
    one compiled program per distinct prompt length by construction.

    prompt: [1, S_p]. Returns (cache, logits) with the row's K/V
    filled, its frontier at S_p, and its next-step logits seeded."""
    _count_trace("admit_row_ring", prompt.shape)
    lg1, mini = prefill(params, prompt, cfg, max_len=prompt.shape[1])
    return (_place_prefill(cache, mini, row, prompt.shape[1]),
            logits.at[row].set(lg1[0]))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def admit_rows(params, cache, logits, rows, prompts, lengths, cfg):
    """BUCKETED, BATCHED admission: land K prompts (one length bucket)
    into K freed cache slots in ONE dispatch.

    prompts: [K, S_bucket] right-padded to the bucket; lengths: [K]
    true prompt lengths (TRACED — any mix of real lengths reuses the
    bucket's compiled program); rows: [K] target slots, with unused
    entries set to DISTINCT out-of-range sentinels (>= batch) whose
    scatter updates drop — the batcher always pads K to the full slot
    count, so each bucket compiles exactly one program however many
    slots freed. The prefill runs all K rows
    (:func:`~tony_tpu.models.decode.prefill_rows`), each slot's K/V land
    via one batch-axis scatter per buffer
    (:func:`~tony_tpu.models.decode.place_rows`), and each slot's
    next-step logits seed from its true last prompt position."""
    _count_trace("admit_rows", prompts.shape)
    lg, mini = prefill_rows(params, prompts, lengths, cfg)
    return (place_rows(cache, mini, rows, lengths),
            logits.at[rows].set(lg, mode="drop", unique_indices=True))


def prefix_template(params, prefix, cfg):
    """Prefill a SHARED PREFIX once (a system prompt every request
    continues from); returns the [L, 1, P, KV, hd] K/V template
    :func:`prefix_admit_rows` copies into each admitted slot. prefix:
    [P] ints. Rolling caches are rejected up front: a ring-shaped
    buffer's shape[2] is the capacity, which the template consumers
    would misread as the prefix length and build a corrupt cache."""
    _check_no_ring(cfg, "prefix templates")
    _, mini = prefill(params, jnp.asarray(prefix, jnp.int32)[None], cfg,
                      max_len=len(prefix))
    return _kv_bufs(mini)


class PrefixEntry:
    """One RESIDENT shared prefix in a batcher's prefix store: the
    token sequence (for matching and suffix splitting) plus its
    precomputed K/V ``template`` (:func:`prefix_template` shape —
    ``[L, 1, P, KV, hd]`` per buffer). ``draft_template`` is the
    speculative batcher's draft-model template (computed locally at
    install — template ships carry only the target's K/V)."""

    __slots__ = ("id", "tokens", "template", "draft_template")

    def __init__(self, prefix_id: str, tokens: list, template: dict,
                 draft_template: dict | None = None) -> None:
        self.id = prefix_id
        self.tokens = tokens
        self.template = template
        self.draft_template = draft_template


class _PrefixHit:
    """Engine-side admission payload for a request that matched a
    resident prefix: only ``suffix`` runs a forward; the prefix K/V
    come from ``entry.template``. Routed by ``_admit_batch`` exactly
    like :class:`KVPackage` payloads are — one admission seam, three
    admission kinds."""

    __slots__ = ("entry", "suffix")

    def __init__(self, entry: PrefixEntry, suffix: list) -> None:
        self.entry = entry
        self.suffix = suffix


def validate_template_bufs(proto: dict, tokens, bufs: dict) -> dict:
    """Validate a (possibly shipped) prefix template against a
    reference cache's buffer layout ``proto`` (``_kv_bufs`` of any
    cache built from the serving config): buffer-name set, dtypes,
    layer count, and trailing head dims must match, and the sequence
    extent must equal the prefix length. Raises ``ValueError`` naming
    the mismatch — request-scoped at the install path, exactly like a
    mismatched KV row shipment. Returns the buffers as device arrays."""
    p_len = len(tokens)
    if set(bufs) != set(proto):
        raise ValueError(
            f"template buffers {sorted(bufs)} do not match this cache's "
            f"layout {sorted(proto)} (quantization mismatch?)")
    out = {}
    for n, c in proto.items():
        a = np.asarray(bufs[n])
        if a.dtype != c.dtype:
            raise ValueError(f"template buffer {n!r} dtype {a.dtype} "
                             f"!= cache dtype {c.dtype}")
        if a.ndim != c.ndim or a.shape[0] != c.shape[0]:
            layers = a.shape[0] if a.ndim else 0
            raise ValueError(
                f"template buffer {n!r} carries {layers} layers; this "
                f"model has {c.shape[0]} (layer mismatch between "
                f"producer and installer?)")
        if a.shape[1] != 1 or a.shape[3:] != c.shape[3:]:
            raise ValueError(f"template buffer {n!r} shape "
                             f"{list(a.shape)} does not fit cache "
                             f"{list(c.shape)}")
        if a.shape[2] != p_len:
            raise ValueError(f"template buffer {n!r} holds {a.shape[2]} "
                             f"positions for a {p_len}-token prefix")
        out[n] = jnp.asarray(a)
    return out


def _extend_from_template(model_params, template, suffix, model_cfg):
    """Build a [L, 1, P+S]-row mini cache from a prefix ``template`` and
    run the ``suffix`` through the model against it (a chunked
    :func:`extend_step` — suffix queries attend the full prefix history
    exactly as a monolithic prefill of prefix+suffix would). Returns
    (suffix logits [1, S, V], filled mini cache, total length P+S).
    Shared by the greedy and speculative prefix admitters."""
    p_len = template["k"].shape[2]
    s_len = suffix.shape[1]
    mini = dict(
        {n: jnp.concatenate(
            [x, jnp.zeros(x.shape[:2] + (s_len,) + x.shape[3:],
                          x.dtype)], axis=2)
         for n, x in template.items()},
        length=jnp.asarray(p_len, jnp.int32))
    lg, mini = extend_step(model_params, suffix, mini, p_len, model_cfg)
    return lg, mini, p_len + s_len


def _extend_rows_from_template(model_params, template, suffixes, lengths,
                               model_cfg):
    """Batched-bucketed counterpart of :func:`_extend_from_template`:
    tile the prefix template across K rows and run all K right-padded
    suffixes [K, S_b] through the model against it in one chunked
    :func:`extend_step`. Each row's padding-tail K/V land beyond its
    frontier (unreachable — the bucketed-admission argument). Returns
    (per-row last-REAL-suffix-position logits [K, V], mini cache,
    per-row totals P + lengths)."""
    p_len = template["k"].shape[2]
    k_rows, s_len = suffixes.shape
    mini = dict(
        {n: jnp.concatenate(
            [jnp.broadcast_to(x, (x.shape[0], k_rows) + x.shape[2:]),
             jnp.zeros(x.shape[:1] + (k_rows, s_len) + x.shape[3:],
                       x.dtype)], axis=2)
         for n, x in template.items()},
        length=jnp.asarray(p_len, jnp.int32))
    lg, mini = extend_step(model_params, suffixes, mini, p_len, model_cfg)
    return (lg[jnp.arange(k_rows), lengths - 1], mini,
            p_len + lengths.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def prefix_admit_row(params, cache, logits, row, template, suffix, cfg):
    """Admit a request that CONTINUES a shared prefix: the prefix's K/V
    come from the precomputed ``template`` (one prefill for the whole
    serve, not one per request) and only the request's ``suffix``
    [1, S] runs a forward (:func:`_extend_from_template`). Admission
    compute drops from O(P+S) to O(S) tokens; at a long system prompt
    and short user turns that is the dominant admission cost. Per-length
    program — the batcher's default is the bucketed
    :func:`prefix_admit_rows`."""
    _count_trace("prefix_admit_row", suffix.shape)
    lg, mini, total = _extend_from_template(params, template, suffix, cfg)
    return (_place_prefill(cache, mini, row, total),
            logits.at[row].set(lg[0, -1]))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def prefix_admit_rows(params, cache, logits, rows, template, suffixes,
                      lengths, cfg):
    """Bucketed, batched shared-prefix admission: K suffixes (one length
    bucket, right-padded) continue the precomputed prefix ``template``
    and land in K freed slots in one dispatch — the
    :func:`admit_rows` contract (sentinel-padded ``rows``, traced true
    ``lengths``, one compiled program per bucket) applied to
    O(suffix)-cost prefix admission."""
    _count_trace("prefix_admit_rows", suffixes.shape)
    lg, mini, totals = _extend_rows_from_template(params, template,
                                                  suffixes, lengths, cfg)
    return (place_rows(cache, mini, rows, totals),
            logits.at[rows].set(lg, mode="drop", unique_indices=True))


@functools.partial(jax.jit, static_argnames=("cfg", "n", "temperature",
                                             "top_k", "top_p"),
                   donate_argnames=("cache", "logits"))
def step_rows(params, cache, logits, keys, offsets, n, cfg,
              temperature=0.0, top_k=0, top_p=0.0):
    """``n`` decode steps for every row at its OWN frontier — greedy at
    ``temperature=0`` (default), otherwise sampled per row through the
    same filter stack as :func:`tony_tpu.models.decode.generate`
    (top-k → temperature → nucleus). ``keys``: [B, 2] PER-ROW PRNG keys
    (each row's occupant request's stream); ``offsets``: [B] int32
    per-row counts of draws already taken, so step ``j`` samples row
    ``r`` from ``fold_in(keys[r], offsets[r] + j)`` — a request's
    samples are a function of its own stream position alone, independent
    of batch composition or admission timing (what lets the pipelined
    loop shift admissions without changing outputs). Returns (tokens
    [B, n], cache, logits). Idle rows decode garbage that the host
    discards — uniform batch math keeps this one compiled program
    regardless of which rows are live."""
    _count_trace("step_rows", (cache["k"].shape, n))

    def body(carry, j):
        lg, c = carry
        step_keys = (jax.vmap(jax.random.fold_in)(keys, offsets + j)
                     if temperature > 0.0 else None)
        tok = _row_samples(lg, step_keys, temperature, top_k, top_p)
        lg, c = decode_step(params, tok, c, c["length"], cfg)
        return (lg, c), tok

    (lg, cache), toks = jax.lax.scan(body, (logits, cache),
                                     jnp.arange(n))
    return toks.T, cache, lg


@functools.partial(jax.jit, donate_argnames=("cache",))
def retire_rows(cache, mask):
    """Reset retired rows' frontiers to 0 (mask: [B] bool). Keeps idle
    slots from marching their garbage frontier into the cache end."""
    return dict(cache, length=jnp.where(mask, 0, cache["length"]))


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode (the KV-shipping device programs)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_ship_rows(params, prompts, lengths, cfg):
    """Prefill one admission bucket of prompts FOR SHIPMENT: the exact
    :func:`~tony_tpu.models.decode.prefill_rows` program the colocated
    ``admit_rows`` runs, minus the landing — the prefill gang has no
    persistent cache to land into; each row's mini-cache K/V, last-real
    logits, and frontier ship to a decode gang instead
    (``tony_tpu/serving/disagg.py``). Because both sides run the same
    bucket ladder and the same prefill program, the shipped buffers are
    bit-identical to what colocated admission would have written —
    which is what makes disaggregated serving token-identical. prompts:
    [K, S_bucket] right-padded, lengths: [K] TRACED; one compiled
    program per (K, bucket) — the worker pads K to its wave size."""
    _count_trace("prefill_ship_rows", prompts.shape)
    return prefill_rows(params, prompts, lengths, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_ship_row(params, prompt, cfg):
    """Exact-length batch-1 prefill for shipment — the rolling (ring)
    cache path, whose wrapped writes cannot take padded prompts; the
    FULL capacity-row ring ships (the wrap is positional — landing is a
    whole-slot write). Retraces per distinct prompt length, exactly as
    colocated ring admission does. prompt: [1, S_p]."""
    _count_trace("prefill_ship_row", prompt.shape)
    return prefill(params, prompt, cfg, max_len=prompt.shape[1])


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefix_ship_rows(params, template, suffixes, lengths, cfg):
    """Prefill one admission bucket of SUFFIXES for shipment against a
    resident prefix ``template`` — the disaggregated counterpart of
    :func:`prefix_admit_rows`: only the suffix tokens run a forward
    (:func:`_extend_rows_from_template`), and the resulting
    prefix+suffix mini cache ships to a decode gang exactly like a
    full-prefill row. The prefill tier's prefix fast path: at a hot
    shared prefix, shipped-row prefill compute drops from O(P+S) to
    O(S) tokens per request while the decode gang needs no prefix
    knowledge at all. suffixes: [K, S_bucket] right-padded; lengths:
    [K] TRACED true suffix lengths. Returns (per-row last-real-suffix
    logits [K, V], mini cache [L, K, P+S_bucket, ...])."""
    _count_trace("prefix_ship_rows", suffixes.shape)
    lg, mini, _ = _extend_rows_from_template(params, template, suffixes,
                                             lengths, cfg)
    return lg, mini


@functools.partial(jax.jit, donate_argnames=("cache", "logits"))
def land_kv_rows(cache, logits, rows, mini, lengths, row_logits, keys,
                 row_keys):
    """Land K shipped-and-bucket-padded KV rows into freed cache slots:
    pure scatters — :func:`~tony_tpu.models.decode.place_rows` on the
    buffers plus the logits and rng-key rebinds — with NO model
    forward, which is the entire point of disaggregation: admission on
    the decode gang costs a memcpy, never a prefill that stalls the
    in-flight decode chunk. Takes the ``admit_rows`` sentinel contract
    (``rows`` padded to the slot count with out-of-range entries whose
    scatters drop), so each bucket compiles exactly one program.
    ``row_keys``: [B, 2] shipped per-request rng stream keys landing
    into the batcher's key table ``keys`` — the decode gang samples
    from the SAME per-request stream the prefill gang derived. Returns
    (cache, logits, keys)."""
    _count_trace("land_kv_rows", mini["k"].shape)
    return (place_rows(cache, mini, rows, lengths),
            logits.at[rows].set(row_logits, mode="drop",
                                unique_indices=True),
            keys.at[rows].set(row_keys, mode="drop",
                              unique_indices=True))


class KVPackage:
    """One prefilled request's device state, shipped from a prefill
    gang to a decode gang (disaggregated serving).

    - ``bufs``: host copies of the row's cache buffers (``k``/``v``
      plus int8 ``k_scale``/``v_scale`` when the cache is quantized —
      int8 caches ship QUANTIZED, ~half the bytes of a dequantized
      ship), each ``[L, 1, S, KV, hd]``-shaped, truncated at the true
      prompt length for linear caches (the padding tail past the
      frontier is unreachable garbage — why ship it) or the full
      capacity ring for rolling caches;
    - ``length``: the row's frontier (true prompt length; for rings the
      absolute position, which may exceed the capacity);
    - ``logits``: [V] last-REAL-position logits seeding the first
      decode step;
    - ``rng_key``: [2] uint32 per-request stream key and ``rng_off``
      stream position — the rng stream state that makes SAMPLED
      disaggregated output identical to colocated serving.

    The wire form lives in ``tony_tpu/serving/kvship.py`` (jax-free);
    this class is the decode-side landing record
    (:meth:`ServeEngine.submit_prefilled`)."""

    __slots__ = ("bufs", "length", "logits", "rng_key", "rng_off")

    def __init__(self, bufs: dict, length: int, logits, rng_key,
                 rng_off: int = 0) -> None:
        self.bufs = bufs
        self.length = int(length)
        self.logits = logits
        self.rng_key = rng_key
        self.rng_off = int(rng_off)

    @property
    def width(self) -> int:
        """Shipped cache positions per layer (the buffers' S extent)."""
        return self.bufs["k"].shape[2]


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_admit_row(params, draft_params, t_cache, d_cache, pending, row,
                   prompt, rng, cfg, draft_cfg, temperature=0.0,
                   top_k=0, top_p=0.0):
    """Speculative admission at the EXACT prompt length: prefill BOTH
    models on the prompt into cache slot ``row`` (the draft keeps its
    own per-slot K/V history) and seed the row's ``pending`` token from
    the target's last-position logits — argmax at ``temperature=0``,
    otherwise a sample through the same filter stack the rounds use
    (the seed token is part of the request's sampled stream). Same
    contract as :func:`admit_row` otherwise; the batcher's default is
    the bucketed :func:`spec_admit_rows`."""
    _count_trace("spec_admit_row", prompt.shape)
    lg, mini_t = prefill(params, prompt, cfg, max_len=prompt.shape[1])
    _, mini_d = prefill(draft_params, prompt, draft_cfg,
                        max_len=prompt.shape[1])
    s_p = prompt.shape[1]
    t_cache = _place_prefill(t_cache, mini_t, row, s_p)
    d_cache = _place_prefill(d_cache, mini_d, row, s_p)
    if temperature == 0.0:
        seed_tok = jnp.argmax(lg[0], axis=-1)
    else:
        seed_tok = jax.random.categorical(
            rng, _filter_logits(lg[0].astype(jnp.float32), temperature,
                                top_k, top_p), axis=-1)
    pending = pending.at[row].set(seed_tok.astype(pending.dtype))
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_admit_rows(params, draft_params, t_cache, d_cache, pending,
                    rows, prompts, lengths, keys, cfg, draft_cfg,
                    temperature=0.0, top_k=0, top_p=0.0):
    """Bucketed, batched speculative admission: K prompts (one length
    bucket) prefill BOTH models in one dispatch each and land in K
    freed slots — the :func:`admit_rows` contract applied to the
    speculative batcher's dual caches. ``keys``: [K, 2] per-request
    seed-draw keys (stream position 0 of each request; rounds consume
    positions 1+), used only at ``temperature > 0``."""
    _count_trace("spec_admit_rows", prompts.shape)
    lg, mini_t = prefill_rows(params, prompts, lengths, cfg)
    _, mini_d = prefill_rows(draft_params, prompts, lengths, draft_cfg)
    t_cache = place_rows(t_cache, mini_t, rows, lengths)
    d_cache = place_rows(d_cache, mini_d, rows, lengths)
    seed_tok = _row_samples(lg, keys, temperature, top_k, top_p)
    pending = pending.at[rows].set(seed_tok.astype(pending.dtype),
                                   mode="drop", unique_indices=True)
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_prefix_admit_row(params, draft_params, t_cache, d_cache, pending,
                          row, t_template, d_template, suffix, rng, cfg,
                          draft_cfg, temperature=0.0, top_k=0, top_p=0.0):
    """Shared-prefix admission for the speculative batcher at the EXACT
    suffix length: BOTH models' prefix K/V come from precomputed
    templates and only the suffix runs a forward through each
    (:func:`_extend_from_template`); the pending seed comes from the
    target's last suffix position, argmax or sampled, as in
    :func:`spec_admit_row`. The batcher's default is the bucketed
    :func:`spec_prefix_admit_rows`."""
    _count_trace("spec_prefix_admit_row", suffix.shape)
    lg, mini_t, total = _extend_from_template(params, t_template,
                                              suffix, cfg)
    _, mini_d, _ = _extend_from_template(draft_params, d_template,
                                         suffix, draft_cfg)
    t_cache = _place_prefill(t_cache, mini_t, row, total)
    d_cache = _place_prefill(d_cache, mini_d, row, total)
    if temperature == 0.0:
        seed_tok = jnp.argmax(lg[0, -1], axis=-1)
    else:
        seed_tok = jax.random.categorical(
            rng, _filter_logits(lg[0, -1].astype(jnp.float32),
                                temperature, top_k, top_p), axis=-1)
    pending = pending.at[row].set(seed_tok.astype(pending.dtype))
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_prefix_admit_rows(params, draft_params, t_cache, d_cache,
                           pending, rows, t_template, d_template,
                           suffixes, lengths, keys, cfg, draft_cfg,
                           temperature=0.0, top_k=0, top_p=0.0):
    """Bucketed, batched shared-prefix speculative admission: K suffixes
    (one length bucket) continue both models' templates in one chunked
    extend each (:func:`_extend_rows_from_template`) and land in K freed
    slots, seeding each slot's pending from its true last suffix
    position."""
    _count_trace("spec_prefix_admit_rows", suffixes.shape)
    lg, mini_t, totals = _extend_rows_from_template(params, t_template,
                                                    suffixes, lengths,
                                                    cfg)
    _, mini_d, _ = _extend_rows_from_template(draft_params, d_template,
                                              suffixes, lengths,
                                              draft_cfg)
    t_cache = place_rows(t_cache, mini_t, rows, totals)
    d_cache = place_rows(d_cache, mini_d, rows, totals)
    seed_tok = _row_samples(lg, keys, temperature, top_k, top_p)
    pending = pending.at[rows].set(seed_tok.astype(pending.dtype),
                                   mode="drop", unique_indices=True)
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg", "n", "k",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_step_rows(params, draft_params, t_cache, d_cache, pending, keys,
                   offsets, n, cfg, draft_cfg, k, temperature=0.0,
                   top_k=0, top_p=0.0):
    """``n`` speculative rounds for every row at its OWN frontier — the
    serving analog of :func:`step_rows` built on the same
    propose-and-verify round the speculative decoder uses
    (:func:`tony_tpu.models.decode._propose_and_verify`). Each round every
    row commits its full per-row acceptance ``acc_r + 1`` (serving has no
    generation budget on device — the host truncates at each request's
    budget/eos and discards idle rows' garbage, exactly as in greedy
    continuous batching). Returns ``(packed [n, B, k+2], t_cache,
    d_cache, pending)`` where ``packed[i, r, 0]`` is round i's per-row
    commit count and ``packed[i, r, 1:]`` its k+1-wide token chunk —
    row r's committed tokens for round i are
    ``packed[i, r, 1:1+packed[i, r, 0]]``, in order. ONE output array by
    design: the host syncs on this value every ``n`` rounds, and each
    separately-fetched device array costs its own transport round trip
    (~100 ms on a tunneled chip — returning chunks and counts apart
    measured 242 ms/sync vs ~130 for the greedy batcher's single token
    array, erasing speculation's win).

    ``temperature > 0`` runs SAMPLED rounds instead
    (:func:`decode._propose_and_verify_sampled`, handed PER-ROW round
    keys ``fold_in(keys[r], offsets[r] + i)`` — each slot's draws come
    from its occupant request's own stream, the same
    admission-timing-independence contract as :func:`step_rows`):
    serving commits the full per-row acceptance every round, so each
    slot's next pending is simply the round's residual/bonus sample,
    and each request's committed stream is distributed exactly as
    target-only sampling through the same filter stack."""
    _count_trace("spec_step_rows", (t_cache["k"].shape, n, k))

    def body(carry, i):
        t_cache, d_cache, pending = carry
        pos = t_cache["length"]                                  # [B]
        if temperature == 0.0:
            chunk, argmaxes, acc, t_cache, d_cache = _propose_and_verify(
                params, draft_params, t_cache, d_cache, pending, pos,
                cfg, draft_cfg, k, None, pending.dtype)
            pending = jnp.take_along_axis(argmaxes, acc[:, None],
                                          axis=1)[:, 0]
        else:
            round_keys = jax.vmap(jax.random.fold_in)(keys, offsets + i)
            chunk, extra, acc, t_cache, d_cache = (
                _propose_and_verify_sampled(
                    params, draft_params, t_cache, d_cache, pending,
                    pos, cfg, draft_cfg, k, None, pending.dtype,
                    round_keys, temperature, top_k, top_p))
            pending = extra
        count = acc + 1
        new_len = (pos + count).astype(jnp.int32)
        t_cache = dict(t_cache, length=new_len)
        d_cache = dict(d_cache, length=new_len)
        packed = jnp.concatenate(
            [count[:, None].astype(jnp.int32),
             chunk.astype(jnp.int32)], axis=1)                   # [B, k+2]
        return (t_cache, d_cache, pending), packed

    (t_cache, d_cache, pending), packed = jax.lax.scan(
        body, (t_cache, d_cache, pending), jnp.arange(n))
    return packed, t_cache, d_cache, pending


class ContinuousBatcher:
    """Host-side admission loop over the device programs above.

    ``serve(prompts, max_new_tokens)`` runs every request to completion
    (``max_new_tokens`` or ``eos_id``) through a fixed ``batch`` of cache
    slots, admitting the next queued request the moment a slot frees.
    At the default ``temperature=0`` outputs are the same greedy tokens
    :func:`decode.generate` produces for each request alone
    (test-verified token-identical on CPU); with ``temperature``/
    ``top_k``/``top_p`` set, slots sample through the same filter stack
    as ``generate`` instead, from per-request key streams (see
    ``__init__``).

    The serve loop is PIPELINED by default (``pipeline=True``): chunk
    N+1 is dispatched before chunk N's tokens are fetched, overlapping
    the fetch's transport round trip and the host bookkeeping with
    device compute. ``pipeline=False`` keeps the sequential
    issue→fetch→bookkeep→admit loop; both produce identical outputs in
    every mode (test-enforced) — the sequential loop exists as the
    equivalence baseline and A/B arm, not for production use.

    Admission is BUCKETED and BATCHED by default: prompts pad to
    power-of-two length buckets (compile once per bucket, not once per
    distinct prompt length) and every slot freed in the same chunk lands
    in one :func:`admit_rows` dispatch. Rolling (ring) caches fall back
    to the exact-length :func:`admit_row_ring` path — padded prompts
    cannot take wrapped writes.
    """

    #: first per-request stream position consumed by step_rows sampling
    #: (the speculative batcher's admission seed-draw takes position 0,
    #: so its rounds start at 1)
    _off0 = 0

    def __init__(self, params, cfg: T.TransformerConfig, batch: int,
                 max_len: int, eos_id: int | None = None,
                 chunk: int = 8, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 seed: int = 0,
                 shared_prefix=None, pipeline: bool = True,
                 bucketed_admission: bool = True,
                 admission_buckets: Sequence[int] | None = None) -> None:
        self.params = params
        self.cfg = cfg
        self.batch = batch
        self.max_len = max_len
        self.eos_id = eos_id
        #: shared-prefix caching: when set (a token sequence, e.g. a
        #: system prompt), every request's prompt is interpreted as a
        #: CONTINUATION of it — the prefix prefills once into a K/V
        #: template that admission copies into the slot, and only the
        #: request's own tokens run a forward (prefix_admit_rows).
        #: Outputs are token-identical to serving prefix+prompt in full.
        self.shared_prefix = (None if shared_prefix is None
                              else list(shared_prefix))
        if self.shared_prefix is not None and not self.shared_prefix:
            raise ValueError("shared_prefix must be non-empty when given")
        #: rolling KV cache (cfg.kv_cache_capacity): slots hold a ring
        #: of O(window) rows and requests may run past max_len — the
        #: budget check below relaxes accordingly. Prefix templates are
        #: positional and don't survive ring wraparound.
        self._ring = bool(cfg.kv_cache_capacity)
        if self.shared_prefix is not None:
            # prefix templates are positional; they don't survive ring
            # wraparound
            _check_no_ring(cfg, "shared-prefix caching")
        self._prefix_template = (
            prefix_template(params, self.shared_prefix, cfg)
            if self.shared_prefix else None)
        #: RESIDENT prefix templates (prefix-aware serving): id ->
        #: PrefixEntry. Entries are immutable once published and the
        #: dict is only ever grown, so install threads and the engine's
        #: reader-thread resolution need no lock (GIL-atomic dict ops).
        self._prefix_store: dict = {}
        self._ring_prefix_warned = False
        #: host-side prefill-compute accounting (the prefix fast path's
        #: FLOPs story, folded into the metrics plane by ServeEngine):
        #: true tokens run through a prefill/extend forward at
        #: admission vs prefix positions satisfied by a template COPY
        self.prefill_forward_tokens = 0
        self.prefix_copied_tokens = 0
        self.prefix_admits = 0
        #: sampling controls (greedy by default). Streams are
        #: PER-REQUEST: request q's t-th draw comes from
        #: fold_in(fold_in(PRNGKey(seed), q), t) — a re-served workload
        #: with the same seed reproduces its outputs, and a request's
        #: samples depend only on (seed, its index, its prompt), not on
        #: admission timing or what else shares the batch
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        #: device steps per host round trip — latency/overhead trade:
        #: a finished row idles at most chunk-1 steps before its slot
        #: is reused
        self.chunk = max(1, chunk)
        #: double-buffered dispatch (see class docstring)
        self.pipeline = bool(pipeline)
        #: bucketed+batched admission; ring caches force the per-length
        #: fallback (wrapped writes can't take padded prompts)
        self.bucketed_admission = bool(bucketed_admission) and not self._ring
        if admission_buckets is not None:
            ladder = sorted({int(b) for b in admission_buckets})
            if not ladder or ladder[0] < 1:
                raise ValueError("admission_buckets must be positive "
                                 f"lengths, got {admission_buckets}")
            self.admission_buckets: tuple[int, ...] | None = tuple(ladder)
        else:
            self.admission_buckets = None          # auto: powers of two
        self.cache = init_kv_cache(cfg, batch, max_len)
        # per-row frontiers from the start (decode.py's [B] position path)
        self.cache = dict(self.cache,
                          length=jnp.zeros((batch,), jnp.int32))
        self.logits = jnp.zeros((batch, cfg.vocab_size),
                                cfg.logits_storage_dtype)
        self.steps_executed = 0
        self.rounds_executed = 0
        self.phase_times = PhaseTimes()
        # seams usable standalone (no serve() call required); serve()
        # re-seeds for per-workload reproducibility
        self._reset_streams()

    # --- per-request rng streams ---

    def _reset_streams(self) -> None:
        self._base_key = jax.random.PRNGKey(self.seed)
        idle = jax.random.fold_in(self._base_key, _IDLE_STREAM)
        #: [B, 2] per-row keys: each row carries its occupant REQUEST's
        #: stream key; idle rows draw garbage from a fixed idle stream
        self._row_keys = jnp.tile(idle[None], (self.batch, 1))
        #: per-row stream positions consumed so far (host-side ints)
        self._row_off = [self._off0] * self.batch
        #: stream index -> positions ALREADY consumed elsewhere (a
        #: migrated session re-admitting with its streamed prefix folded
        #: into the prompt): the row's first sample is drawn at this
        #: offset instead of 0, so the continuation is sample-identical
        #: to the placement it left. Consumed at admission.
        self._stream_skip: dict[int, int] = {}

    def _req_key(self, req: int):
        return jax.random.fold_in(self._base_key, req)

    # --- resident prefix templates (prefix-aware serving) ---

    def install_prefix(self, prefix_id: str, tokens,
                       template: dict | None = None) -> bool:
        """Make a shared prefix RESIDENT: admissions whose prompt
        continues ``tokens`` run only their suffix through the model
        (:func:`prefix_admit_rows` against the stored template) —
        token-identical to full prefill, test-pinned. ``template``
        None computes the prefill here (ONE forward for the whole
        serve); a template shipped from a peer replica installs with
        ZERO prefix forwards (:func:`validate_template_bufs` guards
        the layout). Rolling (ring) caches cannot host positional
        templates: the batcher DEGRADES to prefix-blind serving with
        one warning and returns False — never an error (ring replicas
        still serve every request, just without the fast path).
        Raises ``ValueError`` for an unusable request (empty tokens,
        no room for a suffix, legacy ``shared_prefix`` mode, or a
        mismatched shipped template)."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("prefix tokens must be non-empty")
        if self.shared_prefix is not None:
            raise ValueError(
                "legacy shared_prefix mode already interprets every "
                "prompt as a continuation; per-request prefix "
                "templates compose with plain batchers only")
        if self._ring:
            if not self._ring_prefix_warned:
                self._ring_prefix_warned = True
                import logging
                logging.getLogger(__name__).warning(
                    "rolling (ring) caches cannot host prefix "
                    "templates (positional K/V do not survive "
                    "wraparound); serving prefix-blind")
            return False
        if len(tokens) + 2 > self.max_len:
            raise ValueError(
                f"prefix of {len(tokens)} tokens leaves no room for a "
                f"suffix + generation under max_len {self.max_len}")
        if template is None:
            template = prefix_template(self.params, tokens, self.cfg)
            self.prefill_forward_tokens += len(tokens)
        else:
            template = validate_template_bufs(_kv_bufs(self.cache),
                                              tokens, template)
        self._prefix_store[str(prefix_id)] = self._build_entry(
            str(prefix_id), tokens, template)
        return True

    def _build_entry(self, prefix_id: str, tokens: list,
                     template: dict) -> PrefixEntry:
        """Entry construction hook — the speculative subclass adds the
        draft-model template BEFORE the entry is published to the
        store (a half-built entry must never be resolvable)."""
        return PrefixEntry(prefix_id, tokens, template)

    def install_prefix_template(self, meta: dict, bufs: dict) -> str:
        """Land an unpacked SHIPPED template (``kvship.unpack_template``
        output): vocab is checked against this model up front — a
        template from a differently-shaped model is a request-scoped
        ``ValueError`` at the install path, never garbage K/V
        discovered mid-serve. Returns the installed prefix id."""
        if int(meta["vocab"]) != self.cfg.vocab_size:
            raise ValueError(
                f"template vocab {meta['vocab']} != this model's "
                f"{self.cfg.vocab_size} (shipped from a different "
                f"model?)")
        if not self.install_prefix(meta["id"], meta["tokens"],
                                   template=bufs):
            raise ValueError("rolling-cache layout cannot host prefix "
                             "templates (degraded prefix-blind)")
        return str(meta["id"])

    def resident_prefixes(self) -> list:
        """Ids of the installed prefix templates (what the serving
        server advertises via HELLO/STATS for residency-aware
        routing)."""
        return sorted(self._prefix_store)

    def export_prefix_blob(self, prefix_id: str) -> bytes:
        """Pack the resident ``prefix_id`` for publication to a peer
        replica (the warm-ship path); raises ``ValueError`` when not
        resident."""
        from tony_tpu.serving import kvship
        entry = self._prefix_store.get(str(prefix_id))
        if entry is None:
            raise ValueError(f"prefix {prefix_id!r} is not resident")
        return kvship.pack_template(
            entry.id, entry.tokens,
            {n: np.asarray(a) for n, a in entry.template.items()},
            self.cfg.vocab_size)

    def _resolve_prefix(self, prefix_id, prompt) -> PrefixEntry | None:
        """Resolve a submission against the resident store: the named
        entry when ``prefix_id`` is given and the prompt properly
        continues its tokens, else the LONGEST resident match
        (token-boundary, proper prefix). None = serve prefix-blind —
        a miss is never an error (the fast path is an optimization
        with token-identical outputs)."""
        if self._ring:
            if prefix_id is not None and not self._ring_prefix_warned:
                self._ring_prefix_warned = True
                import logging
                logging.getLogger(__name__).warning(
                    "prefix-id admission on a rolling (ring) cache; "
                    "serving prefix-blind")
            return None
        if not self._prefix_store or self.shared_prefix is not None:
            return None
        if prefix_id is not None:
            entry = self._prefix_store.get(prefix_id)
            if (entry is not None and len(entry.tokens) < len(prompt)
                    and prompt[:len(entry.tokens)] == entry.tokens):
                return entry
        # tokenized fallback: longest resident proper prefix (ONE copy
        # of the matching invariant, snapshot-safe vs install threads)
        from tony_tpu.serving.prefix import match_prefix
        entries = list(self._prefix_store.values())
        pid = match_prefix(prompt, ((e.id, e.tokens) for e in entries))
        return next((e for e in entries if e.id == pid), None) \
            if pid is not None else None

    # --- admission (bucketed/batched with a per-length fallback) ---

    def _bucket_for(self, n: int) -> int:
        """Padded admission length for an n-token prompt (suffix, when a
        shared prefix is set): :func:`bucket_for` against the cache's
        admissible length."""
        cap = self.max_len - (len(self.shared_prefix)
                              if self.shared_prefix else 0)
        return bucket_for(n, cap, self.admission_buckets)

    def _marshal_wave(self, pairs):
        """THE home of the sentinel scheme: ([batch] row targets, [batch,
        2] per-request base rng keys) for a set of admitted (row,
        request) pairs, padded to the full slot count — unused entries
        get DISTINCT out-of-range row sentinels (their scatters drop)
        and the idle rng stream. One marshalling shared by prompt
        placement, stream rebinding, and the speculative seed draws, so
        the scheme cannot drift apart between paths; the keys come from
        ONE vmapped fold_in per wave, not one dispatch per row."""
        rows = self.batch + np.arange(self.batch, dtype=np.int32)
        req_ids = [_IDLE_STREAM] * self.batch
        for i, (row, req) in enumerate(pairs):
            rows[i] = row
            req_ids[i] = req
        return (jnp.asarray(rows),
                jax.vmap(self._req_key)(jnp.asarray(req_ids)))

    @staticmethod
    def _seq_of(payload):
        """The token sequence an admission payload runs through the
        model: the whole prompt, or only the suffix of a prefix hit."""
        return payload.suffix if isinstance(payload, _PrefixHit) \
            else payload

    def _pad_prompts_to(self, grp, prompts, bucket):
        """[batch, bucket] right-padded prompt matrix plus [batch] true
        lengths for one bucket group (entries past the group are inert —
        their scatter targets are :meth:`_marshal_wave`'s out-of-range
        sentinels). Prefix hits pad their SUFFIX (the only tokens that
        run a forward)."""
        toks = np.zeros((self.batch, bucket), np.int64)
        lens = np.ones((self.batch,), np.int32)
        for i, (_, req) in enumerate(grp):
            p = self._seq_of(prompts[req])
            toks[i, :len(p)] = p
            lens[i] = len(p)
        return jnp.asarray(toks, jnp.int32), jnp.asarray(lens)

    def _admit_batch(self, pairs, prompts) -> None:
        """Admit (row, request-index) pairs. ``prompts`` maps each
        request index to EITHER a token sequence (the colocated path —
        prefill here) or a :class:`KVPackage` (disaggregated serving —
        the prefill already ran on another gang; landing is a scatter).
        The engine's admission sweep feeds both through one seam, so
        the slot/occupancy machinery cannot diverge between modes."""
        pkg, toks = [], []
        for pair in pairs:
            (pkg if isinstance(prompts[pair[1]], KVPackage)
             else toks).append(pair)
        if pkg:
            self._admit_packages(pkg, prompts)
        if toks:
            self._admit_prompts(toks, prompts)

    def _admit_packages(self, pairs, pkgs) -> None:
        """Land shipped-KV admissions: group by the landing bucket
        (:func:`bucket_for` over each package's shipped width — the
        SAME ladder as prompt admission, so the decode gang compiles
        one :func:`land_kv_rows` program per bucket) and land each
        group in one scatter dispatch. The host stages each group into
        a slot-count-wide, zero-padded buffer set (sentinel rows drop
        on device); zero padding differs from the colocated path's
        prefill-garbage padding only beyond the frontiers, where no
        query can reach — token outputs are identical."""
        if not pairs:
            return
        with self.phase_times.phase("admit"):
            groups: dict[int, list] = {}
            for row, req in pairs:
                w = pkgs[req].width
                s_b = w if self._ring else bucket_for(
                    w, self.max_len, self.admission_buckets)
                groups.setdefault(s_b, []).append((row, req))
            for s_b in sorted(groups):
                self._land_group(groups[s_b], pkgs, s_b)

    def _land_group(self, grp, pkgs, s_b: int) -> None:
        b = self.batch
        proto = pkgs[grp[0][1]].bufs
        rows = b + np.arange(b, dtype=np.int32)
        lens = np.zeros((b,), np.int32)
        lgs = np.zeros((b, self.cfg.vocab_size),
                       pkgs[grp[0][1]].logits.dtype)
        keys = np.zeros((b, 2), np.uint32)
        mini = {n: np.zeros((a.shape[0], b, s_b) + a.shape[3:], a.dtype)
                for n, a in proto.items()}
        for i, (row, req) in enumerate(grp):
            pkg = pkgs[req]
            rows[i] = row
            lens[i] = pkg.length
            lgs[i] = pkg.logits
            keys[i] = pkg.rng_key
            for n, a in pkg.bufs.items():
                mini[n][:, i:i + 1, :a.shape[2]] = a
        self.cache, self.logits, self._row_keys = land_kv_rows(
            self.cache, self.logits, jnp.asarray(rows),
            {n: jnp.asarray(a) for n, a in mini.items()},
            jnp.asarray(lens), jnp.asarray(lgs), self._row_keys,
            jnp.asarray(keys))
        for row, req in grp:
            self._row_off[row] = pkgs[req].rng_off

    def _validate_package(self, pkg, max_new: int) -> None:
        """Reject a shipped-KV admission the decode batcher could not
        land: wrong buffer set/dtypes/trailing dims vs this cache, a
        width past the cache extent, or frontier + budget past
        ``max_len`` (linear caches). Raises ``ValueError`` naming the
        mismatch — request-scoped at the decode server, exactly like
        prompt validation."""
        if not isinstance(pkg, KVPackage):
            raise ValueError(f"expected a KVPackage, got {type(pkg)}")
        if max_new <= 0:
            raise ValueError(f"max_new_tokens must be positive, "
                             f"got {max_new}")
        if pkg.length < 1:
            raise ValueError(f"package frontier must be >= 1, "
                             f"got {pkg.length}")
        lg = np.asarray(pkg.logits)
        if lg.ndim != 1 or lg.shape[0] != self.cfg.vocab_size:
            raise ValueError(
                f"package logits shape {list(lg.shape)} != "
                f"[{self.cfg.vocab_size}] (vocab mismatch between the "
                f"prefill and decode gangs?)")
        want = _kv_bufs(self.cache)
        if set(pkg.bufs) != set(want):
            raise ValueError(
                f"package buffers {sorted(pkg.bufs)} do not match this "
                f"cache's layout {sorted(want)} (quantization mismatch "
                f"between the prefill and decode gangs?)")
        rows = self.cache["k"].shape[2]
        for n, a in pkg.bufs.items():
            c = want[n]
            if a.dtype != c.dtype:
                raise ValueError(f"package buffer {n!r} dtype {a.dtype} "
                                 f"!= cache dtype {c.dtype}")
            if (a.shape[0] != c.shape[0] or a.shape[1] != 1
                    or a.shape[3:] != c.shape[3:]):
                raise ValueError(f"package buffer {n!r} shape {a.shape} "
                                 f"does not fit cache {c.shape}")
            if a.shape[2] > rows:
                raise ValueError(f"package width {a.shape[2]} exceeds "
                                 f"the cache's {rows} rows")
        if self._ring:
            if pkg.width != rows:
                raise ValueError(
                    f"ring landings must ship the full {rows}-row "
                    f"capacity, got {pkg.width}")
        elif pkg.length + max_new > self.max_len:
            raise ValueError(f"frontier {pkg.length} + {max_new} new "
                             f"tokens exceeds max_len {self.max_len}")
        if pkg.length > pkg.width and not self._ring:
            raise ValueError(f"frontier {pkg.length} exceeds shipped "
                             f"width {pkg.width}")

    def _admit_prompts(self, pairs, prompts) -> None:
        """Admit prompt (row, request-index) pairs: group by (resident
        prefix, length bucket) and land each group in ONE device
        dispatch (legacy per-row programs when bucketing is off/ring).
        A prefix-hit group runs only its SUFFIXES through the model
        against the stored template (:func:`prefix_admit_rows`) — the
        admission fast path. Also rebinds each row's rng stream to its
        new occupant — one scatter of the wave's marshalled keys, not
        a dispatch per row."""
        if not pairs:
            return
        with self.phase_times.phase("admit"):
            if self.bucketed_admission:
                groups: dict[tuple, list] = {}
                for row, req in pairs:
                    p = prompts[req]
                    if isinstance(p, _PrefixHit):
                        cap = self.max_len - len(p.entry.tokens)
                        key = (p.entry.id,
                               bucket_for(len(p.suffix), cap,
                                          self.admission_buckets))
                    else:
                        key = (None, self._bucket_for(len(p)))
                    groups.setdefault(key, []).append((row, req))
                for pid, bucket in sorted(groups,
                                          key=lambda k: (k[0] or "",
                                                         k[1])):
                    grp = groups[(pid, bucket)]
                    entry = (prompts[grp[0][1]].entry if pid is not None
                             else None)
                    rows, keys = self._marshal_wave(grp)
                    toks, lens = self._pad_prompts_to(grp, prompts,
                                                      bucket)
                    self._admit_rows(rows, toks, lens, keys, entry=entry)
                    self._rebind_streams(grp, rows, keys)
                    self._count_admission(grp, prompts)
            else:
                for row, req in pairs:
                    self._admit_legacy(row, req, prompts)
                rows, keys = self._marshal_wave(pairs)
                self._rebind_streams(pairs, rows, keys)
                self._count_admission(pairs, prompts)

    def _count_admission(self, pairs, prompts) -> None:
        """Fold one admitted group into the host-side prefill-compute
        accounting (forward tokens vs template-copied prefix
        positions — the FLOPs contrast the prefix fast path exists
        for). Legacy ``shared_prefix`` mode counts its template copies
        too: prompts there are already suffixes."""
        shared_p = len(self.shared_prefix) if self.shared_prefix else 0
        for _, req in pairs:
            p = prompts[req]
            self.prefill_forward_tokens += len(self._seq_of(p))
            if isinstance(p, _PrefixHit):
                self.prefix_copied_tokens += len(p.entry.tokens)
                self.prefix_admits += 1
            elif shared_p:
                self.prefix_copied_tokens += shared_p

    def _rebind_streams(self, pairs, rows, keys) -> None:
        """Rebind the admitted rows' rng streams to their new occupants:
        ONE scatter of the wave's already-marshalled base keys (the
        sentinel rows drop), plus the host-side stream-position
        resets."""
        self._row_keys = self._row_keys.at[rows].set(
            keys, mode="drop", unique_indices=True)
        for row, req in pairs:
            self._row_off[row] = (self._off0
                                  + self._stream_skip.pop(req, 0))

    def _admit_rows(self, rows, toks, lens, keys, entry=None) -> None:
        if entry is not None:
            self.cache, self.logits = prefix_admit_rows(
                self.params, self.cache, self.logits, rows,
                entry.template, toks, lens, self.cfg)
        elif self._prefix_template is not None:
            self.cache, self.logits = prefix_admit_rows(
                self.params, self.cache, self.logits, rows,
                self._prefix_template, toks, lens, self.cfg)
        else:
            self.cache, self.logits = admit_rows(
                self.params, self.cache, self.logits, rows, toks, lens,
                self.cfg)

    def _admit_legacy(self, row, req, prompts) -> None:
        p = prompts[req]
        if isinstance(p, _PrefixHit):
            self.cache, self.logits = prefix_admit_row(
                self.params, self.cache, self.logits, row,
                p.entry.template,
                jnp.asarray(p.suffix, jnp.int32)[None], self.cfg)
        elif self._prefix_template is not None:
            self.cache, self.logits = prefix_admit_row(
                self.params, self.cache, self.logits, row,
                self._prefix_template,
                jnp.asarray(prompts[req], jnp.int32)[None], self.cfg)
        elif self._ring:
            self.cache, self.logits = admit_row_ring(
                self.params, self.cache, self.logits, row,
                jnp.asarray(prompts[req], jnp.int32)[None], self.cfg)
        else:
            # batch-1 admissions pad to the bucket ladder too: one
            # compiled program per bucket, not one per distinct length
            n = len(prompts[req])
            padded = np.zeros((1, self._bucket_for(n)), np.int64)
            padded[0, :n] = prompts[req]
            self.cache, self.logits = admit_row(
                self.params, self.cache, self.logits, row,
                jnp.asarray(padded, jnp.int32),
                jnp.asarray(n, jnp.int32), self.cfg)

    # --- dispatch/fetch seams (overridden by the speculative batcher) ---

    #: most tokens one chunk can commit per row (the greedy step loop
    #: commits exactly one per step; the speculative batcher overrides)
    def _chunk_tokens_max(self) -> int:
        return self.chunk

    def _issue(self):
        """Issue one device chunk WITHOUT fetching it (async dispatch —
        returns the not-yet-materialized device tokens). The pipelined
        loop issues chunk N+1 here before fetching chunk N."""
        with self.phase_times.phase("dispatch"):
            offs = jnp.asarray(self._row_off, jnp.int32)
            toks, self.cache, self.logits = step_rows(
                self.params, self.cache, self.logits, self._row_keys,
                offs, self.chunk, self.cfg, self.temperature, self.top_k,
                self.top_p)
        self.steps_executed += self.chunk
        for r in range(self.batch):
            self._row_off[r] += self.chunk
        return toks

    def _fetch(self, handle):
        """Block on a previously issued chunk: remaining device compute
        plus the transport round trip — the cost the pipelined loop
        overlaps with the NEXT chunk. Returns per-row sequences of newly
        generated tokens."""
        with self.phase_times.phase("fetch"):
            return np.asarray(handle)

    def _retire(self, mask) -> None:
        self.cache = retire_rows(self.cache, jnp.asarray(mask))

    def _validate_request(self, prompt, max_new: int) -> None:
        """Reject a request the batcher could not serve: empty prompt,
        non-positive budget, or (linear caches — rolling caches have no
        length ceiling) prompt + budget past ``max_len``. Raises
        ``ValueError`` naming the offending dimension."""
        p_len = len(self.shared_prefix) if self.shared_prefix else 0
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if max_new <= 0:
            raise ValueError(f"max_new_tokens must be positive, "
                             f"got {max_new}")
        if not self._ring and p_len + len(prompt) + max_new > self.max_len:
            raise ValueError(
                (f"shared prefix {p_len} + " if p_len else "")
                + f"prompt {len(prompt)} + {max_new} new tokens exceeds "
                  f"max_len {self.max_len}")

    def _validate_prefix_hit(self, hit: "_PrefixHit",
                             max_new: int) -> None:
        """Validate a prefix-hit admission (resident template + suffix)
        against the cache geometry — the fast-path counterpart of
        :meth:`_validate_request` (suffix non-emptiness is guaranteed
        by the proper-prefix match)."""
        total = len(hit.entry.tokens) + len(hit.suffix)
        if max_new <= 0:
            raise ValueError(f"max_new_tokens must be positive, "
                             f"got {max_new}")
        if total + max_new > self.max_len:
            raise ValueError(
                f"prefix {len(hit.entry.tokens)} + suffix "
                f"{len(hit.suffix)} + {max_new} new tokens exceeds "
                f"max_len {self.max_len}")

    def serve(self, prompts: Sequence, max_new_tokens):
        """Run all ``prompts`` (each a [S_p] int sequence) to completion;
        returns a list of per-request generated-token lists, order-
        matching the input. ``max_new_tokens``: one int for all requests
        or a per-request sequence (mixed-length serving is the whole
        point). ``self.steps_executed`` counts device decode steps run —
        the utilization denominator (each step advances every slot);
        ``self.phase_times`` holds per-phase host wall clock
        (dispatch/fetch/admit/retire) for the call.

        A thin CLOSED-BATCH wrapper over :class:`ServeEngine`: submit
        every request up front, drain, run the engine on the calling
        thread, and collect each request's streamed deltas into its
        output list. Token-identical (and ``steps_executed``-identical)
        to the pre-engine fixed-queue loop in every mode — the engine's
        live admission queue degenerates to the old FIFO when everything
        is submitted before the loop starts (test-enforced).

        The call also observes into the default metrics registry
        (``runtime/metrics.py``): admitted/retired request counters,
        useful-token counter, queue-depth gauge, TTFT/inter-token
        histograms, and — on return — the PhaseTimes accumulation as
        per-phase ``tony_serve_phase_*`` counters. Swap in a
        :class:`~tony_tpu.runtime.metrics.NullRegistry` to serve
        uninstrumented (the bench contrast arm)."""
        if isinstance(max_new_tokens, int):
            budget = [max_new_tokens] * len(prompts)
        else:
            budget = list(max_new_tokens)
            if len(budget) != len(prompts):
                raise ValueError("per-request max_new_tokens length "
                                 "must match prompts")
        outputs: list[list[int]] = [[] for _ in prompts]
        engine = ServeEngine(
            self, on_delta=lambda rid, toks: outputs[rid].extend(toks),
            on_retired=lambda rid, reason, n, final:
                outputs[rid].extend(final))
        # every submit happens BEFORE run(), so a bad request anywhere
        # in the list still fails the whole call up front — nothing is
        # admitted, no completed output is discarded mid-serve
        for req, (p, b) in enumerate(zip(prompts, budget)):
            try:
                engine.submit(req, p, b)
            except ValueError as e:
                # unwind the earlier submits (clears the wait queue and
                # zeroes the queue-depth gauge — no phantom depth from
                # an engine that never runs)
                engine._abort_outstanding("stopped")
                raise ValueError(f"request {req}: {e}") from None
        engine.drain()
        engine.run()
        return outputs


class SpeculativeContinuousBatcher(ContinuousBatcher):
    """Continuous batching with speculative decoding per slot — the two
    serving features composed. A cheap draft model proposes
    ``num_speculative`` tokens per round for EVERY slot at its own
    frontier; the target verifies each slot's chunk in one wide
    ``extend_step``; each slot commits its own acceptance
    (:func:`spec_step_rows`, built on the same propose-and-verify round
    as ``decode.speculative_generate_device``). Slot reuse works exactly
    as in the greedy batcher: admission prefills BOTH caches (bucketed
    and batched by default — :func:`spec_admit_rows`), retirement frees
    the slot, and idle rows decode garbage the host discards. The
    pipelined loop and its catch-up semantics are inherited unchanged —
    one packed array per sync keeps the double-buffered fetch a single
    transport round trip.

    Outputs are token-identical to the greedy batcher (and therefore to
    per-request ``decode.generate``) wherever chunked and single-step
    logits agree — bit-exact on CPU, matmul-noise near-ties on TPU, the
    same caveat as all speculative paths. Wall-clock wins need a draft
    that predicts the target AND enough per-request work to amortize the
    round structure; ``rounds_executed`` counts speculative rounds run
    (tokens-per-round = the acceptance-driven efficiency).

    ``chunk`` here counts speculative ROUNDS per host sync, not tokens:
    one round commits between 1 and k+1 tokens per live slot, so a
    finished request idles at most ``chunk-1`` rounds before its slot is
    reused.

    Accounting: ``steps_executed`` counts TARGET-MODEL positions
    verified per slot (``rounds * (k+1)``) so the base class's
    step-utilization reading remains meaningful — useful tokens /
    (steps_executed * slots) is the fraction of verified positions that
    became committed tokens (acceptance efficiency × occupancy).
    ``rounds_executed`` counts speculative rounds.

    ``temperature > 0`` switches every slot's rounds to SPECULATIVE
    SAMPLING (``decode._propose_and_verify_sampled``): each request's
    committed stream is distributed exactly as target-only sampling
    through the same temperature/top-k/top-p stack, for any draft —
    greedy rounds remain the token-exact default. Draws come from
    per-request streams (the admission seed takes stream position 0,
    round ``r`` takes position ``1 + r``), so a request's sampled output
    is independent of admission timing — pipelined == sequential here
    too."""

    #: stream position 0 is the admission seed draw; rounds start at 1
    _off0 = 1

    def __init__(self, params, cfg: T.TransformerConfig,
                 draft_params, draft_cfg: T.TransformerConfig,
                 batch: int, max_len: int,
                 num_speculative: int = 4, eos_id: int | None = None,
                 chunk: int = 4, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 seed: int = 0, shared_prefix=None,
                 pipeline: bool = True, bucketed_admission: bool = True,
                 admission_buckets: Sequence[int] | None = None) -> None:
        super().__init__(params, cfg, batch, max_len, eos_id=eos_id,
                         chunk=chunk, temperature=temperature,
                         top_k=top_k, top_p=top_p, seed=seed,
                         shared_prefix=shared_prefix, pipeline=pipeline,
                         bucketed_admission=bucketed_admission,
                         admission_buckets=admission_buckets)
        if num_speculative < 1:
            raise ValueError("num_speculative must be >= 1")
        _check_draft_vocab(cfg, draft_cfg)
        _check_no_ring(cfg, "speculative serving (chunked verify)")
        _check_no_ring(draft_cfg, "speculative serving (draft)")
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        # the draft needs its own prefix template (its K/V dims differ)
        self._draft_prefix_template = (
            prefix_template(draft_params, self.shared_prefix, draft_cfg)
            if self.shared_prefix else None)
        self.k = num_speculative
        self.d_cache = init_kv_cache(draft_cfg, batch, max_len)
        self.d_cache = dict(self.d_cache,
                            length=jnp.zeros((batch,), jnp.int32))
        # pending token per slot (the committed token whose K/V is not
        # yet written) replaces the greedy batcher's per-slot logits
        self.pending = jnp.zeros((batch,), jnp.int32)

    def _chunk_tokens_max(self) -> int:
        # one sync = chunk rounds x up to k+1 commits per row
        return self.chunk * (self.k + 1)

    def _build_entry(self, prefix_id: str, tokens: list,
                     template: dict) -> PrefixEntry:
        # the draft keeps its own per-slot K/V history, so a resident
        # prefix needs a DRAFT template too; template ships carry only
        # the target's buffers, so it is computed locally (the draft is
        # the cheap model — one small prefill per install)
        return PrefixEntry(
            prefix_id, tokens, template,
            draft_template=prefix_template(self.draft_params, tokens,
                                           self.draft_cfg))

    def _admit_rows(self, rows, toks, lens, keys, entry=None) -> None:
        # the seed draw takes stream position 0 of each admitted
        # request's base key — one vmapped fold over the wave's
        # ALREADY-marshalled keys (shared with the rebind scatter), not
        # a second per-request derivation
        seed_keys = jax.vmap(lambda kk: jax.random.fold_in(kk, 0))(keys)
        if entry is not None:
            self.cache, self.d_cache, self.pending = (
                spec_prefix_admit_rows(
                    self.params, self.draft_params, self.cache,
                    self.d_cache, self.pending, rows, entry.template,
                    entry.draft_template, toks, lens, seed_keys,
                    self.cfg, self.draft_cfg, self.temperature,
                    self.top_k, self.top_p))
        elif self._prefix_template is not None:
            self.cache, self.d_cache, self.pending = (
                spec_prefix_admit_rows(
                    self.params, self.draft_params, self.cache,
                    self.d_cache, self.pending, rows,
                    self._prefix_template, self._draft_prefix_template,
                    toks, lens, seed_keys, self.cfg, self.draft_cfg,
                    self.temperature, self.top_k, self.top_p))
        else:
            self.cache, self.d_cache, self.pending = spec_admit_rows(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, rows, toks, lens, seed_keys, self.cfg,
                self.draft_cfg, self.temperature, self.top_k, self.top_p)

    def _admit_packages(self, pairs, pkgs) -> None:
        # EXPLICIT disaggregation exclusion (not an oversight): a
        # speculative slot needs the DRAFT model's per-slot K/V history
        # too, which the KV shipment does not carry. Serve speculative
        # colocated, or disaggregate the greedy/sampled batcher.
        raise NotImplementedError(
            "speculative serving is not supported in disaggregated "
            "mode (the shipment carries no draft-model cache)")

    def _admit_legacy(self, row, req, prompts) -> None:
        p = prompts[req]
        sub = jax.random.fold_in(self._req_key(req), 0)
        if isinstance(p, _PrefixHit):
            self.cache, self.d_cache, self.pending = spec_prefix_admit_row(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, row, p.entry.template,
                p.entry.draft_template,
                jnp.asarray(p.suffix, jnp.int32)[None], sub, self.cfg,
                self.draft_cfg, self.temperature, self.top_k, self.top_p)
            return
        tokens = jnp.asarray(p, jnp.int32)[None]
        if self._prefix_template is not None:
            self.cache, self.d_cache, self.pending = spec_prefix_admit_row(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, row, self._prefix_template,
                self._draft_prefix_template, tokens, sub, self.cfg,
                self.draft_cfg, self.temperature, self.top_k, self.top_p)
        else:
            self.cache, self.d_cache, self.pending = spec_admit_row(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, row, tokens, sub, self.cfg, self.draft_cfg,
                self.temperature, self.top_k, self.top_p)

    def _issue(self):
        with self.phase_times.phase("dispatch"):
            offs = jnp.asarray(self._row_off, jnp.int32)
            packed, self.cache, self.d_cache, self.pending = (
                spec_step_rows(self.params, self.draft_params, self.cache,
                               self.d_cache, self.pending, self._row_keys,
                               offs, self.chunk, self.cfg, self.draft_cfg,
                               self.k, self.temperature, self.top_k,
                               self.top_p))
        self.rounds_executed += self.chunk
        self.steps_executed += self.chunk * (self.k + 1)
        for r in range(self.batch):
            self._row_off[r] += self.chunk
        return packed

    def _fetch(self, handle):
        # ONE host fetch per sync (see spec_step_rows: separate fetches
        # pay separate transport round trips)
        with self.phase_times.phase("fetch"):
            packed = np.asarray(handle)                # [n, B, k+2]
        return [
            [int(t) for i in range(packed.shape[0])
             for t in packed[i, row, 1:1 + packed[i, row, 0]]]
            for row in range(self.batch)]

    def _retire(self, mask) -> None:
        m = jnp.asarray(mask)
        self.cache = retire_rows(self.cache, m)
        self.d_cache = retire_rows(self.d_cache, m)


#: QoS tiers in admission-priority order (mirrors
#: ``serving.protocol.QOS_CLASSES`` — the wire-side authority; kept as
#: a local literal so the models layer stays importable without the
#: serving plane).
QOS_CLASSES = ("interactive", "standard", "batch")


class EngineBusy(RuntimeError):
    """Explicit overload shed: the engine refused to QUEUE a
    standard/batch submission past its bounded queue depth. A statement
    about load, not about the request — the identical submit is
    expected to succeed once pressure clears; ``retry_after_ms`` is the
    server's backoff hint (the BUSY frame's payload)."""

    def __init__(self, retry_after_ms: int) -> None:
        super().__init__(
            f"engine overloaded; retry after {retry_after_ms} ms")
        self.retry_after_ms = int(retry_after_ms)


class _EngineRequest:
    """Engine-side record of one live request. ``stream`` is the
    request's rng-stream index (assigned in submission order, so the
    closed-batch wrapper reproduces the fixed-queue loop's per-request
    streams exactly); ``budget`` counts REMAINING tokens."""

    __slots__ = ("rid", "prompt", "budget", "stream", "rng_skip",
                 "emitted", "done", "reason", "t_submit", "t_last",
                 "span", "queued_span", "first_span", "cls", "history",
                 "requeued")

    def __init__(self, rid, prompt, budget: int, stream: int,
                 t_submit: float, rng_skip: int = 0,
                 cls: str = "standard") -> None:
        self.rid = rid
        self.prompt = prompt
        self.budget = budget
        self.stream = stream
        #: stream positions already consumed by a previous placement of
        #: this request (router-coordinated migration) — the batcher
        #: draws this row's first sample at this offset
        self.rng_skip = rng_skip
        self.emitted = 0
        self.done = False
        self.reason: str | None = None
        self.t_submit = t_submit
        self.t_last = t_submit
        #: QoS tier (one of :data:`QOS_CLASSES`)
        self.cls = cls
        #: emitted token VALUES, tracked only for evictable rows (batch
        #: class, foldable payload) — a preemption folds prompt+history
        #: into the reincarnation's prompt so the PR 12 rng-offset
        #: re-prefill resumes the stream token-identically
        self.history: list | None = None
        #: True on the tombstone left behind by a preemption whose
        #: stream was re-queued IN-ENGINE under the same rid: its
        #: retirement must not be emitted (the rid is still live) and
        #: its counters must not move
        self.requeued = False
        # TTFT-decomposition spans (tracing.NOOP_SPAN when unsampled):
        # engine.request (submit→retire) with children engine.queued
        # (submit→slot admit) and engine.first_token (admit→first
        # consumed delta)
        self.span = tracing.NOOP_SPAN
        self.queued_span = tracing.NOOP_SPAN
        self.first_span = tracing.NOOP_SPAN


class ServeEngine:
    """Open-loop serving engine: the issue/fetch/consume/settle loop of
    a :class:`ContinuousBatcher` (or its speculative subclass) run
    against a LIVE admission queue.

    - :meth:`submit`/:meth:`cancel` are thread-safe and callable while
      :meth:`run` is live — a streaming server's per-connection reader
      threads feed admissions straight into the loop.
    - ``on_delta(rid, tokens)`` fires the moment a chunk's tokens for a
      request are consumed (NOT on retirement) — the emission point
      time-to-first-token and inter-token latency are measured at
      (``tony_serve_ttft_seconds`` / ``tony_serve_intertoken_seconds``
      land in the registry here).
    - ``on_retired(rid, reason, n_tokens, final_tokens)`` fires exactly
      once per request, reason one of ``"eos"``/``"budget"``/
      ``"cancelled"``/``"stopped"``/``"preempted"`` (the last only for
      a KV-adopted row evicted for an interactive admission — the
      router re-places it; a colocated batch row preempts WITHOUT
      retiring, reincarnated in-engine under the same rid). A request
      retiring on eos/budget
      delivers its LAST delta here (``final_tokens``) rather than
      through ``on_delta``, so a transport can write the final tokens
      and the retirement atomically — a peer can then never observe
      the one without the other.
    - :meth:`drain` is the graceful shutdown: no further submits, run()
      returns once every accepted request has retired. :meth:`stop`
      aborts — outstanding requests retire as ``"stopped"``.

    QoS (SLO-tiered serving): every submission carries a class —
    ``interactive`` / ``standard`` / ``batch`` — with one admission
    queue per class (interactive jumps, batch waits), per-class
    decode-slot floors (``class_floors``, the ``tony.serve.slots.*``
    keys), interactive-over-batch row preemption (evict-to-queue with
    a token-identical resume), and an explicit overload shed
    (:class:`EngineBusy` past ``max_queue_depth``, the BUSY frame).
    Classless callers land as ``standard`` and see the exact pre-QoS
    admission order.

    Callback threading: deltas and eos/budget retirements fire on the
    thread driving :meth:`run`; a ``"cancelled"`` retirement fires on
    the CANCELLING thread (so a streaming client sees its CANCEL
    acknowledged without waiting out the in-flight chunk). Consumers
    that serialize writes (the frame server) take a per-connection send
    lock. A delta already being consumed when its request is cancelled
    may still be emitted after the retirement — cancellation discards,
    so late tokens for a retired rid are dropped by the caller.

    Cancel semantics reuse the pipelined loop's proven catch-up path: a
    cancelled occupant is only MARKED done; the slot frees when the next
    consumed chunk crosses it (its tokens are discarded exactly like
    idle-slot garbage, and the freed slot readmits from the live
    queue). CANCEL racing retirement is idempotent — unknown or
    already-done rids are no-ops.

    One engine run per batcher at a time; creating the engine resets the
    batcher's per-serve state (``steps_executed``, phase times, rng
    streams), exactly as ``serve()`` did before the refactor.
    """

    def __init__(self, batcher: ContinuousBatcher, on_delta=None,
                 on_retired=None, registry=None,
                 class_floors: dict | None = None,
                 max_queue_depth: int = 128,
                 busy_retry_ms: int = 250,
                 latency_buckets=None) -> None:
        # guard BEFORE the state reset below: constructing a second
        # engine over a live one would silently rebind the running
        # engine's rng streams and counters mid-flight
        if getattr(batcher, "_engine_running", False):
            raise RuntimeError("batcher is already driven by a live "
                               "engine")
        self.b = batcher
        self.on_delta = on_delta
        self.on_retired = on_retired
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        #: rids waiting for a slot, FIFO per QoS class (deque: O(1)
        #: admission pops). Admission drains interactive first, then
        #: standard, then batch; a preemption reincarnation goes to the
        #: FRONT of its class queue (it already waited its turn).
        self._waitq: dict[str, collections.deque] = {
            c: collections.deque() for c in QOS_CLASSES}
        #: per-class decode-slot floors (soft reservations, clamped to
        #: the batcher's slot count; the ``tony.serve.slots.<class>``
        #: keys). A class past its floor takes a free slot only if the
        #: REMAINING free slots still cover every other class's unmet
        #: floor.
        self._floors = {c: 0 for c in QOS_CLASSES}
        for c, n in (class_floors or {}).items():
            if c not in self._floors:
                raise ValueError(f"unknown QoS class in floors: {c!r}")
            self._floors[c] = max(0, min(int(n), batcher.batch))
        if sum(self._floors.values()) > batcher.batch:
            raise ValueError(
                f"class floors {self._floors} exceed {batcher.batch} "
                f"decode slots")
        #: total queued admissions past which standard/batch submits
        #: are shed with :class:`EngineBusy` (0 = unbounded, the
        #: pre-QoS queue); interactive admissions always queue
        self._max_queue_depth = max(0, int(max_queue_depth))
        self._busy_retry_ms = max(0, int(busy_retry_ms))
        self._reqs: dict = {}                    # rid -> _EngineRequest
        self._occupant: list[_EngineRequest | None] = \
            [None] * batcher.batch
        self._draining = False
        self._stopped = False
        self._next_stream = 0
        # one engine == one serve lifetime: the closed-batch serve()'s
        # per-call reset moved here
        batcher.steps_executed = 0
        batcher.rounds_executed = 0
        batcher.phase_times = PhaseTimes()
        batcher._reset_streams()
        # Registry instrumentation: a handful of locked increments per
        # host SYNC (token counts batch into one inc per consume; the
        # TTFT/ITL histograms observe once per DELTA, <= slots per
        # sync), pinned < 1% of chunk wall by bench.py's overhead arm.
        reg = registry or metrics_mod.get_default()
        self._reg = reg
        buckets = (metrics_mod.TIME_BUCKETS_S if latency_buckets is None
                   else tuple(latency_buckets))
        self._admitted_c = reg.counter(
            "tony_serve_requests_admitted_total",
            help="requests admitted into cache slots")
        self._retired_c = reg.counter(
            "tony_serve_requests_retired_total",
            help="requests retired (eos or budget)")
        self._cancelled_c = reg.counter(
            "tony_serve_requests_cancelled_total",
            help="requests cancelled before completion")
        self._tokens_c = reg.counter("tony_serve_tokens_total",
                                     help="useful generated tokens")
        self._qdepth_g = reg.gauge("tony_serve_queue_depth",
                                   help="requests waiting for a free slot")
        self._ttft_h = reg.histogram(
            "tony_serve_ttft_seconds",
            help="submit -> first consumed token delta (time to first "
                 "token, engine-side)", buckets=buckets)
        self._itl_h = reg.histogram(
            "tony_serve_intertoken_seconds",
            help="mean per-token gap of each consumed delta after a "
                 "request's first (inter-token latency, engine-side)",
            buckets=buckets)
        # per-class series alongside the aggregates: the same names
        # with a ``class`` label, so classless dashboards keep working
        # while SLO alerting reads only its tier
        self._qdepth_by_cls = {
            c: reg.gauge("tony_serve_queue_depth",
                         help="requests waiting for a free slot",
                         **{"class": c}) for c in QOS_CLASSES}
        self._ttft_by_cls = {
            c: reg.histogram("tony_serve_ttft_seconds",
                             buckets=buckets, **{"class": c})
            for c in QOS_CLASSES}
        self._itl_by_cls = {
            c: reg.histogram("tony_serve_intertoken_seconds",
                             buckets=buckets, **{"class": c})
            for c in QOS_CLASSES}
        self._preempt_c = reg.counter(
            "tony_serve_preemptions_total",
            help="batch rows evicted-to-queue for an interactive "
                 "admission (the stream resumes token-identically)")
        self._shed_c = {
            c: reg.counter(
                "tony_serve_shed_total",
                help="submissions refused with BUSY past the bounded "
                     "queue depth", **{"class": c})
            for c in QOS_CLASSES}
        self._prefill_tok_c = reg.counter(
            "tony_serve_prefill_tokens_total",
            help="true prompt/suffix tokens run through a prefill or "
                 "extend forward at admission (the prefill-FLOPs "
                 "proxy the prefix fast path shrinks)")
        self._prefix_tok_c = reg.counter(
            "tony_serve_prefix_tokens_total",
            help="prefix positions satisfied by a resident-template "
                 "COPY instead of a forward (prefix-aware serving)")
        self._prefix_admits_c = reg.counter(
            "tony_serve_prefix_admits_total",
            help="admissions that went through a resident prefix "
                 "template (only suffix tokens ran the model)")
        self._qdepth_g.set(0)
        for g in self._qdepth_by_cls.values():
            g.set(0)

    # --- thread-safe control surface ---

    def submit(self, rid, prompt, max_new_tokens: int,
               trace_ctx: dict | None = None,
               prefix_id: str | None = None,
               rng: tuple | None = None,
               request_class: str = "standard") -> None:
        """Enqueue a request under caller-chosen id ``rid`` (any
        hashable; must not collide with a LIVE request's). Raises
        ``ValueError`` for un-servable requests (validated up front, so
        a bad request never strands engine state) and ``RuntimeError``
        once draining/stopped.

        ``prefix_id`` optionally names a resident shared-prefix
        template the prompt continues (the ADMIT frame's ``prefix``
        field); the engine also auto-matches the prompt against its
        resident store. A hit admits only the SUFFIX through the model
        — token-identical to full prefill, test-pinned; a miss (or a
        replica degraded prefix-blind) serves normally, never errors.

        ``trace_ctx`` is the submitter's span context (``{"tid", "sid"}``
        off the ADMIT frame): the request's engine-side spans — the TTFT
        decomposition — join that trace; without one the engine
        head-samples a fresh trace per ``tony.trace.sample-rate``.

        ``rng`` optionally pins the request's rng stream:
        ``(stream, off)`` uses stream index ``stream`` (instead of the
        engine's submission counter) with the first ``off`` positions
        treated as already consumed — how a router-coordinated
        migration continues a SAMPLED stream token-identically on a new
        replica (the ADMIT frame's ``rng`` field; see
        ``protocol.parse_rng``).

        ``request_class`` is the QoS tier (:data:`QOS_CLASSES`):
        ``interactive`` jumps the admission queue and may preempt a
        batch row, ``batch`` yields and absorbs preemption; a
        standard/batch submit past the bounded queue depth raises
        :class:`EngineBusy` (the BUSY shed) instead of queueing."""
        prompt = [int(t) for t in prompt]
        max_new_tokens = int(max_new_tokens)
        if request_class not in QOS_CLASSES:
            raise ValueError(
                f"unknown request class {request_class!r} (expected "
                f"one of {', '.join(QOS_CLASSES)})")
        entry = self.b._resolve_prefix(prefix_id, prompt)
        if entry is None:
            self.b._validate_request(prompt, max_new_tokens)
            self._enqueue(rid, prompt, max_new_tokens, trace_ctx,
                          rng=rng, cls=request_class,
                          prompt_tokens=len(prompt))
        else:
            hit = _PrefixHit(entry, prompt[len(entry.tokens):])
            self.b._validate_prefix_hit(hit, max_new_tokens)
            self._enqueue(rid, hit, max_new_tokens, trace_ctx,
                          rng=rng, cls=request_class,
                          prompt_tokens=len(prompt),
                          prefix=entry.id)

    def submit_prefilled(self, rid, package: KVPackage,
                         max_new_tokens: int,
                         trace_ctx: dict | None = None,
                         request_class: str = "standard") -> None:
        """Enqueue an ALREADY-PREFILLED request (disaggregated serving):
        ``package`` is the :class:`KVPackage` a prefill gang shipped —
        admission lands it with :func:`land_kv_rows` (a scatter, no
        model forward), so adopting a row never preempts the in-flight
        decode chunk. Same contract as :meth:`submit` otherwise:
        caller-chosen ``rid``, up-front validation
        (:meth:`ContinuousBatcher._validate_package`),
        ``RuntimeError`` once draining. The shipped rng stream state
        rides the package, so sampled output matches the colocated
        engine serving the same request index.

        ``request_class`` applies the decode tier's per-class floors
        and queue order to an adopted package, but a package is NEVER
        shed with BUSY: the prefill work is already paid — the prefill
        tier sheds before prefilling (see ``serving/disagg.py``)."""
        max_new_tokens = int(max_new_tokens)
        if request_class not in QOS_CLASSES:
            raise ValueError(
                f"unknown request class {request_class!r} (expected "
                f"one of {', '.join(QOS_CLASSES)})")
        self.b._validate_package(package, max_new_tokens)
        self._enqueue(rid, package, max_new_tokens, trace_ctx,
                      cls=request_class, prompt_tokens=package.length,
                      prefilled=True)

    def _wait_total_locked(self) -> int:
        return sum(len(q) for q in self._waitq.values())

    def _set_qdepth_locked(self) -> None:
        self._qdepth_g.set(self._wait_total_locked())
        for c, q in self._waitq.items():
            self._qdepth_by_cls[c].set(len(q))

    def _enqueue(self, rid, payload, max_new_tokens: int,
                 trace_ctx: dict | None, *, prompt_tokens: int,
                 rng: tuple | None = None, cls: str = "standard",
                 prefilled: bool = False, **span_attrs) -> None:
        """The shared admission-queue push behind :meth:`submit` and
        :meth:`submit_prefilled`: drain/duplicate checks, the bounded-
        queue BUSY shed, request registration, the engine-side span
        pair, and the wakeup — ONE place, so the two admission paths
        cannot drift."""
        shed = False
        with self._work:
            if self._draining or self._stopped:
                raise RuntimeError(
                    "engine is draining; not accepting new requests")
            if rid in self._reqs:
                raise ValueError(f"request id {rid!r} is already active")
            # the explicit overload shed: a standard/batch submit past
            # the bounded queue depth is refused NOW with a retry hint
            # instead of growing the queue into a latency grave.
            # Interactive always queues (its overload story is the
            # floor + preemption); an already-prefilled package is
            # exempt too — its work is paid, the prefill tier shed
            # before prefilling.
            if (self._max_queue_depth and cls != "interactive"
                    and not prefilled
                    and self._wait_total_locked() >= self._max_queue_depth):
                shed = True
            else:
                stream = self._next_stream if rng is None else int(rng[0])
                skip = 0 if rng is None else int(rng[1])
                req = _EngineRequest(rid, payload, max_new_tokens, stream,
                                     time.perf_counter(), rng_skip=skip,
                                     cls=cls)
                if cls == "batch" and isinstance(payload,
                                                 (list, _PrefixHit)):
                    # evictable: track emitted values so a preemption
                    # can fold them into the reincarnation's prompt
                    req.history = []
                tr = tracing.get_tracer()
                req.span = tr.start_span("engine.request", ctx=trace_ctx,
                                         prompt_tokens=prompt_tokens,
                                         budget=max_new_tokens,
                                         request_class=cls,
                                         prefilled=prefilled,
                                         **span_attrs)
                req.queued_span = tr.start_span("engine.queued",
                                                parent=req.span)
                if rng is None:
                    # pinned streams live in the router's reserved range;
                    # the local counter keeps its own sequence untouched
                    self._next_stream += 1
                self._reqs[rid] = req
                self._waitq[cls].append(rid)
                self._set_qdepth_locked()
                self._work.notify_all()
        if shed:
            self._shed_c[cls].inc()
            raise EngineBusy(self._busy_retry_ms)

    def cancel(self, rid) -> None:
        """Cancel ``rid``. Idempotent: unknown / already-retired ids are
        no-ops (CANCEL racing retirement is safe). A waiting request
        retires immediately; an admitted one is marked done and its slot
        frees at the next consumed chunk."""
        with self._work:
            req = self._reqs.pop(rid, None)
            if req is None or req.done:
                return
            req.done = True
            req.reason = "cancelled"
            try:
                self._waitq[req.cls].remove(rid)
            except ValueError:
                pass          # admitted: the loop's consume frees it
            self._set_qdepth_locked()
            self._work.notify_all()
        self._cancelled_c.inc()
        req.queued_span.end()
        req.first_span.end()
        req.span.end(reason="cancelled", tokens=req.emitted)
        self._emit_retired(req)

    def drain(self) -> None:
        """Graceful drain: reject further submits; :meth:`run` returns
        once every accepted request has retired."""
        with self._work:
            self._draining = True
            self._work.notify_all()

    def stop(self) -> None:
        """Abort: run() returns after at most the in-flight chunk, and
        every outstanding request retires as ``"stopped"``."""
        with self._work:
            self._draining = True
            self._stopped = True
            self._work.notify_all()

    def live_requests(self) -> list:
        """rids accepted and not yet retired (waiting or admitted) —
        what a transport sweeps when its delivery path dies (the decode
        server's sink-loss cancel)."""
        with self._lock:
            return [rid for rid, r in self._reqs.items() if not r.done]

    def stats(self) -> dict:
        """Live occupancy snapshot (the serving server's STATS payload).
        ``queue_depth`` mirrors the ``tony_serve_queue_depth`` gauge."""
        with self._lock:
            return {
                "queue_depth": self._wait_total_locked(),
                "queue_depths": {c: len(q)
                                 for c, q in self._waitq.items()},
                "active": sum(1 for r in self._occupant
                              if r is not None and not r.done),
                "slots": self.b.batch,
                "class_floors": dict(self._floors),
                "draining": self._draining,
                # the prefix fast path's compute story, readable
                # cross-process (the e2e zero-prefix-forward pin)
                "prefill_tokens": self.b.prefill_forward_tokens,
                "prefix_tokens": self.b.prefix_copied_tokens,
                "prefix_admits": self.b.prefix_admits,
            }

    # --- the loop (one driving thread) ---

    def run(self) -> None:
        """Drive the engine on the CALLING thread until drained or
        stopped. Between bursts of work the thread blocks on the
        admission condition — an idle engine costs nothing."""
        if getattr(self.b, "_engine_running", False):
            raise RuntimeError("batcher is already driven by an engine")
        self.b._engine_running = True
        try:
            # Goodput attribution for the serving plane: the driving
            # thread's wall is "step" (producing tokens) except the
            # blocks inside _wait_for_work, which re-enter "idle" —
            # slot busy-vs-idle falls out of the ledger breakdown.
            with goodput_mod.get_ledger().enter("step"):
                if self.b.pipeline:
                    self._run_pipelined()
                else:
                    self._run_sequential()
        finally:
            # seal the engine even on an abnormal exit (a device error
            # escaping the loop): late submits must raise rather than
            # enqueue into a dead engine the caller thinks is live
            with self._work:
                self._draining = True
                self._stopped = True
            self.b._engine_running = False
            self._abort_outstanding("stopped")
            metrics_mod.observe_phase_times(self.b.phase_times, self._reg)

    def _emit_retired(self, req: _EngineRequest, final=()) -> None:
        if self.on_retired is not None:
            self.on_retired(req.rid, req.reason, req.emitted,
                            list(final))

    def _abort_outstanding(self, reason: str) -> None:
        with self._lock:
            doomed = [r for r in self._reqs.values() if not r.done]
            for req in doomed:
                req.done = True
                req.reason = reason
            self._reqs.clear()
            for q in self._waitq.values():
                q.clear()
            self._occupant = [None] * self.b.batch
            self._set_qdepth_locked()
        for req in doomed:
            req.queued_span.end()
            req.first_span.end()
            req.span.end(reason=reason, tokens=req.emitted)
            self._emit_retired(req)

    def _wait_for_work(self) -> bool:
        """Block until there is runnable work (True) or the engine is
        drained-empty / stopped (False). Live OCCUPANTS count as work,
        not just waiting requests: a trailing ``_settle()`` can admit a
        submission that raced the burst's last sweep, and ignoring it
        here would strand that admitted request (blocked forever, or
        wrongly aborted as ``"stopped"`` under drain)."""
        with self._work:
            while True:
                if self._stopped:
                    return False
                if (self._wait_total_locked()
                        or any(r is not None and not r.done
                               for r in self._occupant)):
                    return True
                if self._draining:
                    return False
                with goodput_mod.get_ledger().enter("idle"):
                    self._work.wait()

    def _pop_admissible_locked(self, free: int, occ: dict):
        """Pop the next admissible waiting request (class-priority
        order: interactive, standard, batch) under the floor
        discipline. ``free`` counts still-free slots INCLUDING the one
        about to be granted; ``occ`` is live per-class occupancy
        including this round's admissions."""
        for cls in QOS_CLASSES:
            # a class past its floor takes a free slot only while the
            # REMAINING free slots still cover every other class's
            # unmet floor (a floor is a reservation, held even absent
            # demand); a class under its own floor is claiming its
            # reservation and always admits
            if occ[cls] >= self._floors[cls]:
                owed = sum(max(0, self._floors[o] - occ[o])
                           for o in QOS_CLASSES if o != cls)
                if free - 1 < owed:
                    continue
            q = self._waitq[cls]
            while q:
                req = self._reqs.get(q.popleft())
                if req is not None and not req.done:
                    return req
        return None

    def _preempt_locked(self):
        """Evict batch rows for interactive admissions still waiting
        after the fill: the victim (fewest emitted tokens — cheapest
        re-prefill) is tombstoned exactly like a cancel (its slot
        frees at the next consumed chunk; stale in-flight tokens
        discard) and its stream is REINCARNATED under the same rid at
        the front of the batch queue — prompt + emitted history folded
        into the new payload, rng offset advanced by the emitted count,
        so the PR 12 re-prefill machinery resumes it token-identically.
        A KV-package victim (decode tier) has no prompt to fold: it
        genuinely retires as ``"preempted"`` and the router re-places
        it. Returns ``(requeued, evicted)`` for the off-lock span /
        retirement work."""
        waiting = len(self._waitq["interactive"])
        requeued, evicted = [], []
        if not waiting:
            return requeued, evicted
        # slots already on their way free (done occupants vacate at the
        # next consumed chunk) count against the need — without this,
        # every settle between eviction and slot-free would evict again
        vacating = sum(1 for r in self._occupant
                       if r is not None and r.done)
        need = waiting - vacating
        while need > 0:
            victims = [r for r in self._occupant
                       if r is not None and not r.done
                       and r.cls == "batch"]
            # never evict below the batch floor — the freed slot would
            # be owed straight back to the batch queue
            if len(victims) <= self._floors["batch"]:
                break
            old = min(victims, key=lambda r: r.emitted)
            old.done = True
            old.reason = "preempted"
            self._reqs.pop(old.rid, None)
            if old.history is not None:
                old.requeued = True
                if isinstance(old.prompt, _PrefixHit):
                    payload = _PrefixHit(
                        old.prompt.entry,
                        list(old.prompt.suffix) + old.history)
                else:
                    payload = list(old.prompt) + old.history
                new = _EngineRequest(old.rid, payload, old.budget,
                                     old.stream, old.t_submit,
                                     rng_skip=old.rng_skip + old.emitted,
                                     cls="batch")
                new.emitted = old.emitted  # resume deltas are ITL
                new.t_last = old.t_last
                new.history = list(old.history)
                new.span = old.span        # same logical request
                old.span = tracing.NOOP_SPAN
                self._reqs[old.rid] = new
                self._waitq["batch"].appendleft(old.rid)
                requeued.append(new)
            else:
                evicted.append(old)
            need -= 1
        if requeued or evicted:
            self._set_qdepth_locked()
        return requeued, evicted

    def _admit_free(self) -> None:
        """Admit waiting requests into every free slot (row order — the
        freed order, since consume builds freed lists row-ascending),
        draining the class queues in priority order under the per-class
        floors, then preempt batch rows for any interactive admissions
        left waiting. The device dispatch runs OUTSIDE the lock; a
        request cancelled between marking and dispatch is discarded at
        its first consume."""
        with self._lock:
            pairs, prompts, admitted = [], {}, []
            occ = {c: 0 for c in QOS_CLASSES}
            free = 0
            for r in self._occupant:
                if r is None:
                    free += 1
                elif not r.done:
                    occ[r.cls] += 1
            for row in range(self.b.batch):
                if self._occupant[row] is not None:
                    continue
                req = self._pop_admissible_locked(free, occ)
                if req is None:
                    break
                self._occupant[row] = req
                occ[req.cls] += 1
                free -= 1
                pairs.append((row, req.stream))
                prompts[req.stream] = req.prompt
                admitted.append(req)
            if admitted:
                self._set_qdepth_locked()
            requeued, evicted = self._preempt_locked()
        if requeued or evicted:
            self._preempt_c.inc(len(requeued) + len(evicted))
            tr = tracing.get_tracer()
            for new in requeued:
                if new.span.recording:
                    new.queued_span = tr.start_span("engine.queued",
                                                    parent=new.span,
                                                    preempted=True)
            for old in evicted:
                old.first_span.end()
                old.span.end(reason="preempted", tokens=old.emitted)
                self._emit_retired(old)
        if admitted:
            tr = tracing.get_tracer()
            for req in admitted:
                req.queued_span.end()
                if req.span.recording:
                    # admit → first consumed delta: the prefill+decode
                    # share of TTFT, next to engine.queued's queue share
                    req.first_span = tr.start_span("engine.first_token",
                                                   parent=req.span)
            b = self.b
            before = (b.prefill_forward_tokens, b.prefix_copied_tokens,
                      b.prefix_admits)
            for req in admitted:
                if req.rng_skip:
                    # consumed by _rebind_streams at this admission
                    b._stream_skip[req.stream] = req.rng_skip
            b._admit_batch(pairs, prompts)
            self._admitted_c.inc(len(admitted))
            # fold the batcher's host-side prefill accounting into the
            # registry (the batcher itself is registry-unaware)
            if b.prefill_forward_tokens > before[0]:
                self._prefill_tok_c.inc(b.prefill_forward_tokens
                                        - before[0])
            if b.prefix_copied_tokens > before[1]:
                self._prefix_tok_c.inc(b.prefix_copied_tokens
                                       - before[1])
            if b.prefix_admits > before[2]:
                self._prefix_admits_c.inc(b.prefix_admits - before[2])

    def _consume(self, host_toks, snap) -> None:
        """Apply one fetched chunk under the occupancy it was ISSUED
        with, freeing completed/cancelled rows and emitting per-request
        deltas. Rows whose snapshot request already finished (a
        speculatively issued chunk crossed the completion, or a cancel
        landed mid-flight) carry garbage and are discarded — the same
        discard as idle-slot garbage."""
        deltas, retired = [], []
        eos = self.b.eos_id
        with self._lock:
            for row, req in enumerate(snap):
                if req is None or req.done:
                    if req is not None and self._occupant[row] is req:
                        # cancelled mid-flight: free the slot now
                        self._occupant[row] = None
                    continue
                new = []
                for t in host_toks[row]:
                    t = int(t)
                    new.append(t)
                    req.emitted += 1
                    req.budget -= 1
                    if req.budget == 0 or (eos is not None and t == eos):
                        # surplus chunk tokens past completion discarded
                        req.done = True
                        req.reason = ("eos" if eos is not None and t == eos
                                      else "budget")
                        self._reqs.pop(req.rid, None)
                        if self._occupant[row] is req:
                            self._occupant[row] = None
                        break
                if new:
                    if req.history is not None:
                        # evictable row: a preemption folds these into
                        # the reincarnation's prompt
                        req.history.extend(new)
                    deltas.append((req, new))
                if req.done:
                    retired.append(req)
        now = time.perf_counter()
        appended = 0
        finals = {id(req): new for req, new in deltas
                  if req in retired}
        for req, new in deltas:
            appended += len(new)
            if req.emitted == len(new):      # this is the first delta
                self._ttft_h.observe(now - req.t_submit)
                self._ttft_by_cls[req.cls].observe(now - req.t_submit)
                req.first_span.end()
            else:
                gap = (now - req.t_last) / len(new)
                self._itl_h.observe(gap)
                self._itl_by_cls[req.cls].observe(gap)
            req.t_last = now
            # a retiring request's FINAL delta rides its retirement
            # callback instead of on_delta, so transports can emit the
            # two atomically (a replica killed between a final TOKENS
            # frame and its RETIRED would otherwise leave a router
            # believing the stream is unfinished and re-admitting PAST
            # an already-streamed eos)
            if id(req) not in finals and self.on_delta is not None:
                self.on_delta(req.rid, new)
        if appended:
            self._tokens_c.inc(appended)
        if retired:
            self._retired_c.inc(len(retired))
            for req in retired:
                req.first_span.end()     # eos on the very first delta
                req.span.end(reason=req.reason, tokens=req.emitted)
                self._emit_retired(req, finals.get(id(req), ()))

    def _settle(self) -> None:
        self._admit_free()
        # reset ALL unoccupied rows (not just newly freed): a slot idle
        # across many chunks would otherwise march its garbage frontier
        # every step until it clamps at the cache end
        with self._lock:
            idle = [r is None for r in self._occupant]
        if any(idle):
            with self.b.phase_times.phase("retire"):
                self.b._retire(idle)

    def _sweep_done_occupants(self) -> bool:
        """Free slots held by done (cancelled) occupants when no chunk
        is in flight to do it; returns True when any slot is LIVE."""
        with self._lock:
            live = False
            for row, req in enumerate(self._occupant):
                if req is None:
                    continue
                if req.done:
                    self._occupant[row] = None
                else:
                    live = True
            return live

    def _certainly_final(self) -> bool:
        """The chunk about to be issued provably retires every live
        request (budget exhaustion; eos and speculative acceptance only
        finish EARLIER, and every speculative round commits >= 1 token)
        with nothing queued — issuing past it would be a guaranteed-
        garbage dispatch. (A submission landing during that final chunk
        is admitted at its settle and the loop continues.)"""
        with self._lock:
            if self._wait_total_locked():
                return False
            return all(req.budget <= self.b.chunk
                       for req in self._occupant
                       if req is not None and not req.done)

    def _defer_issue(self, snap) -> bool:
        """Process the in-flight chunk BEFORE issuing the next one when
        the host can PREDICT a completion with requests still queued:
        budget exhaustion is host-visible ahead of time, and issuing
        across it would run the freed slot idle for a whole chunk — a
        step-utilization loss the sequential loop doesn't pay.
        Unpredictable completions (eos mid-chunk, a cancel) are NOT
        deferred for — the loop stays optimistic and catches up after
        the fact. Budget-only workloads therefore pipeline LOSSLESSLY:
        chunk count, admission timing, and utilization all match the
        sequential loop."""
        with self._lock:
            return bool(self._wait_total_locked()) and any(
                req is not None and not req.done
                and req.budget <= self.b._chunk_tokens_max()
                for req in snap)

    def _run_pipelined(self) -> None:
        """Double-buffered dispatch against the live queue: chunk N+1
        enters the device queue before chunk N's fetch blocks on the
        transport. Structure identical to the pre-engine closed loop —
        the equivalence pin rests on it."""
        b = self.b
        while self._wait_for_work():
            self._admit_free()
            if not self._sweep_done_occupants():
                self._settle()          # everything cancelled pre-issue
                continue
            inflight = (b._issue(), list(self._occupant))
            while inflight is not None:
                handle, snap = inflight
                nxt = None
                if (not self._stopped and not self._certainly_final()
                        and not self._defer_issue(snap)):
                    nxt = (b._issue(), list(self._occupant))
                self._consume(b._fetch(handle), snap)
                self._settle()
                if self._stopped:
                    return               # drop any in-flight chunk
                with self._lock:
                    occupied = any(r is not None for r in self._occupant)
                if nxt is not None and not occupied:
                    # every request retired while the speculative chunk
                    # was in flight (eos beat the budget bound): drop it
                    # unfetched — all its rows are garbage
                    nxt = None
                if nxt is None and occupied:
                    nxt = (b._issue(), list(self._occupant))
                inflight = nxt

    def _run_sequential(self) -> None:
        """issue → fetch → bookkeep → admit; the equivalence baseline
        and A/B arm (``pipeline=False``) — every fetch serializes the
        transport round trip with device compute."""
        b = self.b
        while self._wait_for_work():
            self._admit_free()
            while not self._stopped:
                if not self._sweep_done_occupants():
                    self._settle()
                    break
                snap = list(self._occupant)
                self._consume(b._fetch(b._issue()), snap)
                self._settle()
