"""Continuous batching for the serving path.

Static-batch serving (one :func:`~tony_tpu.models.decode.generate` call
per request batch) leaves rows idle from the moment they finish until the
LAST row finishes — at mixed request lengths most of the batch is dead
weight. Continuous batching retires a row the step it completes and
admits the next queued request into its cache slot while the other rows
keep decoding; utilization follows the OFFERED load, not the slowest
request. (The industry-standard serving pattern; green-field here —
SURVEY.md §2.3, the reference delegates all compute and has no serving
path.)

The round-5 per-row decode machinery is exactly what makes this cheap
(models/decode.py): cache ``length`` is a [B] vector, RoPE positions,
causal masks, and K/V writes all take per-row frontiers, and the
length-aware block-wise attention reads only each batch's LIVE rows of a
shared padded cache. On top of that, three small device programs:

- :func:`admit_row` — a batch-1 prefill whose K/V land in the retired
  row's cache slot (one contiguous ``dynamic_update_slice`` per buffer)
  and whose last-position logits seed the row's next step;
- :func:`step_rows` — a ``lax.scan`` of ``n`` per-row decode steps over
  the whole batch (one dispatch per chunk, not per token; greedy by
  default, or sampled through the same top-k/temperature/nucleus stack
  as ``decode.generate``);
- :func:`retire_rows` — zero the freed rows' frontiers so idle slots
  never walk off the end of the cache.

Correctness argument for slot reuse: a row's queries attend positions
``<= pos_r`` only. A new occupant's prefill rewrites positions
``[0, S_prompt)`` and its decode steps write exactly at ``pos_r`` before
reading it, so every position a query can reach was written by the
CURRENT occupant — the previous request's stale K/V beyond the frontier
is unreachable by construction (the same argument the speculative
decoder makes for rejected-draft entries).

The admission loop itself (:class:`ContinuousBatcher`) is host-driven —
admission is inherently data-dependent control flow (which request, into
which slot, at what length) and runs at human/request rate, while the
token loop stays on device in ``step_rows`` chunks.

:class:`SpeculativeContinuousBatcher` composes the two serving features:
every slot runs draft-propose/target-verify rounds at its own frontier
(:func:`spec_step_rows`) while admission/retirement reuse slots exactly
as in the greedy batcher — vLLM-style continuous batching with
speculative decoding, token-identical to per-request greedy decode.

Shared-prefix caching (``shared_prefix=``, both batchers): a system
prompt every request continues from prefills ONCE into a K/V template;
admission copies the template into the slot and runs only the request's
own tokens through the model (:func:`prefix_admit_row` — a chunked
``extend_step`` against the copied prefix history), token-identical to
serving prefix+prompt in full.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import (_check_draft_vocab, _check_no_ring,
                                    _filter_logits, _kv_bufs,
                                    _propose_and_verify,
                                    _propose_and_verify_sampled, _sample,
                                    decode_step, extend_step,
                                    init_kv_cache, prefill)


def _place_prefill(cache, mini, row, s_p):
    """Land a batch-1 prefill's K/V into cache slot ``row`` (one
    contiguous ``dynamic_update_slice`` per buffer — k/v plus int8
    scales when the cache is quantized) and set the row's frontier to
    the prompt length."""
    placed = {n: jax.lax.dynamic_update_slice(cache[n], mini[n],
                                              (0, row, 0, 0, 0))
              for n in _kv_bufs(mini)}
    return dict(placed, length=cache["length"].at[row].set(s_p))


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def admit_row(params, cache, logits, row, prompt, cfg):
    """Admit a request into cache slot ``row``.

    prompt: [1, S_p] (batch-1 prefill; retraces per distinct prompt
    length — pad/bucket lengths upstream if that matters). Returns
    (cache, logits) with the row's K/V filled, its frontier at S_p, and
    its next-step logits seeded.
    """
    lg1, mini = prefill(params, prompt, cfg, max_len=prompt.shape[1])
    return (_place_prefill(cache, mini, row, prompt.shape[1]),
            logits.at[row].set(lg1[0]))


def prefix_template(params, prefix, cfg):
    """Prefill a SHARED PREFIX once (a system prompt every request
    continues from); returns the [L, 1, P, KV, hd] K/V template
    :func:`prefix_admit_row` copies into each admitted slot. prefix:
    [P] ints."""
    _, mini = prefill(params, jnp.asarray(prefix, jnp.int32)[None], cfg,
                      max_len=len(prefix))
    return _kv_bufs(mini)


def _extend_from_template(model_params, template, suffix, model_cfg):
    """Build a [L, 1, P+S]-row mini cache from a prefix ``template`` and
    run the ``suffix`` through the model against it (a chunked
    :func:`extend_step` — suffix queries attend the full prefix history
    exactly as a monolithic prefill of prefix+suffix would). Returns
    (suffix logits [1, S, V], filled mini cache, total length P+S).
    Shared by the greedy and speculative prefix admitters."""
    p_len = template["k"].shape[2]
    s_len = suffix.shape[1]
    mini = dict(
        {n: jnp.concatenate(
            [x, jnp.zeros(x.shape[:2] + (s_len,) + x.shape[3:],
                          x.dtype)], axis=2)
         for n, x in template.items()},
        length=jnp.asarray(p_len, jnp.int32))
    lg, mini = extend_step(model_params, suffix, mini, p_len, model_cfg)
    return lg, mini, p_len + s_len


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def prefix_admit_row(params, cache, logits, row, template, suffix, cfg):
    """Admit a request that CONTINUES a shared prefix: the prefix's K/V
    come from the precomputed ``template`` (one prefill for the whole
    serve, not one per request) and only the request's ``suffix``
    [1, S] runs a forward (:func:`_extend_from_template`). Admission
    compute drops from O(P+S) to O(S) tokens; at a long system prompt
    and short user turns that is the dominant admission cost."""
    lg, mini, total = _extend_from_template(params, template, suffix, cfg)
    return (_place_prefill(cache, mini, row, total),
            logits.at[row].set(lg[0, -1]))


@functools.partial(jax.jit, static_argnames=("cfg", "n", "temperature",
                                             "top_k", "top_p"),
                   donate_argnames=("cache", "logits"))
def step_rows(params, cache, logits, rng, n, cfg, temperature=0.0,
              top_k=0, top_p=0.0):
    """``n`` decode steps for every row at its OWN frontier — greedy at
    ``temperature=0`` (default), otherwise sampled per row through the
    same filter stack as :func:`tony_tpu.models.decode.generate`
    (top-k → temperature → nucleus). ``rng``: a PRNGKey, split per step
    (rows sample independently from one key — ``categorical`` on [B, V]
    draws per-row). Returns (tokens [B, n], cache, logits). Idle rows
    decode garbage that the host discards — uniform batch math keeps
    this one compiled program regardless of which rows are live."""

    def body(carry, step_rng):
        lg, c = carry
        # _sample handles temperature==0 as argmax; its unused logprob
        # output is DCE'd under jit
        tok, _ = _sample(lg, step_rng, temperature, top_k, top_p)
        lg, c = decode_step(params, tok, c, c["length"], cfg)
        return (lg, c), tok

    (lg, cache), toks = jax.lax.scan(body, (logits, cache),
                                     jax.random.split(rng, n))
    return toks.T, cache, lg


@functools.partial(jax.jit, donate_argnames=("cache",))
def retire_rows(cache, mask):
    """Reset retired rows' frontiers to 0 (mask: [B] bool). Keeps idle
    slots from marching their garbage frontier into the cache end."""
    return dict(cache, length=jnp.where(mask, 0, cache["length"]))


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_admit_row(params, draft_params, t_cache, d_cache, pending, row,
                   prompt, rng, cfg, draft_cfg, temperature=0.0,
                   top_k=0, top_p=0.0):
    """Speculative admission: prefill BOTH models on the prompt into
    cache slot ``row`` (the draft keeps its own per-slot K/V history) and
    seed the row's ``pending`` token from the target's last-position
    logits — argmax at ``temperature=0``, otherwise a sample through the
    same filter stack the rounds use (the seed token is part of the
    request's sampled stream). Same contract as :func:`admit_row`
    otherwise."""
    lg, mini_t = prefill(params, prompt, cfg, max_len=prompt.shape[1])
    _, mini_d = prefill(draft_params, prompt, draft_cfg,
                        max_len=prompt.shape[1])
    s_p = prompt.shape[1]
    t_cache = _place_prefill(t_cache, mini_t, row, s_p)
    d_cache = _place_prefill(d_cache, mini_d, row, s_p)
    if temperature == 0.0:
        seed_tok = jnp.argmax(lg[0], axis=-1)
    else:
        seed_tok = jax.random.categorical(
            rng, _filter_logits(lg[0].astype(jnp.float32), temperature,
                                top_k, top_p), axis=-1)
    pending = pending.at[row].set(seed_tok.astype(pending.dtype))
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_prefix_admit_row(params, draft_params, t_cache, d_cache, pending,
                          row, t_template, d_template, suffix, rng, cfg,
                          draft_cfg, temperature=0.0, top_k=0, top_p=0.0):
    """Shared-prefix admission for the speculative batcher: BOTH models'
    prefix K/V come from precomputed templates and only the suffix runs
    a forward through each (:func:`_extend_from_template`); the pending
    seed comes from the target's last suffix position, argmax or
    sampled, as in :func:`spec_admit_row`."""
    lg, mini_t, total = _extend_from_template(params, t_template,
                                              suffix, cfg)
    _, mini_d, _ = _extend_from_template(draft_params, d_template,
                                         suffix, draft_cfg)
    t_cache = _place_prefill(t_cache, mini_t, row, total)
    d_cache = _place_prefill(d_cache, mini_d, row, total)
    if temperature == 0.0:
        seed_tok = jnp.argmax(lg[0, -1], axis=-1)
    else:
        seed_tok = jax.random.categorical(
            rng, _filter_logits(lg[0, -1].astype(jnp.float32),
                                temperature, top_k, top_p), axis=-1)
    pending = pending.at[row].set(seed_tok.astype(pending.dtype))
    return t_cache, d_cache, pending


@functools.partial(jax.jit, static_argnames=("cfg", "draft_cfg", "n", "k",
                                             "temperature", "top_k",
                                             "top_p"),
                   donate_argnames=("t_cache", "d_cache", "pending"))
def spec_step_rows(params, draft_params, t_cache, d_cache, pending, rng,
                   n, cfg, draft_cfg, k, temperature=0.0, top_k=0,
                   top_p=0.0):
    """``n`` speculative rounds for every row at its OWN frontier — the
    serving analog of :func:`step_rows` built on the same
    propose-and-verify round the speculative decoder uses
    (:func:`tony_tpu.models.decode._propose_and_verify`). Each round every
    row commits its full per-row acceptance ``acc_r + 1`` (serving has no
    generation budget on device — the host truncates at each request's
    budget/eos and discards idle rows' garbage, exactly as in greedy
    continuous batching). Returns ``(packed [n, B, k+2], t_cache,
    d_cache, pending)`` where ``packed[i, r, 0]`` is round i's per-row
    commit count and ``packed[i, r, 1:]`` its k+1-wide token chunk —
    row r's committed tokens for round i are
    ``packed[i, r, 1:1+packed[i, r, 0]]``, in order. ONE output array by
    design: the host syncs on this value every ``n`` rounds, and each
    separately-fetched device array costs its own transport round trip
    (~100 ms on a tunneled chip — returning chunks and counts apart
    measured 242 ms/sync vs ~130 for the greedy batcher's single token
    array, erasing speculation's win).

    ``temperature > 0`` runs SAMPLED rounds instead
    (:func:`decode._propose_and_verify_sampled`): serving commits the
    full per-row acceptance every round, so each slot's next pending is
    simply the round's residual/bonus sample, and each request's
    committed stream is distributed exactly as target-only sampling
    through the same filter stack."""

    def body(carry, round_rng):
        t_cache, d_cache, pending = carry
        pos = t_cache["length"]                                  # [B]
        if temperature == 0.0:
            chunk, argmaxes, acc, t_cache, d_cache = _propose_and_verify(
                params, draft_params, t_cache, d_cache, pending, pos,
                cfg, draft_cfg, k, None, pending.dtype)
            pending = jnp.take_along_axis(argmaxes, acc[:, None],
                                          axis=1)[:, 0]
        else:
            chunk, extra, acc, t_cache, d_cache = (
                _propose_and_verify_sampled(
                    params, draft_params, t_cache, d_cache, pending,
                    pos, cfg, draft_cfg, k, None, pending.dtype,
                    round_rng, temperature, top_k, top_p))
            pending = extra
        count = acc + 1
        new_len = (pos + count).astype(jnp.int32)
        t_cache = dict(t_cache, length=new_len)
        d_cache = dict(d_cache, length=new_len)
        packed = jnp.concatenate(
            [count[:, None].astype(jnp.int32),
             chunk.astype(jnp.int32)], axis=1)                   # [B, k+2]
        return (t_cache, d_cache, pending), packed

    (t_cache, d_cache, pending), packed = jax.lax.scan(
        body, (t_cache, d_cache, pending), jax.random.split(rng, n))
    return packed, t_cache, d_cache, pending


class ContinuousBatcher:
    """Host-side admission loop over the device programs above.

    ``serve(prompts, max_new_tokens)`` runs every request to completion
    (``max_new_tokens`` or ``eos_id``) through a fixed ``batch`` of cache
    slots, admitting the next queued request the moment a slot frees.
    At the default ``temperature=0`` outputs are the same greedy tokens
    :func:`decode.generate` produces for each request alone
    (test-verified token-identical on CPU); with ``temperature``/
    ``top_k``/``top_p`` set, slots sample through the same filter stack
    as ``generate`` instead (seed-reproducible per workload — see
    ``__init__``).
    """

    def __init__(self, params, cfg: T.TransformerConfig, batch: int,
                 max_len: int, eos_id: int | None = None,
                 chunk: int = 8, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 seed: int = 0,
                 shared_prefix=None) -> None:
        self.params = params
        self.cfg = cfg
        self.batch = batch
        self.max_len = max_len
        self.eos_id = eos_id
        #: shared-prefix caching: when set (a token sequence, e.g. a
        #: system prompt), every request's prompt is interpreted as a
        #: CONTINUATION of it — the prefix prefills once into a K/V
        #: template that admission copies into the slot, and only the
        #: request's own tokens run a forward (prefix_admit_row).
        #: Outputs are token-identical to serving prefix+prompt in full.
        self.shared_prefix = (None if shared_prefix is None
                              else list(shared_prefix))
        if self.shared_prefix is not None and not self.shared_prefix:
            raise ValueError("shared_prefix must be non-empty when given")
        #: rolling KV cache (cfg.kv_cache_capacity): slots hold a ring
        #: of O(window) rows and requests may run past max_len — the
        #: budget check below relaxes accordingly. Prefix templates are
        #: positional and don't survive ring wraparound.
        self._ring = bool(cfg.kv_cache_capacity)
        if self.shared_prefix is not None:
            # prefix templates are positional; they don't survive ring
            # wraparound
            _check_no_ring(cfg, "shared-prefix caching")
        self._prefix_template = (
            prefix_template(params, self.shared_prefix, cfg)
            if self.shared_prefix else None)
        #: sampling controls (greedy by default); the rng stream restarts
        #: from ``seed`` at every serve() call, so a workload re-served
        #: with the same seed reproduces its outputs — but a request's
        #: samples depend on its admission timing within the workload,
        #: not on the request alone (shared stream; acceptable for
        #: serving, use generate() for per-request reproducibility)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        # usable standalone (the _admit/_dispatch seams don't require a
        # serve() call first); serve() re-seeds for per-workload
        # reproducibility
        self._rng = jax.random.PRNGKey(seed)
        #: device steps per host round trip — latency/overhead trade:
        #: a finished row idles at most chunk-1 steps before its slot
        #: is reused
        self.chunk = max(1, chunk)
        self.cache = init_kv_cache(cfg, batch, max_len)
        # per-row frontiers from the start (decode.py's [B] position path)
        self.cache = dict(self.cache,
                          length=jnp.zeros((batch,), jnp.int32))
        self.logits = jnp.zeros((batch, cfg.vocab_size),
                                cfg.logits_storage_dtype)

    # --- device seams (overridden by the speculative batcher) ---

    def _admit(self, row: int, tokens) -> None:
        if self._prefix_template is not None:
            self.cache, self.logits = prefix_admit_row(
                self.params, self.cache, self.logits, row,
                self._prefix_template, tokens, self.cfg)
        else:
            self.cache, self.logits = admit_row(
                self.params, self.cache, self.logits, row, tokens,
                self.cfg)

    def _dispatch(self):
        """Run one device chunk; returns per-slot newly generated tokens
        (a [B, n] array or list of per-row sequences, in order)."""
        import numpy as np

        self._rng, sub = jax.random.split(self._rng)
        toks, self.cache, self.logits = step_rows(
            self.params, self.cache, self.logits, sub, self.chunk,
            self.cfg, self.temperature, self.top_k, self.top_p)
        self.steps_executed += self.chunk
        return np.asarray(toks)

    def _retire(self, mask) -> None:
        self.cache = retire_rows(self.cache, jnp.asarray(mask))

    def serve(self, prompts: Sequence, max_new_tokens):
        """Run all ``prompts`` (each a [S_p] int sequence) to completion;
        returns a list of per-request generated-token lists, order-
        matching the input. ``max_new_tokens``: one int for all requests
        or a per-request sequence (mixed-length serving is the whole
        point). ``self.steps_executed`` counts device decode steps run —
        the utilization denominator (each step advances every slot)."""
        queue = list(range(len(prompts)))
        outputs: list[list[int]] = [[] for _ in prompts]
        if isinstance(max_new_tokens, int):
            budget = [max_new_tokens] * len(prompts)
        else:
            budget = list(max_new_tokens)
            if len(budget) != len(prompts):
                raise ValueError("per-request max_new_tokens length "
                                 "must match prompts")
        # validate EVERY request before admitting any: a mid-serve raise
        # would discard completed outputs and strand the batcher state
        p_len = len(self.shared_prefix) if self.shared_prefix else 0
        for req, (p, b) in enumerate(zip(prompts, budget)):
            if len(p) == 0:
                raise ValueError(f"request {req}: empty prompt")
            if b <= 0:
                raise ValueError(f"request {req}: max_new_tokens must be "
                                 f"positive, got {b}")
            if not self._ring and p_len + len(p) + b > self.max_len:
                # rolling caches have no length ceiling — the ring holds
                # the window however long the stream runs
                raise ValueError(
                    f"request {req}: "
                    + (f"shared prefix {p_len} + " if p_len else "")
                    + f"prompt {len(p)} + {b} new tokens exceeds "
                      f"max_len {self.max_len}")
        occupant: list[int | None] = [None] * self.batch
        self.steps_executed = 0
        self.rounds_executed = 0
        self._rng = jax.random.PRNGKey(self.seed)

        def admit_next(row: int) -> None:
            req = queue.pop(0)
            self._admit(row, jnp.asarray(prompts[req], jnp.int32)[None])
            occupant[row] = req

        for row in range(self.batch):
            if queue:
                admit_next(row)

        while any(o is not None for o in occupant):
            host_toks = self._dispatch()
            freed = []
            for row, req in enumerate(occupant):
                if req is None:
                    continue
                for t in host_toks[row]:
                    outputs[req].append(int(t))
                    budget[req] -= 1
                    if budget[req] == 0 or (self.eos_id is not None
                                            and int(t) == self.eos_id):
                        # surplus chunk tokens past completion discarded
                        occupant[row] = None
                        freed.append(row)
                        break
            for row in freed:
                if queue:
                    admit_next(row)
            # reset ALL unoccupied rows (not just newly freed): a slot
            # idle across many chunks would otherwise march its garbage
            # frontier every step until it clamps at the cache end
            if any(o is None for o in occupant):
                self._retire([o is None for o in occupant])
        return outputs


class SpeculativeContinuousBatcher(ContinuousBatcher):
    """Continuous batching with speculative decoding per slot — the two
    serving features composed. A cheap draft model proposes
    ``num_speculative`` tokens per round for EVERY slot at its own
    frontier; the target verifies each slot's chunk in one wide
    ``extend_step``; each slot commits its own acceptance
    (:func:`spec_step_rows`, built on the same propose-and-verify round
    as ``decode.speculative_generate_device``). Slot reuse works exactly
    as in the greedy batcher: admission prefills BOTH caches, retirement
    frees the slot, and idle rows decode garbage the host discards.

    Outputs are token-identical to the greedy batcher (and therefore to
    per-request ``decode.generate``) wherever chunked and single-step
    logits agree — bit-exact on CPU, matmul-noise near-ties on TPU, the
    same caveat as all speculative paths. Wall-clock wins need a draft
    that predicts the target AND enough per-request work to amortize the
    round structure; ``rounds_executed`` counts speculative rounds run
    (tokens-per-round = the acceptance-driven efficiency).

    ``chunk`` here counts speculative ROUNDS per host sync, not tokens:
    one round commits between 1 and k+1 tokens per live slot, so a
    finished request idles at most ``chunk-1`` rounds before its slot is
    reused.

    Accounting: ``steps_executed`` counts TARGET-MODEL positions
    verified per slot (``rounds * (k+1)``) so the base class's
    step-utilization reading remains meaningful — useful tokens /
    (steps_executed * slots) is the fraction of verified positions that
    became committed tokens (acceptance efficiency × occupancy).
    ``rounds_executed`` counts speculative rounds.

    ``temperature > 0`` switches every slot's rounds to SPECULATIVE
    SAMPLING (``decode._propose_and_verify_sampled``): each request's
    committed stream is distributed exactly as target-only sampling
    through the same temperature/top-k/top-p stack, for any draft —
    greedy rounds remain the token-exact default."""

    def __init__(self, params, cfg: T.TransformerConfig,
                 draft_params, draft_cfg: T.TransformerConfig,
                 batch: int, max_len: int,
                 num_speculative: int = 4, eos_id: int | None = None,
                 chunk: int = 4, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 seed: int = 0, shared_prefix=None) -> None:
        super().__init__(params, cfg, batch, max_len, eos_id=eos_id,
                         chunk=chunk, temperature=temperature,
                         top_k=top_k, top_p=top_p, seed=seed,
                         shared_prefix=shared_prefix)
        if num_speculative < 1:
            raise ValueError("num_speculative must be >= 1")
        _check_draft_vocab(cfg, draft_cfg)
        _check_no_ring(cfg, "speculative serving (chunked verify)")
        _check_no_ring(draft_cfg, "speculative serving (draft)")
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        # the draft needs its own prefix template (its K/V dims differ)
        self._draft_prefix_template = (
            prefix_template(draft_params, self.shared_prefix, draft_cfg)
            if self.shared_prefix else None)
        self.k = num_speculative
        self.d_cache = init_kv_cache(draft_cfg, batch, max_len)
        self.d_cache = dict(self.d_cache,
                            length=jnp.zeros((batch,), jnp.int32))
        # pending token per slot (the committed token whose K/V is not
        # yet written) replaces the greedy batcher's per-slot logits
        self.pending = jnp.zeros((batch,), jnp.int32)

    def _admit(self, row: int, tokens) -> None:
        self._rng, sub = jax.random.split(self._rng)
        if self._prefix_template is not None:
            self.cache, self.d_cache, self.pending = spec_prefix_admit_row(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, row, self._prefix_template,
                self._draft_prefix_template, tokens, sub, self.cfg,
                self.draft_cfg, self.temperature, self.top_k, self.top_p)
        else:
            self.cache, self.d_cache, self.pending = spec_admit_row(
                self.params, self.draft_params, self.cache, self.d_cache,
                self.pending, row, tokens, sub, self.cfg, self.draft_cfg,
                self.temperature, self.top_k, self.top_p)

    def _dispatch(self):
        import numpy as np

        self._rng, sub = jax.random.split(self._rng)
        packed, self.cache, self.d_cache, self.pending = (
            spec_step_rows(self.params, self.draft_params, self.cache,
                           self.d_cache, self.pending, sub, self.chunk,
                           self.cfg, self.draft_cfg, self.k,
                           self.temperature, self.top_k, self.top_p))
        self.rounds_executed += self.chunk
        self.steps_executed += self.chunk * (self.k + 1)
        # ONE host fetch per sync (see spec_step_rows: separate fetches
        # pay separate transport round trips)
        packed = np.asarray(packed)                    # [n, B, k+2]
        return [
            [int(t) for i in range(packed.shape[0])
             for t in packed[i, row, 1:1 + packed[i, row, 0]]]
            for row in range(self.batch)]

    def _retire(self, mask) -> None:
        m = jnp.asarray(mask)
        self.cache = retire_rows(self.cache, m)
        self.d_cache = retire_rows(self.d_cache, m)
