"""Sharded train-step builder: loss → pjit-compiled SPMD update.

The TPU-native replacement for what the reference leaves entirely to user
TF/PyTorch code (SURVEY.md §2.3: PS/worker and all-reduce DP live in
tony-examples, not the framework). Here the framework owns the recipe:
params live device-sharded per logical-axis rules, the batch arrives sharded
over dp/fsdp, jax.grad + optax run under jit over the global mesh, and XLA
inserts the gradient psum/reduce-scatter collectives that NCCL all-reduce
performed in the reference's PyTorch example (tony-examples/mnist-pytorch/
mnist_distributed.py:113-126).
"""

from __future__ import annotations

import collections
import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel.sharding import (DEFAULT_RULES, Rules,
                                        logical_sharding, param_shardings,
                                        shard_pytree)
from tony_tpu.runtime import metrics as metrics_mod


# Train state is a plain dict pytree: {"params", "opt_state", "step"}.
TrainState = dict

#: Trace-time program counters keyed by (program name, batch leaf
#: shapes/dtypes): incremented when the train/eval step is TRACED
#: (compiled), not when it is called — the train-side twin of
#: ``serve.TRACE_COUNTS``. The conftest ``retrace_guard`` fixture reads
#: both, so tests pin "one compiled train step per batch shape across a
#: full run_training run" the same way serve pins bucketed admission.
TRACE_COUNTS: collections.Counter = collections.Counter()


def _count_trace(name: str, batch: Any) -> None:
    TRACE_COUNTS[(name, tuple(
        (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", "?")))
        for l in jax.tree.leaves(batch)))] += 1


def masked_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean -log p[target] over positions with targets >= 0 (-1 = ignore).

    Uses the logsumexp form so the [B, S, V] log_softmax is never
    materialized — at LM vocab sizes that array is the largest HBM tensor
    in the step. The target logit is picked with an on-the-fly one-hot
    compare-and-reduce rather than ``take_along_axis``: a gather is its own
    HLO and forces a SECOND full pass over the logits (+2.8 ms/step
    measured at 16×1024×32k on one v5e — and its backward is a scatter),
    while the compare/select/reduce fuses into the same fusion that
    computes lse, so the logits are read once. Loss math runs in f32
    whatever the logits' storage dtype (models may store them bf16 —
    TransformerConfig.logits_dtype — and the upcast here is elementwise,
    so it fuses into the reduction passes rather than materializing).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)              # [B, S]
    onehot = targets[..., None] == jnp.arange(logits.shape[-1])     # virtual
    picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)       # [B, S]
    mask = (targets >= 0).astype(jnp.float32)
    return ((lse - picked) * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def init_state(params: Any, optimizer: optax.GradientTransformation,
               mesh: Mesh | None = None, axes: Any = None,
               rules: Rules = DEFAULT_RULES) -> TrainState:
    """Build (and, given a mesh, device-shard) the train state."""
    if mesh is not None and axes is not None:
        params = shard_pytree(params, axes, mesh, rules)
    opt_state = optimizer.init(params)
    return {"params": params, "opt_state": opt_state,
            "step": jnp.zeros((), jnp.int32)}


def make_train_step(loss_fn: Callable[[Any, Any], jax.Array] | None,
                    optimizer: optax.GradientTransformation,
                    mesh: Mesh | None = None,
                    donate: bool = True,
                    value_and_grad_fn: Callable | None = None) -> Callable:
    """Compile ``state, batch → state, metrics``.

    ``loss_fn(params, batch) -> scalar``. Under a mesh the step runs as one
    SPMD program; gradients of replicated params are reduced by XLA
    automatically (no explicit all-reduce anywhere).

    ``value_and_grad_fn(params, batch) -> (loss, grads)`` replaces
    ``jax.value_and_grad(loss_fn)`` for schedules that produce their own
    gradients (the 1F1B pipeline, transformer.lm_value_and_grad — 1F1B
    must run the loss inside the pipeline, so it cannot be a jax.grad
    target); ``loss_fn`` may then be None.
    """

    fused = hasattr(optimizer, "fused_apply")
    if fused and mesh is not None:
        # fail where the step is built, not with an opaque SPMD lowering
        # error: a pallas_call does not partition under pjit, so sharded
        # params need the optax formulation (default_optimizer docstring)
        raise ValueError("fused optimizers are single-chip only — use "
                         "default_optimizer(fused=False) with a mesh")

    vag = value_and_grad_fn or jax.value_and_grad(loss_fn)

    def step(state: TrainState, batch: Any):
        _count_trace("train_step", batch)   # trace-time only: counts compiles
        loss, grads = vag(state["params"], batch)
        if fused:
            # single-pass update (ops/optim.py): params change inside the
            # kernel, no separate apply_updates traversal
            params, opt_state, gnorm = optimizer.fused_apply(
                grads, state["opt_state"], state["params"])
        else:
            updates, opt_state = optimizer.update(grads, state["opt_state"],
                                                  state["params"])
            params = optax.apply_updates(state["params"], updates)
            gnorm = optax.global_norm(grads)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state["step"]}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    if mesh is None:
        return _instrument_step(jitted)

    def sharded_step(state, batch):
        # set_mesh must wrap the CALL, not the traced body: the ambient mesh
        # is what lets bare-PartitionSpec sharding constraints resolve.
        with jax.set_mesh(mesh):
            return jitted(state, batch)

    return _instrument_step(sharded_step)


def _instrument_step(step_fn: Callable) -> Callable:
    """Observe per-call wall time and example throughput into the default
    metrics registry (``tony_train_step_seconds`` histogram,
    ``tony_train_steps_total`` / ``tony_train_examples_total`` counters).

    The timing is the HOST wall of the dispatch: jitted steps run async,
    but under a saturated loop with donated state each dispatch gates on
    the previous step's completion, so steady-state wall-per-call tracks
    step time (the same caveat every async-dispatch profiler carries;
    ``PhaseTimes``/``StepTracer`` in runtime/profiler.py give the precise
    per-phase / device-side views). Cost per call is one perf_counter
    pair plus three GIL-atomic observations — noise next to any real
    step."""

    def instrumented(state, batch):
        t0 = time.perf_counter()
        out = step_fn(state, batch)
        dt = time.perf_counter() - t0
        reg = metrics_mod.get_default()
        reg.histogram("tony_train_step_seconds",
                      help="host wall seconds per train-step dispatch"
                      ).observe(dt)
        reg.counter("tony_train_steps_total", help="train steps run").inc()
        leaves = jax.tree.leaves(batch)
        if leaves and getattr(leaves[0], "shape", None):
            # leading batch dim of the first leaf = local examples/step;
            # rate(examples_total) is the examples/s the fleet view wants
            reg.counter("tony_train_examples_total",
                        help="examples consumed by train steps").inc(
                            leaves[0].shape[0])
        return out

    return instrumented


def batch_sharding(mesh: Mesh, rules: Rules = DEFAULT_RULES,
                   logical: tuple = ("batch",)) -> NamedSharding:
    """Sharding for input batches: batch dim over dp/fsdp, rest replicated
    (callers append dims, e.g. ("batch", "seq") for token arrays)."""
    return logical_sharding(logical, mesh, rules)


def data_parallel_rank(mesh: Mesh, axes: tuple[str, ...] = ("dp", "fsdp"),
                       ) -> int:
    """This process's rank along the data-parallel mesh axes — the value to
    seed per-process data generation with. Processes at the same dp/fsdp
    coordinate (e.g. pure-pp or pure-tp meshes, where the batch is
    REPLICATED across processes) get the same rank and must feed identical
    data; seeding by task index there would hand ``global_batch`` divergent
    "replicas" that silently disagree across devices.

    Memoized per (mesh, axes): the body runs an ``np.vectorize`` scan over
    every mesh device, and data sources call this from step-adjacent paths
    (the prefetcher's epoch seeding) — the device↔process assignment is
    fixed for the life of the process, so the scan pays once."""
    return _data_parallel_rank_cached(mesh, tuple(axes))


@functools.lru_cache(maxsize=64)
def _data_parallel_rank_cached(mesh: Mesh, axes: tuple[str, ...]) -> int:
    import numpy as np
    local = set(jax.local_devices())
    coords = np.argwhere(
        np.vectorize(lambda d: d in local)(mesh.devices))
    if coords.size == 0:    # process owns no mesh device (untracked types)
        return 0
    first = coords[0]
    rank = 0
    for ax in axes:
        if ax in mesh.axis_names:
            i = mesh.axis_names.index(ax)
            rank = rank * mesh.devices.shape[i] + int(first[i])
    return rank


def global_batch(sharding: NamedSharding, local_tree: Any) -> Any:
    """Assemble each process's LOCAL batch shard into global jax.Arrays —
    the multi-host feeding recipe (every process calls this with its own,
    different data; ``jax.device_put`` would instead assert the value is
    identical everywhere). Leaves may differ in rank; the sharding's spec
    applies to the leading (batch) dims and replicates the rest."""
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        local_tree)


def make_eval_step(loss_fn: Callable[[Any, Any], jax.Array],
                   mesh: Mesh | None = None) -> Callable:
    def eval_step(params, batch):
        _count_trace("eval_step", batch)
        return loss_fn(params, batch)

    jitted = jax.jit(eval_step)
    if mesh is None:
        return jitted

    def sharded(params, batch):
        with jax.set_mesh(mesh):
            return jitted(params, batch)
    return sharded


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.01,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000,
                      fused: bool = False):
    """AdamW + linear warmup→cosine decay, the standard LM recipe.

    ``fused=True`` selects the single-pass Pallas update (ops/optim.py)
    with f32 moments — a NUMERICS upgrade for bf16 models (optax silently
    inherits bf16 moments from bf16 grads), at a measured ~1 ms/step cost
    at 66 M params on one v5e. It is not a throughput win: XLA fuses the
    optax chain into the backward epilogue (grads are consumed in
    registers, never re-read from HBM), which a custom call cannot match
    — see docs/performance.md "What didn't help". The optax chain is
    the default and the only multi-chip path (a pallas_call does not
    partition under pjit). Both match to fp tolerance (tests/test_ops.py).
    """
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    if fused:
        from tony_tpu.ops.optim import FusedAdamW
        return FusedAdamW(sched, weight_decay=weight_decay, clip_norm=1.0)
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(sched, weight_decay=weight_decay),
    )
