"""MNIST models: the north-star workload.

BASELINE.json's target is the reference's ``mnist_distributed.py`` examples
(tony-examples/mnist-tensorflow, tony-examples/mnist-pytorch) re-done
TPU-native: same MLP/CNN-scale models, but as pjit data-parallel programs
instead of PS/worker TF or torch all-reduce. Synthetic-data helpers keep the
E2E suite hermetic (no dataset download in CI, mirroring the reference's
use of the bundled MNIST tarball)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

NUM_CLASSES = 10
INPUT_DIM = 784


def init_mlp(rng: jax.Array, hidden: int = 512, depth: int = 2,
             dtype=jnp.float32) -> dict:
    keys = jax.random.split(rng, depth + 1)
    dims = [INPUT_DIM] + [hidden] * depth + [NUM_CLASSES]
    return {
        f"layer_{i}": {
            "w": (jax.random.normal(keys[i], (dims[i], dims[i + 1]),
                                    jnp.float32)
                  * (dims[i] ** -0.5)).astype(dtype),
            "b": jnp.zeros((dims[i + 1],), dtype),
        }
        for i in range(depth + 1)
    }


def mlp_logical_axes(params: dict) -> dict:
    return {name: {"w": ("embed", "mlp"), "b": ("mlp",)}
            for name in params}


def mlp_forward(params: dict, x: jax.Array) -> jax.Array:
    """x: [B, 784] → logits [B, 10]."""
    n = len(params)
    for i in range(n):
        p = params[f"layer_{i}"]
        x = x @ p["w"] + p["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x.astype(jnp.float32)


def init_cnn(rng: jax.Array, dtype=jnp.float32) -> dict:
    """LeNet-scale convnet (the reference TF example's architecture class)."""
    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def conv(key, shape):
        fan_in = shape[0] * shape[1] * shape[2]
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "conv1": {"w": conv(k1, (5, 5, 1, 32)), "b": jnp.zeros((32,), dtype)},
        "conv2": {"w": conv(k2, (5, 5, 32, 64)), "b": jnp.zeros((64,), dtype)},
        "fc1": {"w": (jax.random.normal(k3, (7 * 7 * 64, 256), jnp.float32)
                      * ((7 * 7 * 64) ** -0.5)).astype(dtype),
                "b": jnp.zeros((256,), dtype)},
        "fc2": {"w": (jax.random.normal(k4, (256, NUM_CLASSES), jnp.float32)
                      * (256 ** -0.5)).astype(dtype),
                "b": jnp.zeros((NUM_CLASSES,), dtype)},
    }


def cnn_forward(params: dict, x: jax.Array) -> jax.Array:
    """x: [B, 784] or [B, 28, 28, 1] → logits [B, 10]."""
    if x.ndim == 2:
        x = x.reshape(-1, 28, 28, 1)
    for name in ("conv1", "conv2"):
        p = params[name]
        x = jax.lax.conv_general_dilated(
            x, p["w"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + p["b"])
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    return (x @ params["fc2"]["w"] + params["fc2"]["b"]).astype(jnp.float32)


def nll_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return (logits.argmax(-1) == labels).mean()


def synthetic_batch(rng: jax.Array, batch_size: int) -> dict:
    """Deterministic, learnable synthetic MNIST: images are class-dependent
    patterns + noise, so a correct training loop visibly reduces loss."""
    k1, k2 = jax.random.split(rng)
    labels = jax.random.randint(k1, (batch_size,), 0, NUM_CLASSES)
    base = jax.nn.one_hot(labels, NUM_CLASSES)
    pattern = jnp.tile(base, (1, INPUT_DIM // NUM_CLASSES + 1))[:, :INPUT_DIM]
    noise = jax.random.normal(k2, (batch_size, INPUT_DIM)) * 0.3
    return {"image": pattern + noise, "label": labels}
