"""Framework-owned training driver: the loop every example hand-rolled.

``run_training`` fuses the device-prefetched input pipeline
(``tony_tpu.io.prefetch``), the instrumented train step
(``train.make_train_step``), periodic eval, and async orbax checkpointing
(``CheckpointManager.save`` never blocks the loop; the manager's
``wait_until_finished`` runs ONCE, at exit) into one driver — so the step
dispatch cadence is gated only by device compute, never by decode, H2D
copies, or checkpoint IO.

The loop observes ``tony_data_wait_seconds`` into the default metrics
registry: the host wall each iteration spent blocked on ``next(data)``.
That histogram is the direct input-boundedness signal — near zero means
the prefetcher stays ahead and training is device-bound; a per-step value
tracking decode cost means the pipeline is input-bound (raise the
prefetch depth, add reader processes, or move decode off the host). It
ships through the PR 2 metrics plane like every ``tony_*`` series
(heartbeat → coordinator → history server `/metrics`).

KeyboardInterrupt-safe by construction: the ``finally`` closes the data
iterator (stopping its ``tony-datafeed-*`` producer thread) before
waiting out pending checkpoint saves.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Any, Callable, Iterable

from tony_tpu import constants
from tony_tpu.runtime import goodput as goodput_mod
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.runtime import tracing

log = logging.getLogger(__name__)


class GangLostError(RuntimeError):
    """The step loop died because its GANG did, not because of user code:
    a collective transport or the distributed runtime failed under the
    step (a peer process was preempted mid-collective). Trainers should
    exit with :attr:`exit_code` — the executor recognizes it and, under
    elastic training, holds the report and relaunches the trainer against
    the resized gang instead of failing the job."""

    exit_code = constants.EXIT_GANG_LOST


#: conservative substrings identifying collective/distributed-runtime
#: failures across the transports this framework runs on (gloo on CPU,
#: libtpu/megascale on slices, the jax coordination service everywhere).
#: Deliberately NOT "unavailable"/"connection" alone — user code talks to
#: networks too; every marker here names a collectives layer.
_GANG_LOSS_MARKERS = (
    "gloo", "coordination service", "nccl", "megascale",
    "distributed service", "all-reduce failed", "all-gather failed",
    "collective", "preempted",
)


def _looks_like_gang_loss(e: BaseException) -> bool:
    seen: set[int] = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        msg = str(e).lower()
        if any(m in msg for m in _GANG_LOSS_MARKERS):
            return True
        e = e.__cause__ or e.__context__
    return False


#: data-wait buckets: the healthy value is ~0 (the prefetcher stays ahead
#: of the step loop), so sub-millisecond resolution matters more than the
#: minute-scale tail of the generic time ladder
DATA_WAIT_BUCKETS_S: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 5.0)


def run_training(step_fn: Callable[[Any, Any], tuple[Any, dict]],
                 state: Any, data: Iterable, steps: int, *,
                 start_step: int = 0, checkpoint=None,
                 eval_fn: Callable[[Any], Any] | None = None,
                 eval_every: int = 0, log_every: int = 20,
                 log_fn: Callable[[int, dict, Any], None] | None = None,
                 step_hook: Callable[[int], None] | None = None,
                 ) -> tuple[Any, dict]:
    """Drive ``steps - start_step`` train steps; returns (state, metrics).

    - ``step_fn(state, batch) -> (state, metrics)`` — any step with the
      ``make_train_step`` shape (donation-safe: the returned state is the
      live one).
    - ``data`` — an iterator of device-ready batches, normally a
      :class:`~tony_tpu.io.prefetch.DevicePrefetcher`; the loop closes it
      at exit if it has a ``close()``. A batch is fetched per step and
      the blocked wall observed into ``tony_data_wait_seconds``. If the
      iterator runs dry early the loop stops cleanly (finite datasets).
      ``None`` means this process consumes NO input feed — the shape of
      a cross-slice pipeline stage gang past stage 0, whose "input" is
      activations arriving on its tensor channel inside ``step_fn``;
      the loop then passes ``batch=None`` every step.
    - ``checkpoint`` — a :class:`~tony_tpu.models.checkpoint
      .CheckpointManager`; ``save(step+1, state)`` is offered every step
      (the manager's ``save_interval_steps`` decides), and the pipeline
      is never drained mid-run — only ``wait_until_finished`` at exit.
    - ``eval_fn(state)`` runs every ``eval_every`` steps; the most
      recent result rides in ``metrics["eval"]`` from then on, so log
      cadences that don't align with the eval cadence still surface it.
    - ``log_fn(step, metrics, batch)`` runs every ``log_every`` steps and
      on the final step (the batch is passed so callers can derive
      global examples/step from the assembled shape).
    - ``step_hook(step)`` runs first each iteration (profiler tracers).
    """
    if data is None:
        data = itertools.repeat(None)
    it = iter(data)
    reg = metrics_mod.get_default()
    wait_hist = reg.histogram(
        "tony_data_wait_seconds",
        help="host wall seconds the train loop spent blocked on data",
        buckets=DATA_WAIT_BUCKETS_S)
    metrics: dict = {}
    last_eval = None
    tracer = tracing.get_tracer()
    flight = tracing.get_flight()
    # Goodput attribution: each phase below ALSO lands in the process
    # ledger (data_wait/step/checkpoint/eval), which publishes to the
    # executor via TONY_GOODPUT_SPOOL and rides heartbeats from there.
    ledger = goodput_mod.get_ledger()
    try:
        for step in range(start_step, steps):
            if step_hook is not None:
                step_hook(step)
            # Per-step trace (head-sampled via tony.trace.sample-rate):
            # the step root with its phases as children — the causal
            # view behind the tony_data_wait/step-wall aggregates.
            with tracer.span("train.step", step=step) as step_span:
                t0 = time.perf_counter()
                try:
                    with ledger.enter("data_wait"):
                        batch = next(it)
                except StopIteration:
                    log.warning("data exhausted at step %d (wanted %d); "
                                "stopping early", step, steps)
                    break
                wait = time.perf_counter() - t0
                wait_hist.observe(wait)
                tracer.record_span("train.data_wait", wait,
                                   parent=step_span)
                try:
                    with tracer.span("train.dispatch"), \
                            ledger.enter("step"):
                        state, metrics = step_fn(state, batch)
                except Exception as e:
                    if _looks_like_gang_loss(e):
                        # the GANG failed, not the user's step: surface
                        # the distinguished error so elastic executors
                        # relaunch instead of charging a user failure
                        # (the finally below still flushes in-flight
                        # checkpoint saves — the checkpoint-sync step of
                        # a degraded resume). The flight ring dumps
                        # first: the step-level postmortem of WHAT died
                        # mid-collective survives the process.
                        log.warning(
                            "step %d failed with a collective/"
                            "distributed-runtime error — gang lost: %s",
                            step, e)
                        flight.record("gang_lost", step=step,
                                      error=str(e)[:500])
                        flight.dump("gang_lost", step=step)
                        raise GangLostError(str(e)) from e
                    raise
                if checkpoint is not None:
                    with tracer.span("train.checkpoint"), \
                            ledger.enter("checkpoint"):
                        checkpoint.save(step + 1, state)
                if (eval_fn is not None and eval_every > 0
                        and (step + 1) % eval_every == 0):
                    with tracer.span("train.eval"), ledger.enter("eval"):
                        last_eval = eval_fn(state)
                if last_eval is not None:
                    metrics = dict(metrics)
                    metrics["eval"] = last_eval
                if log_fn is not None and (step % max(1, log_every) == 0
                                           or step == steps - 1):
                    log_fn(step, metrics, batch)
    finally:
        close = getattr(data, "close", None)
        if close is not None:
            close()
        if checkpoint is not None:
            with ledger.enter("checkpoint"):
                checkpoint.wait_until_finished()
        # push the final breakdown to the executor bridge even if the
        # loop ends between throttled publishes
        ledger.publish()
    return state, metrics
