"""ResNet-50 (v1.5), functional, for the 8-worker data-parallel config.

BASELINE.json's progression names "ClusterSubmitter ResNet-50/ImageNet
(8 workers, data-parallel)"; this is that model, TPU-first:

- NHWC layout (TPU conv native), bf16 compute, f32 BN statistics.
- BatchNorm as explicit state (params vs. batch_stats pytrees). Under pjit
  with the batch sharded over dp, the mean/var reductions are GLOBAL —
  XLA inserts the cross-replica psum, giving sync-BN semantics for free
  (the reference's per-GPU local BN needed explicit sync to match).
- No flax dependency: plain pytrees keep the logical-axis sharding rules
  uniform with the transformer family.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

STAGE_SIZES = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


def _conv_init(key, shape, dtype):
    fan_out = shape[0] * shape[1] * shape[3]   # He init, fan-out (torch parity)
    return (jax.random.normal(key, shape, jnp.float32)
            * ((2.0 / fan_out) ** 0.5)).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_resnet(rng: jax.Array, depth: int = 50, num_classes: int = 1000,
                dtype=jnp.bfloat16) -> tuple[dict, dict]:
    """Returns (params, batch_stats)."""
    if depth not in STAGE_SIZES:
        raise ValueError(f"unsupported depth {depth}")
    sizes = STAGE_SIZES[depth]
    keys = iter(jax.random.split(rng, 200))
    params: dict = {"stem": {"conv": _conv_init(next(keys), (7, 7, 3, 64),
                                                dtype),
                             "bn": _bn_init(64, dtype)}}
    stats: dict = {"stem": _bn_stats(64)}
    in_c = 64
    for si, blocks in enumerate(sizes):
        width = 64 * (2 ** si)
        out_c = width * 4
        for bi in range(blocks):
            name = f"stage{si}_block{bi}"
            p = {
                "conv1": _conv_init(next(keys), (1, 1, in_c, width), dtype),
                "bn1": _bn_init(width, dtype),
                "conv2": _conv_init(next(keys), (3, 3, width, width), dtype),
                "bn2": _bn_init(width, dtype),
                "conv3": _conv_init(next(keys), (1, 1, width, out_c), dtype),
                "bn3": _bn_init(out_c, dtype),
            }
            s = {"bn1": _bn_stats(width), "bn2": _bn_stats(width),
                 "bn3": _bn_stats(out_c)}
            if bi == 0:
                p["proj"] = _conv_init(next(keys), (1, 1, in_c, out_c), dtype)
                p["proj_bn"] = _bn_init(out_c, dtype)
                s["proj_bn"] = _bn_stats(out_c)
            params[name] = p
            stats[name] = s
            in_c = out_c
    params["head"] = {
        "w": (jax.random.normal(next(keys), (in_c, num_classes), jnp.float32)
              * (in_c ** -0.5)).astype(dtype),
        "b": jnp.zeros((num_classes,), dtype),
    }
    return params, stats


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _batch_norm(x, p, s, train: bool, momentum=0.9, eps=1e-5):
    """Returns (y, new_stats). Reductions over (N,H,W) are global under pjit
    when N is dp-sharded — sync-BN by construction."""
    if train:
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) - mean) * inv
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new_s


def _bottleneck(x, p, s, stride: int, train: bool):
    new_s = {}
    shortcut = x
    if "proj" in p:
        shortcut = _conv(x, p["proj"], stride)
        shortcut, new_s["proj_bn"] = _batch_norm(shortcut, p["proj_bn"],
                                                 s["proj_bn"], train)
    h = _conv(x, p["conv1"])
    h, new_s["bn1"] = _batch_norm(h, p["bn1"], s["bn1"], train)
    h = jax.nn.relu(h)
    h = _conv(h, p["conv2"], stride)        # v1.5: stride on the 3x3
    h, new_s["bn2"] = _batch_norm(h, p["bn2"], s["bn2"], train)
    h = jax.nn.relu(h)
    h = _conv(h, p["conv3"])
    h, new_s["bn3"] = _batch_norm(h, p["bn3"], s["bn3"], train)
    return jax.nn.relu(h + shortcut), new_s


def forward(params: dict, stats: dict, x: jax.Array, depth: int = 50,
            train: bool = True) -> tuple[jax.Array, dict]:
    """x: [B, H, W, 3] → (logits f32, new_batch_stats)."""
    sizes = STAGE_SIZES[depth]
    new_stats: dict = {}
    h = _conv(x, params["stem"]["conv"], stride=2)
    h, new_stats["stem"] = _batch_norm(h, params["stem"]["bn"], stats["stem"],
                                       train)
    h = jax.nn.relu(h)
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    for si, blocks in enumerate(sizes):
        for bi in range(blocks):
            name = f"stage{si}_block{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            h, new_stats[name] = _bottleneck(h, params[name], stats[name],
                                             stride, train)
    h = h.mean(axis=(1, 2))                 # global average pool
    logits = (h @ params["head"]["w"] + params["head"]["b"])
    return logits.astype(jnp.float32), new_stats


def classification_loss(params, stats, batch, depth=50):
    logits, new_stats = forward(params, stats, batch["image"], depth,
                                train=True)
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.take_along_axis(logp, batch["label"][:, None],
                                axis=-1).mean()
    return loss, new_stats
