"""Model families: the workloads of BASELINE.json's progression configs.

MNIST MLP/CNN (north star), ResNet-50 (8-worker DP), BERT-base (16-worker
multi-host), and the flagship decoder LM exercising every parallel strategy
(DP/FSDP/TP/SP/CP/EP). All plain-pytree functional models annotated with the
logical sharding axes from tony_tpu.parallel.sharding.
"""

from tony_tpu.models import bert, mnist, resnet, transformer
from tony_tpu.models.loop import run_training
from tony_tpu.models.train import (
    TrainState,
    batch_sharding,
    default_optimizer,
    init_state,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "TrainState",
    "batch_sharding",
    "bert",
    "default_optimizer",
    "init_state",
    "make_eval_step",
    "make_train_step",
    "mnist",
    "resnet",
    "run_training",
    "transformer",
]
