"""Layered configuration: defaults → job file → CLI overrides → site file.

TPU-native rebuild of the reference's Hadoop-Configuration-based config stack
(reference: TonyClient.initTonyConf, tony-core/.../TonyClient.java:364-380 and
tony-default.xml). We keep the exact layering contract and the Hadoop
``<configuration><property>`` XML on-disk format so a TonY user's ``tony.xml``
files work unchanged, without depending on Hadoop: stdlib ElementTree parses
and writes it. ``key=value`` files and CLI ``--conf k=v`` overrides are also
accepted.

The frozen result is written as ``tony-final.xml`` and shipped to every
process (reference: TonyClient.java:186-192, TaskExecutor.init:167).
"""

from __future__ import annotations

import os
import re
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Iterable, Mapping

from tony_tpu.conf import keys as K

_MEMORY_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kKmMgGtT]?)[bB]?\s*$")
_MEM_MULT = {"": 1, "k": 1.0 / 1024, "m": 1, "g": 1024, "t": 1024 * 1024}


def parse_memory_string(value: str) -> int:
    """Parse '2g' / '2048m' / '2048' → MiB (reference: Utils.parseMemoryString,
    util/Utils.java:131-143)."""
    m = _MEMORY_RE.match(str(value))
    if not m:
        raise ValueError(f"cannot parse memory string: {value!r}")
    amount = float(m.group(1))
    mib = int(amount * _MEM_MULT[m.group(2).lower()])
    # Sub-MiB asks ("512k") round up to 1 MiB rather than truncating to zero.
    return 1 if mib == 0 and amount > 0 else mib


# Chips per slice host by TPU generation: v2/v3/v4/v5p boards carry 4 chips
# per host VM; v5e (v5litepod) and v6e carry 8. A topology's host count is
# ceil(chips / chips_per_host) — sub-host slices (e.g. v5e 2x2) still get
# one full host VM.
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4,
                   "v5litepod": 8, "v5e": 8, "v6e": 8}


def tpu_hosts_for(accelerator_type: str, topology: str) -> int | None:
    """Host-VM count of a slice, or None when it cannot be derived
    (unknown generation / unparseable topology)."""
    gen = accelerator_type.split("-")[0].lower()
    per_host = _CHIPS_PER_HOST.get(gen)
    if per_host is None or not topology:
        return None
    chips = 1
    for dim in topology.lower().split("x"):
        if not dim.isdigit():
            return None
        chips *= int(dim)
    return max(1, -(-chips // per_host))


@dataclass
class TaskRequest:
    """Per-job-type resource ask. Analog of TensorFlowContainerRequest
    (reference: tony-core/.../tensorflow/TensorFlowContainerRequest.java:16-56),
    extended with the north-star TPU resource dimensions."""
    job_type: str
    instances: int
    memory_mb: int = 2048
    vcores: int = 1
    gpus: int = 0
    tpus: int = 0                 # TPU chips per task (tony.{job}.tpus)
    tpu_topology: str = ""        # pod-slice topology, e.g. "2x4" (tony.{job}.tpu.topology)
    slices: int = 1               # pod slices (gangs) backing this job type (tony.{job}.slices)
    program: str = ""             # per-gang PROGRAM overriding the job command (tony.{job}.program)
    resources: str = ""           # extra localized resources (comma-sep paths)
    env: dict[str, str] = field(default_factory=dict)
    priority: int = 0             # unique per job type (Utils.java:330-336, YARN-7631)


class TonyConfig:
    """A flat ``str → str`` configuration with typed getters.

    Same data model as Hadoop ``Configuration`` (all values are strings), so
    behavior matches the reference everywhere it passes config across process
    boundaries via tony-final.xml.
    """

    def __init__(self, values: Mapping[str, str] | None = None,
                 load_defaults: bool = True) -> None:
        self._values: dict[str, str] = {}
        if load_defaults:
            self._values.update(K.DEFAULTS)
        if values:
            self._values.update({str(k): str(v) for k, v in values.items()})

    # -- mapping surface ----------------------------------------------------
    def __contains__(self, key: str) -> bool:
        return key in self._values

    def __iter__(self):
        return iter(self._values)

    def items(self):
        return self._values.items()

    def as_dict(self) -> dict[str, str]:
        return dict(self._values)

    def set(self, key: str, value: object) -> None:
        self._values[str(key)] = str(value)

    def update(self, other: Mapping[str, str]) -> None:
        for k, v in other.items():
            self.set(k, v)

    # -- typed getters ------------------------------------------------------
    def get(self, key: str, default: str | None = None) -> str | None:
        return self._values.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._values.get(key)
        return int(v) if v not in (None, "") else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._values.get(key)
        return float(v) if v not in (None, "") else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._values.get(key)
        if v in (None, ""):
            return default
        return str(v).strip().lower() in ("true", "1", "yes")

    def get_memory_mb(self, key: str, default: int = 0) -> int:
        v = self._values.get(key)
        return parse_memory_string(v) if v not in (None, "") else default

    def get_latency_buckets(self) -> tuple[float, ...]:
        """The latency histogram bucket ladder
        (``tony.metrics.latency-buckets``): parsed + validated bounds,
        or the built-in default ladder when unset. ValueError on a
        malformed spec (also enforced at :meth:`load`)."""
        from tony_tpu.runtime import metrics
        return metrics.parse_latency_buckets(
            self._values.get(K.METRICS_LATENCY_BUCKETS_KEY) or "")

    def get_list(self, key: str, default: Iterable[str] = ()) -> list[str]:
        v = self._values.get(key)
        if v in (None, ""):
            return list(default)
        return [s.strip() for s in str(v).split(",") if s.strip()]

    # -- layered loading ----------------------------------------------------
    @classmethod
    def load(cls, conf_file: str | None = None,
             cli_overrides: Mapping[str, str] | None = None,
             conf_dir: str | None = None) -> "TonyConfig":
        """defaults → conf_file (tony.xml) → CLI overrides → site file.

        Exactly the reference's precedence (TonyClient.initTonyConf:364-380):
        the site file (``$TONY_CONF_DIR/tony-site.xml``) wins last so cluster
        operators can pin values.
        """
        conf = cls()
        if conf_file is None and os.path.exists("tony.xml"):
            conf_file = "tony.xml"
        if conf_file:
            conf.update(read_conf_file(conf_file))
        if cli_overrides:
            conf.update(cli_overrides)
        conf_dir = conf_dir or os.environ.get("TONY_CONF_DIR")
        if conf_dir:
            site = os.path.join(conf_dir, "tony-site.xml")
            if os.path.exists(site):
                conf.update(read_conf_file(site))
        # a malformed latency-bucket ladder is refused HERE — discovered
        # at the first observe() it would take the serve loop down
        # instead of the operator's deploy
        conf.get_latency_buckets()
        return conf

    @classmethod
    def from_file(cls, path: str, load_defaults: bool = True) -> "TonyConfig":
        conf = cls(load_defaults=load_defaults)
        conf.update(read_conf_file(path))
        return conf

    @classmethod
    def from_xml_bytes(cls, data: bytes,
                       load_defaults: bool = True) -> "TonyConfig":
        """Parse configuration XML already in memory (e.g. fetched from
        remote storage by the history server)."""
        conf = cls(load_defaults=load_defaults)
        conf.update(_props_from_root(ET.fromstring(data)))
        return conf

    def write_xml(self, path: str) -> None:
        """Write Hadoop-style configuration XML (the tony-final.xml freeze)."""
        root = ET.Element("configuration")
        for k in sorted(self._values):
            prop = ET.SubElement(root, "property")
            ET.SubElement(prop, "name").text = k
            ET.SubElement(prop, "value").text = self._values[k]
        tree = ET.ElementTree(root)
        ET.indent(tree)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tree.write(path, encoding="unicode", xml_declaration=True)

    # -- job-type / task-request assembly -----------------------------------
    def job_types(self) -> list[str]:
        return K.discover_job_types(self._values)

    def _validate_topology(self, jt: str, instances: int,
                           topology: str, slices: int) -> None:
        """Fail at parse time when tony.{job}.instances cannot match the
        gang's host count: the TPU backend launches exactly one executor
        per slice host (``ssh --worker=<i>``), so a mismatch would surface
        much later as an opaque ssh error (the reference's analog is
        truncating bad resource asks up front, TonyClient.java:145-157).
        With tony.{job}.slices=N, instances spans all N gangs."""
        accel = self.get(K.TPU_ACCELERATOR_TYPE_KEY) or ""
        hosts = tpu_hosts_for(accel, topology)
        if hosts is None:
            return            # unknown generation or no topology: skip
        if instances != hosts * slices:
            per_slice = (f"{hosts} host{'s' if hosts != 1 else ''}"
                         f" per slice × {slices} slice"
                         f"{'s' if slices != 1 else ''}")
            raise ValueError(
                f"tony.{jt}.instances={instances} does not match "
                f"accelerator {accel!r} topology {topology!r} with "
                f"tony.{jt}.slices={slices}: that is {per_slice} (one "
                f"executor runs per slice host). Set "
                f"tony.{jt}.instances={hosts * slices}.")

    def task_requests(self) -> dict[str, TaskRequest]:
        """Build per-job-type resource asks from config.

        Mirror of Utils.parseContainerRequests (reference: util/Utils.java:
        314-340): regex-discovered job types, per-type resource keys, a unique
        priority per type so allocations can be matched back.
        """
        requests: dict[str, TaskRequest] = {}
        for priority, jt in enumerate(self.job_types()):
            instances = self.get_int(K.instances_key(jt), 0)
            if instances <= 0:
                continue
            env = {}
            for pair in self.get_list(K.env_key(jt)):
                if "=" in pair:
                    k, _, v = pair.partition("=")
                    env[k] = v
            slices = self.get_int(K.slices_key(jt),
                                  int(K.JOB_TYPE_DEFAULTS["slices"]))
            if slices < 1:
                raise ValueError(f"tony.{jt}.slices must be >= 1, "
                                 f"got {slices}")
            if instances % slices:
                raise ValueError(
                    f"tony.{jt}.instances={instances} is not divisible by "
                    f"tony.{jt}.slices={slices}; every slice gang has the "
                    f"same host count")
            topology = self.get(K.tpu_topology_key(jt), "") or ""
            if topology:
                self._validate_topology(jt, instances, topology, slices)
            requests[jt] = TaskRequest(
                job_type=jt,
                instances=instances,
                memory_mb=self.get_memory_mb(
                    K.memory_key(jt), parse_memory_string(K.JOB_TYPE_DEFAULTS["memory"])),
                vcores=self.get_int(K.vcores_key(jt), int(K.JOB_TYPE_DEFAULTS["vcores"])),
                gpus=self.get_int(K.gpus_key(jt), 0),
                tpus=self.get_int(K.tpus_key(jt), 0),
                tpu_topology=topology,
                slices=slices,
                program=self.get(K.program_key(jt), "") or "",
                resources=self.get(K.resources_key(jt), "") or "",
                env=env,
                priority=priority,
            )
        self._validate_dcn(requests)
        self._validate_pipeline(requests)
        return requests

    def pipeline_stages(self) -> list[str]:
        """Job types in PIPELINE STAGE ORDER (tony.pipeline.stages), []
        when the job declares no cross-slice pipeline."""
        return self.get_list(K.PIPELINE_STAGES_KEY)

    def pipeline_interleave(self) -> int:
        """Virtual stages per gang (tony.pipeline.interleave); 1 = the
        classic non-interleaved 1F1B schedule."""
        v = self.get_int(K.PIPELINE_INTERLEAVE_KEY, 1)
        if v < 1:
            raise ValueError(
                f"{K.PIPELINE_INTERLEAVE_KEY}={v} — interleave must be >= 1")
        return v

    def channel_compression(self) -> str:
        """On-the-wire codec for inter-gang tensor channels
        (tony.channel.compression): none, bf16, or int8."""
        codec = (self.get(K.CHANNEL_COMPRESSION_KEY, "none") or "none").strip()
        from ..channels.channel import CODECS
        if codec not in CODECS:
            raise ValueError(
                f"{K.CHANNEL_COMPRESSION_KEY}={codec!r} — must be one of "
                f"{CODECS}")
        return codec

    def _validate_pipeline(self, requests: dict[str, TaskRequest]) -> None:
        """Fail at parse time when the stage declaration cannot wire up:
        every stage must be a declared job type, stages must be distinct,
        and adjacent stages need matching host counts (the channel
        registry pairs tasks rank-to-rank across stages)."""
        self.pipeline_interleave()
        self.channel_compression()
        stages = self.pipeline_stages()
        if not stages:
            return
        if len(stages) < 2:
            raise ValueError(
                f"{K.PIPELINE_STAGES_KEY}={stages} — a pipeline needs at "
                f"least 2 stage job types")
        if len(set(stages)) != len(stages):
            raise ValueError(
                f"{K.PIPELINE_STAGES_KEY}={stages} repeats a job type; "
                f"each stage gang is a distinct type")
        for jt in stages:
            if jt not in requests:
                raise ValueError(
                    f"{K.PIPELINE_STAGES_KEY} names {jt!r} but "
                    f"tony.{jt}.instances is not declared (> 0)")
        counts = {jt: requests[jt].instances for jt in stages}
        if len(set(counts.values())) != 1:
            raise ValueError(
                f"pipeline stages have mismatched host counts {counts}; "
                f"the channel registry pairs stage tasks rank-to-rank, so "
                f"every stage needs the same tony.{{job}}.instances")

    def _validate_dcn(self, requests: dict[str, TaskRequest]) -> None:
        """Fail at parse time when tony.application.mesh.dcn cannot build a
        hybrid mesh: every task would otherwise provision real slices, stage,
        and only then die in runtime.mesh() (the fail-fast contract of
        _validate_topology)."""
        import math
        dcn = self.mesh_dcn_axes()
        if not dcn:
            return
        if any(v < 1 for v in dcn.values()):
            raise ValueError(
                f"tony.application.mesh.dcn sizes must be explicit positive "
                f"integers (no -1 inference): {dcn}")
        product = math.prod(dcn.values())
        multi = {jt: r.slices for jt, r in requests.items() if r.slices > 1}
        if not multi:
            raise ValueError(
                f"tony.application.mesh.dcn={dcn} is set but no job type "
                f"has tony.{{job}}.slices > 1 — dcn axes span slices")
        for jt, slices in multi.items():
            if slices != product:
                raise ValueError(
                    f"tony.application.mesh.dcn={dcn} spans {product} "
                    f"slices but tony.{jt}.slices={slices}; the dcn axis "
                    f"product must equal the slice count")

    def untracked_job_types(self) -> set[str]:
        """Job types excluded from completion counting (reference:
        Utils.isJobTypeTracked, util/Utils.java:475; default 'ps')."""
        return set(self.get_list(K.APPLICATION_UNTRACKED_KEY))

    def is_job_type_tracked(self, job_type: str) -> bool:
        return job_type not in self.untracked_job_types()

    def mesh_axes(self) -> dict[str, int]:
        """Parse tony.application.mesh: 'dp=2,tp=4' → {'dp': 2, 'tp': 4}.
        Strict — a malformed axis raises at submission time rather than
        surfacing as a bad mesh inside every task."""
        from tony_tpu.parallel.mesh import parse_mesh_string
        return parse_mesh_string(self.get(K.APPLICATION_MESH_KEY, "") or "")

    def mesh_dcn_axes(self) -> dict[str, int]:
        """Parse tony.application.mesh.dcn — the axes laid out ACROSS slices
        (data-center network) for multi-slice jobs; {} for single-slice."""
        from tony_tpu.parallel.mesh import parse_mesh_string
        return parse_mesh_string(
            self.get(K.APPLICATION_MESH_DCN_KEY, "") or "")


def read_conf_file(path: str) -> dict[str, str]:
    """Read a config file: Hadoop-style XML or flat ``key=value`` lines."""
    if path.endswith(".xml"):
        return _read_xml(path)
    return _read_kv(path)


def _read_xml(path: str) -> dict[str, str]:
    return _props_from_root(ET.parse(path).getroot())


def _props_from_root(root) -> dict[str, str]:
    out: dict[str, str] = {}
    for prop in root.iter("property"):
        name = prop.findtext("name")
        value = prop.findtext("value")
        if name is not None:
            out[name.strip()] = (value or "").strip()
    return out


def _read_kv(path: str) -> dict[str, str]:
    out: dict[str, str] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            k, sep, v = line.partition("=")
            if sep:
                out[k.strip()] = v.strip()
    return out


def parse_cli_confs(pairs: Iterable[str]) -> dict[str, str]:
    """Parse repeated ``--conf k=v`` flags (reference: Utils.parseKeyValue,
    util/Utils.java:207)."""
    out: dict[str, str] = {}
    for pair in pairs:
        k, sep, v = pair.partition("=")
        if not sep:
            raise ValueError(f"--conf expects key=value, got {pair!r}")
        out[k.strip()] = v.strip()
    return out
