"""The ``tony.*`` configuration key namespace and its defaults.

TPU-native analog of the reference's ``TonyConfigurationKeys.java`` (reference:
tony-core/src/main/java/com/linkedin/tony/TonyConfigurationKeys.java:1-206) and
``tony-default.xml`` (tony-core/src/main/resources/tony-default.xml). The two
are kept in lock-step here by construction — every ``*_KEY`` constant must have
an entry in ``DEFAULTS`` (or be a documented dynamic-key builder), enforced by
``tests/test_config.py::test_keys_defaults_bijection`` (mirror of the
reference's ``TestTonyConfigurationFields.java:15-63``).

Additions over the reference (the "north star" of BASELINE.json): TPU topology
is a first-class per-job-type resource (``tony.{job}.tpus``,
``tony.{job}.tpu.topology``) and mesh-axis layout is declarative config
(``tony.application.mesh``).
"""

from __future__ import annotations

import re

TONY_PREFIX = "tony."

# ---------------------------------------------------------------------------
# Application-level keys (TonyConfigurationKeys.java "tony.application.*")
# ---------------------------------------------------------------------------
APPLICATION_NAME_KEY = "tony.application.name"
APPLICATION_FRAMEWORK_KEY = "tony.application.framework"          # jax|tensorflow|pytorch
APPLICATION_SINGLE_NODE_KEY = "tony.application.single-node"
APPLICATION_TIMEOUT_KEY = "tony.application.timeout"              # ms; 0 = none
APPLICATION_NODE_LABEL_KEY = "tony.application.node-label"
APPLICATION_PREPROCESS_KEY = "tony.application.enable-preprocess"
APPLICATION_SECURITY_KEY = "tony.application.security.enabled"
# Control-plane TLS: per-job self-signed cert at submit, gRPC over TLS,
# clients pinned to the job cert (rpc/tls.py; the reference's
# HTTPS-keystore/kerberos analog — TonyConfigurationKeys.java:55-68).
TLS_ENABLED_KEY = "tony.tls.enabled"
APPLICATION_MESH_KEY = "tony.application.mesh"                    # e.g. "dp=2,tp=4" (TPU-native)
# DCN (cross-slice) mesh axes for multi-slice jobs, e.g. "dp=2": these axes
# are laid out ACROSS slices (slow network), tony.application.mesh axes
# within a slice (ICI). Only meaningful when some tony.{job}.slices > 1.
APPLICATION_MESH_DCN_KEY = "tony.application.mesh.dcn"
APPLICATION_UNTRACKED_KEY = "tony.application.untracked.jobtypes" # e.g. "ps"

# ---------------------------------------------------------------------------
# Coordinator keys ("tony.am.*" in the reference; name kept for compat)
# ---------------------------------------------------------------------------
AM_RETRY_COUNT_KEY = "tony.am.retry-count"
AM_MEMORY_KEY = "tony.am.memory"
AM_VCORES_KEY = "tony.am.vcores"
AM_GPUS_KEY = "tony.am.gpus"

# Coordinator crash recovery ("tony.coordinator.*"): the write-ahead
# session journal + executor re-attach plane. A restarted coordinator
# (tony.am.retry-count relaunches on the SAME job dir) replays the
# journal, re-adopts live slices, and serves a bumped incarnation id;
# executors ride out the outage instead of suiciding.
# ---------------------------------------------------------------------------
# How long an executor keeps retrying an unreachable coordinator before
# giving up (exit 75, the lost-coordinator suicide). Also the liveness
# grace a restarted coordinator grants re-adopted tasks on top of the
# normal expiry window. 0 restores the old fail-fast behavior (five
# consecutive heartbeat failures are fatal).
COORDINATOR_REATTACH_TIMEOUT_KEY = "tony.coordinator.reattach-timeout-ms"
# Write the fsync'd session journal (<job_dir>/session.journal). Off
# means a coordinator crash loses the session exactly as before.
COORDINATOR_JOURNAL_ENABLED_KEY = "tony.coordinator.journal-enabled"

# ---------------------------------------------------------------------------
# Cluster-daemon keys ("tony.daemon.*") — the persistent multi-tenant
# scheduler (docs/cluster.md). The daemon owns a pool of slices and a
# job queue; these bound the queue, fence preemptions, and reap idle
# warm slices.
# ---------------------------------------------------------------------------
# Max QUEUED jobs; submissions past this are rejected at the wire.
DAEMON_QUEUE_LIMIT_KEY = "tony.daemon.queue-limit"
# Max concurrently GRANTED slices per user (gang counted at grant
# time). 0 = unlimited.
DAEMON_USER_QUOTA_KEY = "tony.daemon.user-quota"
# Checkpoint-fence grace for an induced shrink: the victim gets this
# long to commit its fence before the slices are drained.
DAEMON_PREEMPTION_GRACE_MS_KEY = "tony.daemon.preemption-grace-ms"
# A free slice idle longer than this is reaped (real teardown) instead
# of staying warm. 0 = never reap.
DAEMON_POOL_IDLE_REAP_MS_KEY = "tony.daemon.pool-idle-reap-ms"

# ---------------------------------------------------------------------------
# Task keys ("tony.task.*")
# ---------------------------------------------------------------------------
TASK_EXECUTOR_PYTHON_OPTS_KEY = "tony.task.executor.python-opts"  # jvm-opts analog
TASK_HEARTBEAT_INTERVAL_KEY = "tony.task.heartbeat-interval-ms"
TASK_MAX_MISSED_HEARTBEATS_KEY = "tony.task.max-missed-heartbeats"
# In-session single-task relaunch budget for failed NON-CHIEF tracked
# tasks (the reference kills the whole job and marks per-task restart
# TODO — TonyApplicationMaster.java:1158-1159). Suited to loosely-coupled
# jobs (independent workers, PS/worker TF): a jax.distributed collective
# gang cannot absorb a single-process restart mid-run.
TASK_RESTART_COUNT_KEY = "tony.task.restart-count"
TASK_REGISTRATION_TIMEOUT_KEY = "tony.task.registration-timeout-ms"
TASK_EXECUTION_TIMEOUT_KEY = "tony.task.execution-timeout-ms"
TASK_PROFILE_ENABLED_KEY = "tony.task.profile.enabled"            # per-host jax.profiler
TASK_PROFILE_DIR_KEY = "tony.task.profile.dir"                    # trace output root

# ---------------------------------------------------------------------------
# Launch fan-out ("tony.launch.*"): how many backend launch_task calls the
# coordinator keeps in flight at once during schedule_tasks. Provisioning
# and staging a gang takes minutes on real TPU fleets; the backend's
# claim-or-wait gang logic already tolerates concurrent callers, so a
# multi-gang job's bring-up wall is max-of-gangs instead of sum-of-gangs.
# 1 restores the old serial behavior.
# ---------------------------------------------------------------------------
LAUNCH_MAX_CONCURRENT_KEY = "tony.launch.max-concurrent"

# ---------------------------------------------------------------------------
# Elastic training ("tony.elastic.*"): on gang loss to preemption (backend
# report or liveness expiry), keep the session alive — detach the lost
# gang, bump the cluster-spec epoch so survivors checkpoint-sync and
# re-handshake over the shrunk world, and (optionally) reprovision the
# lost capacity in the background and grow back at the next barrier.
# Off by default: stop-the-world session re-runs (tony.tpu.preemption-
# retries) remain the behavior unless a job opts in.
# ---------------------------------------------------------------------------
ELASTIC_ENABLED_KEY = "tony.elastic.enabled"
# Minimum surviving TRACKED tasks required to degrade instead of falling
# back to the stop-the-world preemption retry (each tracked job type must
# also keep >= 1 task, and the chief's gang is never detachable).
ELASTIC_MIN_TASKS_KEY = "tony.elastic.min-tasks"
# How many shrink EVENTS (gang-loss epochs, not individual tasks) the
# session absorbs elastically; losses beyond it fall back to the
# stop-the-world preemption budget.
ELASTIC_BUDGET_KEY = "tony.elastic.budget"
# Reprovision replacement capacity in the background and expand the mesh
# back once every replacement has registered.
ELASTIC_REGROW_KEY = "tony.elastic.regrow"
# Delay before the background relaunch of lost tasks (real capacity takes
# time to come back; the first re-create usually hits the same stockout).
ELASTIC_REGROW_BACKOFF_KEY = "tony.elastic.regrow-backoff-ms"
# How long losses are accumulated before ONE shrink epoch is cut: a
# preempted slice surfaces as several per-task completion events (and
# possibly a liveness expiry racing them), and resyncing the survivors
# once per event would thrash the barrier.
ELASTIC_QUIESCE_KEY = "tony.elastic.quiesce-ms"

# ---------------------------------------------------------------------------
# Cross-slice MPMD pipeline ("tony.pipeline.*"): job types in STAGE ORDER,
# e.g. "stage0,stage1" — each named job type's gang runs one pipeline
# stage of the model, its own PROGRAM (tony.{job}.program), and exchanges
# activations/cotangents with its neighbor stages over typed inter-gang
# tensor channels (tony_tpu.channels) whose endpoints the coordinator's
# channel registry assigns at gang-barrier release. Empty = no pipeline.
# ---------------------------------------------------------------------------
PIPELINE_STAGES_KEY = "tony.pipeline.stages"
# Virtual stages per gang (interleaved/looped 1F1B): chunk j on gang s is
# virtual stage j*S+s, shrinking the pipeline bubble ~1/v at the cost of
# ring channel traffic. 1 = classic non-interleaved schedule.
PIPELINE_INTERLEAVE_KEY = "tony.pipeline.interleave"
# On-the-wire codec for inter-gang tensor channels: "none" (raw bytes,
# bit-exact), "bf16", or "int8" (per-tensor-scale quantization). Both
# ends of every channel must agree — negotiated at the channel handshake.
CHANNEL_COMPRESSION_KEY = "tony.channel.compression"

# ---------------------------------------------------------------------------
# Metrics plane ("tony.metrics.*" — the TaskMonitor/MetricsRpc analog):
# executors piggyback a registry snapshot on every heartbeat; the
# coordinator folds its per-task last-snapshot table into a
# METRICS_SNAPSHOT jhist event on this cadence (0 disables the periodic
# emit; the final at-stop snapshot still lands).
# ---------------------------------------------------------------------------
METRICS_SNAPSHOT_INTERVAL_KEY = "tony.metrics.snapshot-interval-ms"

# ---------------------------------------------------------------------------
# Distributed tracing ("tony.trace.*") + crash flight recorder: producers
# record causal spans into a per-process ring (runtime/tracing.py), span
# batches piggyback on heartbeats, the coordinator folds them into
# TRACE_SPAN jhist events (clock-offset-corrected), and the history
# server exports GET /api/jobs/<id>/trace as Chrome-trace JSON.
# ---------------------------------------------------------------------------
# Head-sampling rate for fine-grained trace roots (per-request,
# per-step): 0 disables them, 1.0 records everything. Coarse spans (job
# lifecycle, bring-up, incidents) are always-on regardless.
TRACE_SAMPLE_RATE_KEY = "tony.trace.sample-rate"
# Bounded per-process span storage: both the pending-ship buffer and the
# recent-spans ring the flight recorder dumps.
TRACE_RING_KEY = "tony.trace.ring-size"
# Events kept in each process's flight-recorder ring (the postmortem
# dump's depth).
FLIGHT_RING_KEY = "tony.flight-recorder.ring-size"

# ---------------------------------------------------------------------------
# Goodput ledger + straggler detector ("tony.goodput.*" /
# "tony.straggler.*"): per-task wall-clock attribution rides heartbeats
# (runtime/goodput.py), the coordinator folds it into GOODPUT jhist
# events and compares per-task step walls across each gang.
# ---------------------------------------------------------------------------
# Detector tick + GOODPUT aggregation window. Each window the coordinator
# updates per-task step-wall EWMAs from the ledger deltas.
GOODPUT_WINDOW_MS_KEY = "tony.goodput.window-ms"
# A task is suspected when its step-wall EWMA exceeds the gang median by
# this factor ...
STRAGGLER_FACTOR_KEY = "tony.straggler.factor"
# ... for this many consecutive windows (hysteresis against one-off
# checkpoint or GC pauses).
STRAGGLER_WINDOWS_KEY = "tony.straggler.windows"

# ---------------------------------------------------------------------------
# Chief designation (TonyConfigurationKeys: chief name/index)
# ---------------------------------------------------------------------------
CHIEF_REGEX_KEY = "tony.application.chief.name"
CHIEF_INDEX_KEY = "tony.application.chief.index"

# ---------------------------------------------------------------------------
# History / events ("tony.history.*")
# ---------------------------------------------------------------------------
HISTORY_LOCATION_KEY = "tony.history.location"
HISTORY_INTERMEDIATE_KEY = "tony.history.intermediate"
HISTORY_FINISHED_KEY = "tony.history.finished"
HISTORY_RETENTION_SECONDS_KEY = "tony.history.retention-seconds"
HISTORY_SERVER_PORT_KEY = "tony.history.server.port"
# Bind address: loopback by default — job configs can embed env/paths, so
# exposing the server beyond the host is an explicit operator decision
# (the reference's analog is its keytab login, tony-history-server/app/
# hadoop/Security.java).
HISTORY_SERVER_BIND_KEY = "tony.history.server.bind"
# Bearer token required on every route except /healthz when set (directly
# or via a chmod-600 file; the file wins).
HISTORY_SERVER_TOKEN_KEY = "tony.history.server.token"
HISTORY_SERVER_TOKEN_FILE_KEY = "tony.history.server.token-file"
# HTTPS for the history server (reference: tony.https.* keystore keys,
# TonyConfigurationKeys.java:55-68): PEM cert + key paths; both set = TLS.
HISTORY_SERVER_TLS_CERT_KEY = "tony.history.server.tls-cert"
HISTORY_SERVER_TLS_KEY_KEY = "tony.history.server.tls-key"

# ---------------------------------------------------------------------------
# Backend / scheduler ("tony.scheduler.*" — new layer; the reference hardwires
# YARN, we make the slice provider pluggable: local | tpu)
# ---------------------------------------------------------------------------
SCHEDULER_BACKEND_KEY = "tony.scheduler.backend"
TPU_PROJECT_KEY = "tony.tpu.project"
TPU_ZONE_KEY = "tony.tpu.zone"
TPU_ACCELERATOR_TYPE_KEY = "tony.tpu.accelerator-type"
TPU_RUNTIME_VERSION_KEY = "tony.tpu.runtime-version"
TPU_PREEMPTIBLE_KEY = "tony.tpu.preemptible"
TPU_PROVISION_TIMEOUT_KEY = "tony.tpu.provision-timeout-ms"
# Slice preemption is infrastructure, not user failure: retried from a
# separate budget so tony.am.retry-count keeps meaning "user-failure retries"
# (SURVEY.md §7 hard part (d): distinguish preemption from user crash).
TPU_PREEMPTION_RETRIES_KEY = "tony.tpu.preemption-retries"
# How often the backend refreshes slice state via the cloud API (gcloud
# describe); completion polling reads the cached state.
TPU_STATE_REFRESH_KEY = "tony.tpu.state-refresh-ms"
# Transient-infrastructure retries inside ONE provisioning attempt (quota
# backoff on create, dropped ssh during staging) — distinct from the
# gang-level preemption budget, which reprovisions a LOST slice.
TPU_CREATE_RETRIES_KEY = "tony.tpu.create-retries"
TPU_STAGE_RETRIES_KEY = "tony.tpu.stage-retries"
TPU_RETRY_BACKOFF_KEY = "tony.tpu.retry-backoff-ms"

# ---------------------------------------------------------------------------
# Staging / storage ("tony.staging.*"; HDFS-dir analog)
# ---------------------------------------------------------------------------
STAGING_DIR_KEY = "tony.staging.dir"
# Set by the client when the staging root is remote (gs://): the full job
# dir was pushed here and slice hosts localize from it.
REMOTE_JOB_DIR_KEY = "tony.staging.remote-job-dir"
# Per-job GCS identity (the analog of the reference's per-filesystem
# delegation tokens — tony.other.namenodes, TonyConfigurationKeys.java:29,
# fetched in TonyClient.java:509): the client mints a short-lived access
# token for this service account (gcloud impersonation) and every gsutil
# call in the job — client staging, coordinator history writes, executor
# data reads — runs under it instead of ambient host credentials.
# Either ONE service account (a single identity for every bucket) or
# comma-separated "bucket=sa" pairs ("*" = default identity) — the
# reference's namenode LIST, one delegation token per filesystem: a job
# can read data from one project's bucket and write history to another's
# under distinct identities; calls to a bucket with no mapped identity
# fail rather than fall back to ambient credentials.
GCS_SERVICE_ACCOUNT_KEY = "tony.gcs.service-account"
# Renewal period for the scoped token (impersonation tokens expire ~1h):
# the client re-mints on this cadence and pushes via renewGcsToken; the
# coordinator fans the replacement out on heartbeat responses.
GCS_TOKEN_RENEW_MS_KEY = "tony.gcs.token-renew-ms"
SRC_DIR_KEY = "tony.application.src-dir"                          # "" = no implicit staging
PYTHON_VENV_KEY = "tony.application.python-venv"
PYTHON_BINARY_PATH_KEY = "tony.application.python-binary-path"
CONTAINER_LOG_DIR_KEY = "tony.container.log-dir"

# ---------------------------------------------------------------------------
# Docker passthrough (TonyClient.java:340-349)
# ---------------------------------------------------------------------------
DOCKER_ENABLED_KEY = "tony.docker.enabled"
DOCKER_IMAGE_KEY = "tony.docker.image"

# ---------------------------------------------------------------------------
# Serving router ("tony.router.*"): the front door's health-check knobs,
# lifted from hardcoded constants so fleet simulations can run at
# accelerated time (milliseconds of ping cadence against hundreds of
# simulated replicas) without patching the router.
# ---------------------------------------------------------------------------
# Cadence of the router's STATS health ping per replica link.
ROUTER_HEALTH_INTERVAL_MS_KEY = "tony.router.health-interval-ms"
# Consecutive UNANSWERED pings before a connected-but-hung replica is
# marked down (unanswered pings, not wall-clock staleness — the router's
# own scheduling stalls must not down healthy replicas).
ROUTER_MAX_MISSED_PINGS_KEY = "tony.router.max-missed-pings"

# ---------------------------------------------------------------------------
# Serving engine QoS ("tony.serve.*"): SLO-tiered admission. Every
# request carries a class (interactive | standard | batch, absent =
# standard); the engine keeps one admission queue per class, reserves
# decode-slot floors per class, preempts batch rows for interactive
# admissions, and sheds standard/batch load past a bounded queue depth
# with a BUSY frame instead of growing the queue.
# ---------------------------------------------------------------------------
# Decode-slot floor per class: a free slot is handed to another class
# only if enough free slots remain to cover this class's unmet floor.
# Floors are soft capacity reservations (never exceed the batcher's
# slot count — oversized floors are clamped at engine construction).
SERVE_SLOTS_INTERACTIVE_KEY = "tony.serve.slots.interactive"
SERVE_SLOTS_STANDARD_KEY = "tony.serve.slots.standard"
SERVE_SLOTS_BATCH_KEY = "tony.serve.slots.batch"
# Total queued admissions (all classes) past which a standard/batch
# submission is shed with BUSY. Interactive admissions always queue —
# their overload story is the floor + preemption, not shedding. 0
# disables shedding (the pre-QoS unbounded queue).
SERVE_MAX_QUEUE_DEPTH_KEY = "tony.serve.max-queue-depth"
# The retry_after_ms hint a BUSY frame carries.
SERVE_BUSY_RETRY_MS_KEY = "tony.serve.busy-retry-ms"

# Latency histogram bucket upper bounds (seconds), comma-separated and
# strictly increasing — the buckets every tony_*_seconds histogram
# (TTFT, inter-token, placement...) observes into. The default spans
# 1ms..60s log-ish; interactive sub-100ms SLO work wants finer low-end
# buckets. Malformed/non-monotonic bounds are refused at config load.
METRICS_LATENCY_BUCKETS_KEY = "tony.metrics.latency-buckets"

# ---------------------------------------------------------------------------
# Weight distribution plane ("tony.weights.*"): the warm scale-up path —
# content-addressed weight + compiled-program artifacts shipped peer-to-peer
# over the channel plane (tony_tpu/serving/weightstore.py) instead of N
# replicas each cold-loading from storage.
# ---------------------------------------------------------------------------
# Chunk size for the resumable byte-blob lane a weight ship rides (each
# chunk is one seq-numbered channel frame, so a disconnect mid-ship
# resumes at the first unacked chunk instead of restarting the blob).
WEIGHTS_CHUNK_BYTES_KEY = "tony.weights.chunk-bytes"
# Ship int8-quantized weights on the wire (digest is computed over the
# as-served dequantized tree on BOTH ends, so a lossy wire cannot land
# silently — mismatches are refused). Only safe when the serving stack
# dequantizes back to the exact shipped version; leave false otherwise.
WEIGHTS_QUANTIZE_WIRE_KEY = "tony.weights.quantize-wire"
# Directory for the shippable JAX persistent compilation cache ("" =
# don't attach one). Shipping it alongside weights lands replicas
# pre-traced: first token needs no XLA compile.
WEIGHTS_COMPILE_CACHE_DIR_KEY = "tony.weights.compile-cache-dir"

# ---------------------------------------------------------------------------
# Defaults registry — the tony-default.xml analog. One entry per static key.
# Values are strings, exactly like Hadoop Configuration; typed getters on
# TonyConfig parse them.
# ---------------------------------------------------------------------------
DEFAULTS: dict[str, str] = {
    APPLICATION_NAME_KEY: "tony-tpu-application",
    APPLICATION_FRAMEWORK_KEY: "jax",
    APPLICATION_SINGLE_NODE_KEY: "false",
    APPLICATION_TIMEOUT_KEY: "0",
    APPLICATION_NODE_LABEL_KEY: "",
    APPLICATION_PREPROCESS_KEY: "false",
    APPLICATION_SECURITY_KEY: "false",
    TLS_ENABLED_KEY: "false",
    APPLICATION_MESH_KEY: "",
    APPLICATION_MESH_DCN_KEY: "",
    APPLICATION_UNTRACKED_KEY: "ps",
    AM_RETRY_COUNT_KEY: "0",
    AM_MEMORY_KEY: "2g",
    AM_VCORES_KEY: "1",
    AM_GPUS_KEY: "0",
    COORDINATOR_REATTACH_TIMEOUT_KEY: "30000",
    COORDINATOR_JOURNAL_ENABLED_KEY: "true",
    DAEMON_QUEUE_LIMIT_KEY: "1000",
    DAEMON_USER_QUOTA_KEY: "0",
    DAEMON_PREEMPTION_GRACE_MS_KEY: "5000",
    DAEMON_POOL_IDLE_REAP_MS_KEY: "300000",
    TASK_EXECUTOR_PYTHON_OPTS_KEY: "",
    TASK_HEARTBEAT_INTERVAL_KEY: "1000",
    TASK_MAX_MISSED_HEARTBEATS_KEY: "25",
    TASK_RESTART_COUNT_KEY: "0",
    TASK_REGISTRATION_TIMEOUT_KEY: "300000",
    TASK_EXECUTION_TIMEOUT_KEY: "0",
    TASK_PROFILE_ENABLED_KEY: "false",
    TASK_PROFILE_DIR_KEY: "",
    LAUNCH_MAX_CONCURRENT_KEY: "8",
    ELASTIC_ENABLED_KEY: "false",
    ELASTIC_MIN_TASKS_KEY: "1",
    ELASTIC_BUDGET_KEY: "3",
    ELASTIC_REGROW_KEY: "true",
    ELASTIC_REGROW_BACKOFF_KEY: "1000",
    ELASTIC_QUIESCE_KEY: "300",
    PIPELINE_STAGES_KEY: "",
    PIPELINE_INTERLEAVE_KEY: "1",
    CHANNEL_COMPRESSION_KEY: "none",
    METRICS_SNAPSHOT_INTERVAL_KEY: "5000",
    GOODPUT_WINDOW_MS_KEY: "2000",
    STRAGGLER_FACTOR_KEY: "2.0",
    STRAGGLER_WINDOWS_KEY: "3",
    TRACE_SAMPLE_RATE_KEY: "1.0",
    TRACE_RING_KEY: "2048",
    FLIGHT_RING_KEY: "256",
    CHIEF_REGEX_KEY: "^(chief|master)$",
    CHIEF_INDEX_KEY: "0",
    HISTORY_LOCATION_KEY: "",
    HISTORY_INTERMEDIATE_KEY: "",
    HISTORY_FINISHED_KEY: "",
    HISTORY_RETENTION_SECONDS_KEY: "2592000",
    HISTORY_SERVER_PORT_KEY: "19886",
    HISTORY_SERVER_BIND_KEY: "127.0.0.1",
    HISTORY_SERVER_TOKEN_KEY: "",
    HISTORY_SERVER_TOKEN_FILE_KEY: "",
    HISTORY_SERVER_TLS_CERT_KEY: "",
    HISTORY_SERVER_TLS_KEY_KEY: "",
    SCHEDULER_BACKEND_KEY: "local",
    TPU_PROJECT_KEY: "",
    TPU_ZONE_KEY: "",
    TPU_ACCELERATOR_TYPE_KEY: "",
    TPU_RUNTIME_VERSION_KEY: "tpu-ubuntu2204-base",
    TPU_PREEMPTIBLE_KEY: "false",
    TPU_PROVISION_TIMEOUT_KEY: "600000",
    TPU_PREEMPTION_RETRIES_KEY: "3",
    TPU_STATE_REFRESH_KEY: "10000",
    TPU_CREATE_RETRIES_KEY: "3",
    TPU_STAGE_RETRIES_KEY: "2",
    TPU_RETRY_BACKOFF_KEY: "5000",
    STAGING_DIR_KEY: "",
    REMOTE_JOB_DIR_KEY: "",
    GCS_SERVICE_ACCOUNT_KEY: "",
    GCS_TOKEN_RENEW_MS_KEY: "2700000",
    SRC_DIR_KEY: "",
    PYTHON_VENV_KEY: "",
    PYTHON_BINARY_PATH_KEY: "",
    CONTAINER_LOG_DIR_KEY: "",
    DOCKER_ENABLED_KEY: "false",
    DOCKER_IMAGE_KEY: "",
    ROUTER_HEALTH_INTERVAL_MS_KEY: "500",
    ROUTER_MAX_MISSED_PINGS_KEY: "3",
    SERVE_SLOTS_INTERACTIVE_KEY: "0",
    SERVE_SLOTS_STANDARD_KEY: "0",
    SERVE_SLOTS_BATCH_KEY: "0",
    SERVE_MAX_QUEUE_DEPTH_KEY: "128",
    SERVE_BUSY_RETRY_MS_KEY: "250",
    METRICS_LATENCY_BUCKETS_KEY: "",
    WEIGHTS_CHUNK_BYTES_KEY: "8388608",
    WEIGHTS_QUANTIZE_WIRE_KEY: "false",
    WEIGHTS_COMPILE_CACHE_DIR_KEY: "",
}

# ---------------------------------------------------------------------------
# Per-job-type dynamic keys. Job types are DISCOVERED from config by regex,
# exactly like the reference (TonyConfigurationKeys.java:136 regex
# ``tony\.([a-z]+)\.instances``; Utils.parseContainerRequests:314-340). Any
# ``tony.<type>.instances`` in config creates a task group — no code change.
# ---------------------------------------------------------------------------
INSTANCES_REGEX = re.compile(r"^tony\.([a-z][a-z0-9]*)\.instances$")

# Keys that never denote a job type even though they match the shape.
NON_JOB_TYPE_WORDS = frozenset({"application", "task", "am", "history", "tpu",
                                "scheduler", "staging", "docker", "container",
                                "launch", "elastic", "metrics", "pipeline",
                                "channel", "trace", "router", "fleet",
                                "coordinator", "weights", "goodput",
                                "straggler", "daemon", "serve"})


def instances_key(job_type: str) -> str:
    return f"tony.{job_type}.instances"


def memory_key(job_type: str) -> str:
    return f"tony.{job_type}.memory"


def vcores_key(job_type: str) -> str:
    return f"tony.{job_type}.vcores"


def gpus_key(job_type: str) -> str:
    return f"tony.{job_type}.gpus"


def tpus_key(job_type: str) -> str:
    """North-star addition: TPU chips per task of this job type."""
    return f"tony.{job_type}.tpus"


def tpu_topology_key(job_type: str) -> str:
    """North-star addition: pod-slice topology for this job type, e.g. '4x4'."""
    return f"tony.{job_type}.tpu.topology"


def slices_key(job_type: str) -> str:
    """Multi-slice scale-out: number of pod slices (gangs) backing this job
    type. tony.{job}.instances spans ALL slices (instances = slices ×
    hosts-per-slice); collectives ride ICI within a slice and DCN across
    (the per-job-type scaling analog of Utils.parseContainerRequests:314-340,
    where the unit of scaling was one container instead of one gang)."""
    return f"tony.{job_type}.slices"


def program_key(job_type: str) -> str:
    """Per-gang PROGRAM: the user command THIS job type's executors run,
    overriding the job-wide command — how an MPMD pipeline job gives each
    stage gang its own trainer entry point (one model, different stage
    programs on disjoint device sets)."""
    return f"tony.{job_type}.program"


def resources_key(job_type: str) -> str:
    return f"tony.{job_type}.resources"


def env_key(job_type: str) -> str:
    return f"tony.{job_type}.env"


# Per-job-type defaults applied when the dynamic key is absent
# (tony-default.xml ships worker/ps defaults; we do the same via this table).
JOB_TYPE_DEFAULTS: dict[str, str] = {
    "instances": "0",
    "memory": "2g",
    "vcores": "1",
    "gpus": "0",
    "tpus": "0",
    "tpu.topology": "",
    "slices": "1",
    "program": "",
    "resources": "",
    "env": "",
}


def discover_job_types(conf_dict: dict[str, str]) -> list[str]:
    """Find all job types declared in a flat config mapping.

    Mirror of Utils.parseContainerRequests' regex-driven discovery
    (reference: tony-core/src/main/java/com/linkedin/tony/util/Utils.java:314-340).
    """
    types = []
    for key in conf_dict:
        m = INSTANCES_REGEX.match(key)
        if m and m.group(1) not in NON_JOB_TYPE_WORDS:
            types.append(m.group(1))
    return sorted(types)
