"""Heartbeat liveness monitoring inside the coordinator.

Analog of the reference's use of Hadoop's ``AbstractLivelinessMonitor``
(reference: TonyApplicationMaster.java:168-193 constructs the monitor with
expiry = hb-interval * max(3, max-consecutive-missed), :811-819 receives
pings, :1155-1165 declares tasks dead). A dead task fails the whole job —
acceptable for gang-scheduled SPMD, where one lost process stalls every
collective."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from tony_tpu.runtime import metrics as metrics_mod

log = logging.getLogger(__name__)


class HeartbeatMonitor:
    """Tracks last-ping times; fires ``on_expired(task_id)`` once per task
    whose silence exceeds ``hb_interval_ms * max(3, max_missed)``."""

    def __init__(self, hb_interval_ms: int, max_missed: int,
                 on_expired: Callable[[str], None]) -> None:
        self.expiry_s = hb_interval_ms / 1000.0 * max(3, max_missed)
        # Check at least 4x/s so expiry detection and shutdown joins stay
        # snappy even with the default 1s heartbeat interval.
        self.check_period_s = min(max(hb_interval_ms / 1000.0, 0.05), 0.25)
        self.on_expired = on_expired
        self._last_ping: dict[str, float] = {}  # guarded-by: _lock
        self._expired: set[str] = set()         # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, task_id: str, grace_s: float = 0.0) -> None:
        """Start tracking a task (first ping = registration time, reference
        :833 registers the task with the monitor when its spec arrives).

        ``grace_s`` credits the task extra silence on top of the normal
        expiry window — a restarted coordinator re-adopting live tasks
        grants each one its full executor re-attach window, so a task
        whose executor is still backing off toward the NEW coordinator is
        not declared dead for an outage the coordinator itself caused."""
        with self._lock:
            self._last_ping[task_id] = time.monotonic() + grace_s

    def unregister(self, task_id: str) -> None:
        """Stop tracking (task completed normally)."""
        with self._lock:
            self._last_ping.pop(task_id, None)
            self._expired.discard(task_id)

    def ping(self, task_id: str) -> None:
        with self._lock:
            if task_id in self._last_ping:
                self._last_ping[task_id] = time.monotonic()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="tony-hb-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def reset(self) -> None:
        """Forget all tasks (session retry rebuilds registrations)."""
        with self._lock:
            self._last_ping.clear()
            self._expired.clear()

    def _run(self) -> None:
        while not self._stop.wait(self.check_period_s):
            now = time.monotonic()
            newly_dead = []
            with self._lock:
                for task_id, last in self._last_ping.items():
                    if task_id not in self._expired and now - last > self.expiry_s:
                        self._expired.add(task_id)
                        newly_dead.append(task_id)
            for task_id in newly_dead:
                log.warning("task %s missed heartbeats for %.1fs — deemed dead",
                            task_id, self.expiry_s)
                # rides the coordinator's "am:0" entry in METRICS_SNAPSHOT
                # events, so expiries are visible fleet-wide
                metrics_mod.get_default().counter(
                    "tony_missed_heartbeat_expiries_total",
                    help="tasks deemed dead after missed heartbeats",
                    task=task_id).inc()
                try:
                    self.on_expired(task_id)
                except Exception:
                    log.exception("on_expired callback failed for %s", task_id)
