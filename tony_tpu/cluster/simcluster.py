"""SimCluster: the SimFleet pattern applied to the cluster scheduler.

Real CI cannot buy a 1000-job multi-tenant schedule with cross-job
preemption — so this harness runs the REAL policy code
(:class:`~tony_tpu.cluster.scheduler.ClusterScheduler`, unmodified)
under a virtual clock: oracle jobs with exact committed-step
arithmetic, a seeded arrival trace, and seeded preemption chaos.  A
thousand-job day replays in milliseconds, deterministically, and every
property the daemon promises is checked *at every event*:

- **No double grant** — ``check_invariant()`` after every event (the
  scheduler also self-checks at every grant).
- **Preemption loses zero committed steps** — each run episode of a job
  covers a half-open step interval ``[resume, committed)``; at
  completion the episodes must tile ``[0, duration_steps)`` exactly:
  no gap (lost work) and no overlap (re-done work).
- **Bounded queue waits / no starvation** — every submitted job reaches
  a terminal state and the wait distribution is reported (p50/p99) for
  the test to pin.

Bring-up cost is the PR 4 contrast collapsed to two constants: a gang
whose slices all carry the job's staging digest pays ``warm_adopt_s``;
anything else pays ``cold_bringup_s``.  Warm-pool affinity is therefore
directly visible in the completed-jobs-per-virtual-hour number.
"""

from __future__ import annotations

import heapq
import itertools
import random
from dataclasses import dataclass, field

from tony_tpu.cluster import scheduler as S


@dataclass
class SimJobSpec:
    """One job in an arrival trace."""

    job_id: str
    arrival_s: float
    user: str
    priority: int
    slices: int
    digest: str
    elastic: bool
    duration_steps: int
    steps_per_s: float = 100.0


def generate_trace(seed: int, n_jobs: int = 1000, pool_size: int = 8,
                   users: int = 6, mean_interarrival_s: float = 2.0,
                   digests: int = 4) -> list[SimJobSpec]:
    """Seeded arrival trace: mixed users, priorities, gang sizes, and a
    small digest vocabulary (so warm hits actually happen).  ~70% of
    jobs are elastic — the preemption chaos needs victims."""
    rng = random.Random(seed)
    t = 0.0
    out: list[SimJobSpec] = []
    for i in range(n_jobs):
        t += rng.expovariate(1.0 / mean_interarrival_s)
        gang = rng.choice((1, 1, 1, 2, 2, 4))
        out.append(SimJobSpec(
            job_id=f"sim-{i}",
            arrival_s=round(t, 6),
            user=f"user-{rng.randrange(users)}",
            priority=rng.choice((0, 0, 0, 1, 1, 2)),
            slices=min(gang, pool_size),
            digest=f"digest-{rng.randrange(digests)}",
            elastic=rng.random() < 0.7,
            duration_steps=rng.randrange(50, 500),
        ))
    return out


@dataclass
class SimReport:
    """What a run observed — the chaos suite pins against these."""

    completed: int = 0
    failed_to_finish: list[str] = field(default_factory=list)
    queue_waits: list[float] = field(default_factory=list)
    preemptions: int = 0
    requeues: int = 0
    warm_hits: int = 0
    cold_grants: int = 0
    grants: int = 0
    virtual_makespan_s: float = 0.0
    per_user_waits: dict = field(default_factory=dict)

    def wait_quantile(self, q: float) -> float:
        if not self.queue_waits:
            return 0.0
        waits = sorted(self.queue_waits)
        idx = min(len(waits) - 1, int(q * len(waits)))
        return waits[idx]


class _SimRun:
    """Per-job execution state: the oracle's committed-step arithmetic
    plus the episode ledger the zero-lost-steps pin is built on."""

    __slots__ = ("spec", "run_start", "rate", "resume", "gen", "episodes")

    def __init__(self, spec: SimJobSpec) -> None:
        self.spec = spec
        self.run_start = 0.0
        self.rate = spec.steps_per_s
        self.resume = 0
        self.gen = 0              # bumped per (re)start/fence: stale
        #                           heap entries are skipped by gen
        self.episodes: list[tuple[int, int]] = []

    def committed(self, now: float) -> int:
        if now <= self.run_start:
            return self.resume
        # the epsilon absorbs float error at exact step boundaries (a
        # completion event lands at precisely finish_time)
        steps = self.resume + int((now - self.run_start) * self.rate + 1e-6)
        return min(steps, self.spec.duration_steps)


class SimCluster:
    """Virtual-time event loop over the real scheduler.

    ``chaos_seed`` injects forced preemption pressure on top of the
    trace's natural priority mix: at seeded points a phantom
    high-priority probe job (1-2 slices, short) arrives, shrinking
    whatever elastic work is in its way — the preemption path is
    exercised hundreds of times per run.
    """

    ARRIVAL, COMPLETION, FENCE = "arrival", "completion", "fence"

    def __init__(self, pool_size: int = 8, queue_limit: int = 10_000,
                 user_quota: int = 0, grace_s: float = 0.5,
                 cold_bringup_s: float = 2.0, warm_adopt_s: float = 0.05,
                 chaos_seed: int | None = None,
                 chaos_every_s: float = 60.0) -> None:
        self.pool = S.SlicePool()
        for i in range(pool_size):
            self.pool.add(f"slice-{i}")
        self.sched = S.ClusterScheduler(self.pool, queue_limit=queue_limit,
                                        user_quota=user_quota)
        self.grace_s = grace_s
        self.cold_bringup_s = cold_bringup_s
        self.warm_adopt_s = warm_adopt_s
        self.chaos_rng = (random.Random(chaos_seed)
                          if chaos_seed is not None else None)
        self.chaos_every_s = chaos_every_s
        self._heap: list[tuple] = []
        self._tie = itertools.count()
        self.runs: dict[str, _SimRun] = {}

    # -- event plumbing ------------------------------------------------------
    def _push(self, t: float, kind: str, job_id: str, gen: int) -> None:
        heapq.heappush(self._heap, (t, next(self._tie), kind, job_id, gen))

    def run(self, trace: list[SimJobSpec],
            max_events: int = 2_000_000) -> SimReport:
        report = SimReport()
        for spec in trace:
            self.runs[spec.job_id] = _SimRun(spec)
            self._push(spec.arrival_s, self.ARRIVAL, spec.job_id, 0)
        if self.chaos_rng is not None and trace:
            horizon = max(s.arrival_s for s in trace)
            t, i = 0.0, 0
            while t < horizon:
                t += self.chaos_rng.expovariate(1.0 / self.chaos_every_s)
                spec = SimJobSpec(
                    job_id=f"chaos-{i}", arrival_s=round(t, 6),
                    user="chaos", priority=3,
                    slices=self.chaos_rng.choice((1, 2)),
                    digest="", elastic=False,
                    duration_steps=self.chaos_rng.randrange(20, 80))
                i += 1
                self.runs[spec.job_id] = _SimRun(spec)
                self._push(spec.arrival_s, self.ARRIVAL, spec.job_id, 0)
        now = 0.0
        events = 0
        while self._heap:
            events += 1
            if events > max_events:
                raise RuntimeError(
                    f"SimCluster exceeded {max_events} events — "
                    "schedule is not converging")
            t, _, kind, job_id, gen = heapq.heappop(self._heap)
            now = max(now, t)
            run = self.runs[job_id]
            if kind == self.ARRIVAL:
                self._arrive(run, now)
            elif gen != run.gen:
                continue                      # stale (job was fenced)
            elif kind == self.COMPLETION:
                self._complete(run, now, report)
            elif kind == self.FENCE:
                self._fence(run, now, report)
            self._schedule(now, report)
            self.sched.check_invariant()
        report.virtual_makespan_s = round(now, 6)
        for job in self.sched.jobs.values():
            if job.state not in S.TERMINAL_STATES:
                report.failed_to_finish.append(job.job_id)
        return report

    # -- event handlers ------------------------------------------------------
    def _arrive(self, run: _SimRun, now: float) -> None:
        spec = run.spec
        self.sched.submit(S.Job(
            job_id=spec.job_id, user=spec.user, slices=spec.slices,
            priority=spec.priority, digest=spec.digest,
            elastic=spec.elastic), now)

    def _complete(self, run: _SimRun, now: float,
                  report: SimReport) -> None:
        job = self.sched.jobs[run.spec.job_id]
        if job.state not in (S.RUNNING, S.PREEMPTING):
            return
        # a completion event IS the finish time: everything committed
        end = run.spec.duration_steps
        run.episodes.append((run.resume, end))
        self.sched.complete(job.job_id, now)
        report.completed += 1
        self._assert_tiling(run)

    def _fence(self, run: _SimRun, now: float, report: SimReport) -> None:
        job = self.sched.jobs[run.spec.job_id]
        if job.state != S.PREEMPTING:
            return
        fence_step = run.committed(now)
        run.episodes.append((run.resume, fence_step))
        run.gen += 1                      # invalidates the old completion
        self.sched.preemption_complete(job.job_id, now, fence_step)
        if job.state == S.QUEUED:
            report.requeues += 1
            run.resume = fence_step       # next grant resumes here
        else:
            # partial shrink: still running on fewer slices from the
            # fence point (the drained slices' in-flight work since the
            # fence is discarded, exactly the elastic-shrink contract)
            run.resume = fence_step
            run.run_start = now
            self._push(self._finish_time(run, now), self.COMPLETION,
                       job.job_id, run.gen)

    # -- scheduling ----------------------------------------------------------
    def _schedule(self, now: float, report: SimReport) -> None:
        while True:
            grants, shrinks = self.sched.tick(now)
            for g in grants:
                run = self.runs[g.job.job_id]
                if run.resume != g.job.resume_step:
                    raise AssertionError(
                        f"job {g.job.job_id!r} granted with resume_step "
                        f"{g.job.resume_step}, oracle fence committed "
                        f"{run.resume} — committed steps lost/re-done")
                warm = g.warm_hits == len(g.slice_ids)
                bringup = self.warm_adopt_s if warm else self.cold_bringup_s
                run.gen += 1
                run.run_start = now + bringup
                report.grants += 1
                report.queue_waits.append(g.wait_s)
                report.per_user_waits.setdefault(
                    g.job.user, []).append(g.wait_s)
                report.warm_hits += g.warm_hits
                report.cold_grants += len(g.slice_ids) - g.warm_hits
                self._push(self._finish_time(run, now + bringup),
                           self.COMPLETION, g.job.job_id, run.gen)
            for s in shrinks:
                report.preemptions += 1
                self._push(now + self.grace_s, self.FENCE,
                           s.job.job_id, self.runs[s.job.job_id].gen)
            if not grants:
                break

    def _finish_time(self, run: _SimRun, run_start: float) -> float:
        remaining = run.spec.duration_steps - run.resume
        return run_start + remaining / run.rate

    # -- pins ----------------------------------------------------------------
    @staticmethod
    def _assert_tiling(run: _SimRun) -> None:
        """The zero-lost-steps pin: episodes tile [0, duration_steps)
        exactly — every committed step exactly once."""
        expect = 0
        for start, end in run.episodes:
            if start != expect:
                raise AssertionError(
                    f"job {run.spec.job_id!r}: episode starts at step "
                    f"{start}, previous committed through {expect} — "
                    f"{'lost' if start > expect else 're-done'} steps "
                    f"(episodes: {run.episodes})")
            expect = end
        if expect != run.spec.duration_steps:
            raise AssertionError(
                f"job {run.spec.job_id!r}: committed {expect} of "
                f"{run.spec.duration_steps} steps "
                f"(episodes: {run.episodes})")
