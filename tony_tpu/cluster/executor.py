"""Per-host task executor: the agent that wraps the user training process.

TPU-native rebuild of the reference's ``TaskExecutor`` (reference: tony-core/
src/main/java/com/linkedin/tony/TaskExecutor.java:83-343). Lifecycle kept
one-for-one: reserve a data-plane port → register with the coordinator and
poll the gang barrier → export the framework runtime environment → fork-exec
the user command → heartbeat on a schedule → report the exit code and exit
with it (the process exit status stays the authoritative result, as in the
reference where the YARN container exit code is what the AM trusts).

The framework env switch (reference :131-154) gains a JAX arm — the TPU-first
default — exporting everything ``tony_tpu.runtime.initialize()`` needs for
``jax.distributed.initialize``: coordinator address (process 0's endpoint),
dense process id, process count, and the mesh spec. TF_CONFIG and
RANK/WORLD/INIT_METHOD arms are kept for reference-parity.

Chaos hooks (TEST_TASK_EXECUTOR_HANG / _NUM_HB_MISS / _SKEW) are read by this
production code exactly as in the reference (TaskExecutor.java:238-340) so the
E2E suite can drive failure paths.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

from tony_tpu import constants
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
from tony_tpu.rpc.client import ApplicationRpcClient, RpcRetryError
from tony_tpu.runtime import goodput as goodput_mod
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.runtime import tracing

log = logging.getLogger("tony_tpu.executor")

# Resolved at import time: the preexec hook runs between fork and exec in a
# process whose Heartbeater thread may hold the import/allocator locks —
# importing or CDLL-loading there can deadlock the child. Pre-resolving
# leaves only a plain FFI call in the fork window.
try:
    import ctypes
    _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:  # non-Linux: PDEATHSIG is best-effort anyway
    _LIBC = None
_PR_SET_PDEATHSIG = 1


def reserve_port() -> int:
    """Reserve a free port for the task's data plane (the jax.distributed
    coordinator service when this task becomes process 0). Reference reserves
    via ServerSocket(0) then releases (TaskExecutor.java:69-81)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


class Heartbeater(threading.Thread):
    """1s-period heartbeat sender (reference: TaskExecutor.Heartbeater:234-273).
    After 5 consecutive failed sends the coordinator is presumed gone; with
    ``reattach_timeout_s`` > 0 the thread enters a bounded re-attach window
    (capped jittered backoff, optional RPC-target refresh per attempt) —
    a restarted coordinator that starts answering, possibly under a NEW
    incarnation, resumes the beat with the user process untouched. Only
    when the window expires (or with the timeout at 0, the legacy
    fail-fast shape) does the executor die. Transient single-send failures
    NEVER kill the thread — they are counted
    (``tony_heartbeat_send_failures_total``) and retried on schedule, so
    the final-beat flush machinery in run() is never forfeited to one
    blip. Supports the TEST_TASK_EXECUTOR_NUM_HB_MISS chaos hook (skip the
    first N pings to trigger coordinator-side expiry)."""

    MAX_CONSECUTIVE_FAILURES = 5
    #: re-attach backoff bounds: start fast (the coordinator restart the
    #: window exists for takes ~a second locally), cap at 2s so the window
    #: budget is spent probing, not sleeping
    REATTACH_BACKOFF_MIN_S = 0.2
    REATTACH_BACKOFF_MAX_S = 2.0

    def __init__(self, rpc: ApplicationRpcClient, task_id: str,
                 interval_s: float, gcs_token_file: str | None = None,
                 snapshot_fn=None, on_epoch=None, spans_fn=None,
                 reattach_timeout_s: float = 0.0, refresh_rpc=None,
                 on_reattach=None, goodput_fn=None) -> None:
        super().__init__(name="heartbeater", daemon=True)
        self.rpc = rpc
        self.task_id = task_id
        self.interval_s = interval_s
        #: () -> compact JSON metrics snapshot piggybacked on each beat
        #: (None = old-style liveness-only heartbeats). A provider error
        #: must never cost a ping — collection is wrapped below.
        self.snapshot_fn = snapshot_fn
        #: () -> compact JSON trace-span batch (tracing.encode_batch) —
        #: the executor's own spans plus the user process's spool tail.
        #: Same contract as snapshot_fn: errors never cost a ping.
        self.spans_fn = spans_fn
        #: () -> cumulative goodput-ledger wire JSON (runtime/goodput.py)
        #: — host ledger merged with the user process's spool snapshot.
        #: Same contract as snapshot_fn: errors never cost a ping.
        self.goodput_fn = goodput_fn
        #: last measured beat RTT — shipped on the NEXT beat as the
        #: coordinator's clock-offset half-trip estimate
        self.last_rtt = 0.0
        # Old-impl compatibility (tests with pre-trace fakes): only pass
        # the trace piggyback when the RPC surface accepts it — the same
        # inspect precedent as the server-side handler.
        try:
            import inspect
            _params = inspect.signature(
                rpc.task_executor_heartbeat).parameters
            self._rpc_takes_trace = "spans" in _params
            self._rpc_takes_goodput = "goodput" in _params
        except (TypeError, ValueError):
            self._rpc_takes_trace = True
            self._rpc_takes_goodput = True
        #: epoch observer (elastic resync): called with the coordinator's
        #: cluster epoch from every ack; the executor compares it to the
        #: epoch its user process was launched under and resyncs on a
        #: bump. Errors in the observer must never cost a ping.
        self.on_epoch = on_epoch
        #: how long to keep retrying an unreachable coordinator before
        #: giving up (tony.coordinator.reattach-timeout-ms); 0 restores
        #: the legacy die-after-5-failures behavior
        self.reattach_timeout_s = reattach_timeout_s
        #: () -> None, called before each re-attach probe — the executor
        #: re-reads coordinator.addr and swaps in a client for the NEW
        #: address (a restarted coordinator may bind a different port).
        #: Errors must never abort the window.
        self.refresh_rpc = refresh_rpc
        #: (new_incarnation) -> None, called when an ack's incarnation
        #: CHANGES from the first-seen value — the executor re-runs the
        #: registration handshake so the restarted coordinator re-learns
        #: this task's endpoint. Errors must never kill the beat.
        self.on_reattach = on_reattach
        #: coordinator incarnation from the registration response (seeded
        #: by the executor); 0 = not tracked
        self.incarnation = 0
        self.stop_event = threading.Event()
        self.skip_remaining = int(
            os.environ.get(constants.TEST_TASK_EXECUTOR_NUM_HB_MISS, "0"))
        self._failures = 0
        #: heartbeat responses carry the job's current GCS token (client-
        #: pushed renewals); a change is republished to this local file,
        #: which the user process's storage layer re-reads per call —
        #: env can't reach an already-forked child, a file can
        self.gcs_token_file = gcs_token_file
        self._last_token = os.environ.get(constants.TONY_GCS_TOKEN, "")

    def _republish_token(self, token: str) -> None:
        if not token or token == self._last_token:
            return
        self._last_token = token
        os.environ[constants.TONY_GCS_TOKEN] = token
        if self.gcs_token_file:
            tmp = self.gcs_token_file + ".tmp"
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(token)
            os.replace(tmp, self.gcs_token_file)    # atomic for readers
            log.info("renewed GCS token republished to %s",
                     self.gcs_token_file)

    def _snapshot(self) -> str:
        if self.snapshot_fn is None:
            return ""
        try:
            return self.snapshot_fn() or ""
        except Exception:
            log.warning("metrics snapshot collection failed; sending "
                        "plain heartbeat", exc_info=True)
            return ""

    def _spans(self) -> str:
        if self.spans_fn is None:
            return ""
        try:
            return self.spans_fn() or ""
        except Exception:
            log.warning("trace span collection failed; sending span-less "
                        "heartbeat", exc_info=True)
            return ""

    def _goodput(self) -> str:
        if self.goodput_fn is None:
            return ""
        try:
            return self.goodput_fn() or ""
        except Exception:
            log.warning("goodput snapshot collection failed; sending "
                        "ledger-less heartbeat", exc_info=True)
            return ""

    def _send_beat(self) -> None:
        """One heartbeat send + ack handling; raises on send failure (the
        caller counts). Ack handling — token republish, epoch observer,
        incarnation tracking — is each individually shielded: a broken
        observer must not turn a DELIVERED beat into a counted failure."""
        # collect the piggybacks BEFORE the clock starts: the
        # RTT shipped on the next beat must measure the RPC, not
        # snapshot assembly
        snapshot = self._snapshot()
        spans = self._spans() if self._rpc_takes_trace else ""
        goodput = self._goodput() if self._rpc_takes_goodput else ""
        t0 = time.perf_counter()
        if self._rpc_takes_goodput:
            ack = self.rpc.task_executor_heartbeat(
                self.task_id, snapshot, spans=spans,
                client_rtt=self.last_rtt, goodput=goodput)
        elif self._rpc_takes_trace:
            ack = self.rpc.task_executor_heartbeat(
                self.task_id, snapshot, spans=spans,
                client_rtt=self.last_rtt)
        else:
            ack = self.rpc.task_executor_heartbeat(self.task_id,
                                                   snapshot)
        measured = time.perf_counter() - t0
        # an implausibly large "RTT" spanned the client's
        # internal retries (deadline + backoff), not one round
        # trip — shipping it would skew the midpoint estimate;
        # 0 means "no estimate this beat"
        self.last_rtt = measured if measured < 5.0 else 0.0
        self._failures = 0
        try:
            self._republish_token(ack.gcs_token)
        except Exception:
            log.warning("GCS token republish failed", exc_info=True)
        if self.on_epoch is not None:
            try:
                self.on_epoch(ack.cluster_epoch)
            except Exception:
                log.warning("cluster-epoch observer failed",
                            exc_info=True)
        self._handle_incarnation(getattr(ack, "incarnation", 0))

    def _handle_incarnation(self, inc: int) -> None:
        """First nonzero incarnation is remembered; a CHANGE afterwards
        means a restarted coordinator answered this beat — fire
        ``on_reattach`` so the executor re-registers its endpoint."""
        if inc <= 0:
            return
        if self.incarnation == 0:
            self.incarnation = inc
            return
        if inc == self.incarnation:
            return
        old, self.incarnation = self.incarnation, inc
        log.warning("coordinator incarnation changed %d -> %d — a restarted "
                    "coordinator recovered the session", old, inc)
        if self.on_reattach is not None:
            try:
                self.on_reattach(inc)
            except Exception:
                log.warning("re-attach handshake failed (next beat retries)",
                            exc_info=True)

    def _count_failure(self) -> None:
        self._failures += 1
        metrics_mod.get_default().counter(
            "tony_heartbeat_send_failures_total",
            help="heartbeat sends that failed (transient or fatal)").inc()
        log.warning("heartbeat send failure %d/%d", self._failures,
                    self.MAX_CONSECUTIVE_FAILURES)

    def _reattach(self) -> bool:
        """The coordinator stopped answering: probe it for up to
        ``reattach_timeout_s`` with capped jittered backoff, refreshing
        the RPC target each attempt (a restarted coordinator may listen
        on a new port — refresh_rpc re-reads coordinator.addr). Jitter
        matters: every executor in the job enters this window at the
        same instant, and synchronized probes would hammer the
        recovering coordinator in waves. Returns True once a beat lands;
        exits the process when the window expires."""
        deadline = time.monotonic() + self.reattach_timeout_s
        backoff = self.REATTACH_BACKOFF_MIN_S
        log.warning("coordinator unreachable — entering re-attach window "
                    "(%.0fs)", self.reattach_timeout_s)
        while not self.stop_event.is_set():
            if time.monotonic() > deadline:
                break
            if self.refresh_rpc is not None:
                try:
                    self.refresh_rpc()
                except Exception:
                    log.warning("RPC target refresh failed", exc_info=True)
            try:
                self._send_beat()
                log.info("coordinator answering again — re-attach window "
                         "closed, resuming normal beats")
                return True
            except Exception:
                self._count_failure()
            if self.stop_event.wait(backoff * (0.5 + random.random() / 2)):
                break
            backoff = min(backoff * 2, self.REATTACH_BACKOFF_MAX_S)
        if self.stop_event.is_set():
            return False
        log.error("coordinator did not come back within %.0fs — lost the "
                  "coordinator, exiting", self.reattach_timeout_s)
        os._exit(constants.EXIT_LOST_COORDINATOR)

    def run(self) -> None:
        while not self.stop_event.wait(self.interval_s):
            if self.skip_remaining > 0:
                self.skip_remaining -= 1
                log.info("chaos: skipping heartbeat (%d more to skip)",
                         self.skip_remaining)
                continue
            try:
                self._send_beat()
            except Exception:  # any send failure counts
                self._count_failure()
                if self._failures >= self.MAX_CONSECUTIVE_FAILURES:
                    if self.reattach_timeout_s > 0:
                        self._reattach()
                    else:
                        log.error("too many heartbeat failures — lost the "
                                  "coordinator, exiting")
                        os._exit(constants.EXIT_LOST_COORDINATOR)


class TaskExecutor:
    def __init__(self, am_address: str, task_command: str,
                 conf: TonyConfig, shell_env: dict[str, str]) -> None:
        self.am_address = am_address
        self.task_command = task_command
        self.conf = conf
        self.shell_env = shell_env
        self.job_name = os.environ[constants.JOB_NAME]
        self.task_index = int(os.environ[constants.TASK_INDEX])
        self.task_num = int(os.environ[constants.TASK_NUM])
        self.session_id = os.environ.get(constants.SESSION_ID, "0")
        self.task_id = f"{self.job_name}:{self.task_index}"
        self.data_port = reserve_port()
        self.tb_port = reserve_port()
        # Inter-gang tensor channels (cross-slice pipeline): reserve the
        # hub's listen port up front — like data_port it survives elastic
        # resyncs (the executor never exits for one), so peers can keep
        # dialing the same endpoint across user-process relaunches. Only
        # pipeline jobs advertise one.
        self.channel_port = (reserve_port()
                             if conf.get(K.PIPELINE_STAGES_KEY) else 0)
        self.notebook_port = (reserve_port()
                              if self.job_name == constants.NOTEBOOK_JOB_NAME
                              else 0)
        self.rpc = ApplicationRpcClient.get_instance(am_address)
        # Tracing + flight recorder: the executor's tracer holds ITS
        # spans (lifecycle, incidents); the user process mirrors its own
        # spans to the SPOOL file, which the heartbeater tails onto each
        # beat — the bridge from the fork-exec'd child to the
        # coordinator (metrics stay process-local; spans must not).
        try:
            self._trace_sample = float(
                conf.get(K.TRACE_SAMPLE_RATE_KEY) or "1.0")
        except ValueError:
            self._trace_sample = 1.0
        self._trace_ring = conf.get_int(K.TRACE_RING_KEY, 2048)
        self._flight_ring = conf.get_int(K.FLIGHT_RING_KEY, 256)
        self.trace_spool = os.path.join(
            os.getcwd(), f".trace-{self.job_name}-{self.task_index}.jsonl")
        try:
            # a previous executor GENERATION's spool (in-session restart
            # into the same working dir) must not re-ship its spans as
            # duplicates through this generation's fresh reader
            os.unlink(self.trace_spool)
        except OSError:
            pass
        tracing.configure(proc=f"{self.task_id}/executor",
                          sample_rate=self._trace_sample,
                          ring_size=self._trace_ring,
                          flight_dir=os.getcwd(),
                          flight_ring=self._flight_ring)
        self._spool_reader = tracing.SpoolReader(self.trace_spool)
        # Goodput ledger: the HOST-side accountant of this task's wall
        # clock. The user process keeps its own ledger and publishes it
        # to the goodput spool (same child→executor bridge as the trace
        # spool); goodput_snapshot() substitutes that breakdown for the
        # host ledger's internal "user" span at each beat.
        self.goodput_spool = os.path.join(
            os.getcwd(), f".goodput-{self.job_name}-{self.task_index}.json")
        try:
            # a previous generation's spool must not be merged into this
            # generation's fresh host ledger
            os.unlink(self.goodput_spool)
        except OSError:
            pass
        self._ledger = goodput_mod.GoodputLedger(
            registry=metrics_mod.get_default(),
            extra_categories=(goodput_mod.USER_CATEGORY,))
        #: one-shot incident tail attached to the FINAL beat after an
        #: abnormal child exit, so the coordinator can hang it on the
        #: incident's jhist event even when nobody can read this host
        self._flight_tail: dict | None = None
        self.hb_interval_s = conf.get_int(K.TASK_HEARTBEAT_INTERVAL_KEY, 1000) / 1000.0
        self.registration_timeout_s = conf.get_int(
            K.TASK_REGISTRATION_TIMEOUT_KEY, 300000) / 1000.0
        #: coordinator-crash survival: how long the heartbeater keeps
        #: probing an unreachable coordinator before the executor gives
        #: up (0 = legacy fail-fast after 5 missed sends)
        self.reattach_timeout_s = conf.get_int(
            K.COORDINATOR_REATTACH_TIMEOUT_KEY, 30000) / 1000.0
        self._heartbeater: Heartbeater | None = None
        self.bootstrap: dict | None = None
        self._started_at = time.monotonic()
        #: elastic resync: set by the heartbeat epoch observer when the
        #: coordinator cuts a new cluster-spec epoch; the run loop stops
        #: the user process, re-runs the registration handshake and
        #: relaunches instead of exiting
        self._resync = threading.Event()
        self._resync_target = 0          # highest epoch the observer saw
        self._user_proc: subprocess.Popen | None = None
        self._user_proc_lock = threading.Lock()

    #: grace between the resync SIGINT (which lets run_training's finally
    #: close the prefetcher and wait out in-flight async checkpoint saves
    #: — the checkpoint-sync step) and the SIGKILL escalation. A trainer
    #: blocked in a collective on the DEAD gang never feels the SIGINT,
    #: so this grace bounds the recovery wall — overridable via env for
    #: jobs whose checkpoint flush genuinely needs longer (or tests that
    #: need it shorter).
    RESYNC_KILL_GRACE_S = float(
        os.environ.get("TONY_RESYNC_KILL_GRACE_S", "10"))

    def _on_cluster_epoch(self, epoch: int) -> None:
        """Heartbeat-ack epoch observer (runs on the Heartbeater thread):
        an epoch ahead of the one the user process was launched under
        means the gang changed shape — interrupt the user process (SIGINT
        first: trainers exit through their KeyboardInterrupt-safe finally,
        completing in-flight checkpoint saves) and arm the resync loop."""
        if self.bootstrap is None \
                or epoch <= self.bootstrap.get("cluster_epoch", 0) \
                or self._resync.is_set():
            return
        log.warning("cluster epoch moved to %d (ours: %d) — stopping the "
                    "user process for an elastic resync", epoch,
                    self.bootstrap.get("cluster_epoch", 0))
        self._resync_target = max(self._resync_target, epoch)
        self._resync.set()
        self._interrupt_user_process()

    def _refresh_rpc(self) -> None:
        """Re-attach probe hook (runs on the Heartbeater thread): re-read
        coordinator.addr from the job dir — a restarted coordinator
        usually rebinds its journaled port, but a port collision makes it
        pick a fresh one and rewrite the file — and swap in a
        freshly-dialed RPC client. The heartbeater AND the executor's
        own handle both move, so the final-beat flush and the
        register_execution_result report reach the moved coordinator."""
        path = os.path.join(os.getcwd(), constants.COORDINATOR_ADDR_FILE)
        try:
            with open(path) as f:
                addr = f.read().strip()
        except OSError:
            return
        if not addr:
            return
        if addr != self.am_address:
            log.info("coordinator address moved %s -> %s",
                     self.am_address, addr)
            self.am_address = addr
        # Same-address restart is the COMMON case (the recovered
        # coordinator rebinds its journaled port) and the old channel is
        # stuck in gRPC's connection backoff — force a fresh dial either
        # way; probes run at most every couple hundred ms, so the churn
        # is bounded.
        self.rpc = ApplicationRpcClient.reconnect(addr)
        if self._heartbeater is not None:
            self._heartbeater.rpc = self.rpc

    def _on_coordinator_restart(self, incarnation: int) -> None:
        """Incarnation-change observer (runs on the Heartbeater thread): a
        restarted coordinator recovered the session from its journal and
        re-adopted us from the journaled spec — re-run the registration
        handshake to confirm our live endpoint (idempotent; the recovered
        barrier is already released, so this returns immediately and the
        epoch is unchanged — the user process is never touched)."""
        log.warning("re-attached to restarted coordinator (incarnation %d) "
                    "— re-running the registration handshake", incarnation)
        self.register_and_get_cluster_spec()
        log.info("re-attach handshake complete (epoch %d)",
                 self.bootstrap.get("cluster_epoch", 0))

    def _interrupt_user_process(self) -> None:
        with self._user_proc_lock:
            proc = self._user_proc
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError):
            return

        def _escalate():
            if proc.poll() is None:
                log.warning("user process ignored resync SIGINT for %.0fs "
                            "— escalating to SIGKILL",
                            self.RESYNC_KILL_GRACE_S)
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        t = threading.Timer(self.RESYNC_KILL_GRACE_S, _escalate)
        t.daemon = True
        t.start()

    # ------------------------------------------------------------------
    def metrics_snapshot(self) -> str:
        """Compact JSON snapshot for the heartbeat piggyback: this host's
        process stats (RSS/CPU from /proc, uptime) plus whatever else
        landed in the executor's default registry (e.g. child exit
        codes). The user training process runs in its own process — its
        registry stays there; what ships here is the HOST-side view the
        coordinator can't see otherwise."""
        reg = metrics_mod.get_default()
        metrics_mod.sample_host_stats(reg)
        reg.gauge("tony_executor_uptime_seconds",
                  help="seconds since this executor started").set(
                      time.monotonic() - self._started_at)
        return reg.to_wire_json()

    def trace_batch(self) -> str:
        """Span batch for the heartbeat piggyback: the executor's own
        pending spans, the user process's spool tail, and — on the final
        beat after an incident — the one-shot flight-recorder tail.
        Returns "" when there is nothing to ship (the common idle beat:
        no bytes on the wire)."""
        tracer = tracing.get_tracer()
        spans = tracer.drain(tracing.MAX_SPANS_PER_BATCH)
        spans.extend(self._spool_reader.read_new(
            tracing.MAX_SPANS_PER_BATCH))
        # keep the spool FILE bounded: truncate once fully consumed,
        # skip a runaway backlog (the writer appends forever otherwise)
        self._spool_reader.maybe_rotate()
        tail, self._flight_tail = self._flight_tail, None
        if not spans and not tail:
            return ""
        return tracing.encode_batch(spans, flight=tail)

    def goodput_snapshot(self) -> str:
        """Merged goodput wire for the heartbeat piggyback: the host
        ledger (provision/stage/resync + the internal ``user`` span)
        with the user process's own spool-published breakdown
        substituted in (see runtime/goodput.py merge_wires). Cumulative
        totals — a re-delivered beat re-ingests to the same table."""
        host = self._ledger.snapshot()
        child = None
        try:
            with open(self.goodput_spool, encoding="utf-8") as f:
                child = goodput_mod.from_wire_json(f.read())
        except OSError:
            pass
        return json.dumps(goodput_mod.merge_wires(host, child),
                          sort_keys=True)

    # ------------------------------------------------------------------
    def register_and_get_cluster_spec(self) -> dict:
        """Register our endpoint, then poll until the gang barrier releases
        (reference: registerAndGetClusterSpec:196-212 polls until non-null)."""
        host = socket.gethostname()
        spec = f"{host}:{self.data_port}"
        deadline = time.monotonic() + self.registration_timeout_s
        backoff = 0.1
        while True:
            resp = self.rpc.register_worker_spec(self.task_id, spec,
                                                 self.channel_port)
            if resp.released:
                self.bootstrap = {
                    "cluster_spec": resp.spec,
                    "coordinator_address": resp.coordinator_address,
                    "process_id": resp.process_id,
                    "num_processes": resp.num_processes,
                    "mesh_spec": resp.mesh_spec,
                    "cluster_epoch": resp.cluster_epoch,
                    "channel_spec": getattr(resp, "channel_spec", ""),
                    "incarnation": getattr(resp, "incarnation", 0),
                }
                if self._heartbeater is not None:
                    # keep the heartbeater's first-seen incarnation in
                    # step with the coordinator that just answered the
                    # handshake, so only FUTURE restarts trigger another
                    # re-attach
                    self._heartbeater.incarnation = \
                        self.bootstrap["incarnation"]
                return self.bootstrap
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"gang barrier did not release within "
                    f"{self.registration_timeout_s:.0f}s")
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 2.0)

    def _publish_gcs_token(self) -> str:
        """Write the current GCS token to this task's local token file
        (0600) and return its path; the heartbeater atomically rewrites
        it when the client pushes a renewal."""
        # job_name in the filename: executors of different job types with
        # the same index can share a working directory without contending
        # on one file
        path = os.path.join(
            os.getcwd(), f".gcs-token-{self.job_name}-{self.task_index}")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(os.environ.get(constants.TONY_GCS_TOKEN, ""))
        self._gcs_token_file = path
        return path

    # ------------------------------------------------------------------
    def framework_env(self) -> dict[str, str]:
        """The runtime adapter switch (reference: TaskExecutor.java:131-154),
        with JAX as the first-class TPU arm."""
        assert self.bootstrap is not None
        env: dict[str, str] = {
            constants.JOB_NAME: self.job_name,
            constants.TASK_INDEX: str(self.task_index),
            constants.TASK_NUM: str(self.task_num),
            constants.SESSION_ID: self.session_id,
            constants.CLUSTER_SPEC: self.bootstrap["cluster_spec"],
            constants.TB_PORT: str(self.tb_port),
        }
        if self.notebook_port:
            env[constants.NOTEBOOK_PORT] = str(self.notebook_port)
        if getattr(self, "_gcs_token_file", None):
            # scoped GCS identity: the user process reads the token from a
            # FILE the heartbeater refreshes on client-pushed renewals —
            # env alone would freeze the submit-time token into a child
            # that may outlive it
            env[constants.TONY_GCS_TOKEN_FILE] = self._gcs_token_file
        env[constants.CLUSTER_EPOCH] = str(
            self.bootstrap.get("cluster_epoch", 0))
        # Cross-slice pipeline identity + channel endpoints: the
        # coordinator's channel registry told us which stage gang this
        # task belongs to and where its neighbor stages' hubs listen;
        # the trainer opens its tensor channels straight from these
        # (channels.open_stage_links_from_env) — no RPC on the data path.
        from tony_tpu.channels.registry import parse_channel_spec
        ch = parse_channel_spec(self.bootstrap.get("channel_spec", ""))
        if ch is not None:
            env[constants.PIPELINE_STAGE] = str(ch["stage"])
            env[constants.PIPELINE_NUM_STAGES] = str(ch["num_stages"])
            env[constants.PIPELINE_RANK] = str(ch.get("rank", 0))
            env[constants.CHANNEL_PORT] = str(self.channel_port)
            env[constants.CHANNEL_PREV] = ch.get("prev", "")
            env[constants.CHANNEL_NEXT] = ch.get("next", "")
            env[constants.PIPELINE_INTERLEAVE] = str(ch.get("interleave", 1))
            env[constants.CHANNEL_COMPRESSION] = ch.get("compression",
                                                        "none")
        cluster = json.loads(self.bootstrap["cluster_spec"])
        # Multi-slice identity: which gang of the job type this host is in
        # (tony.{job}.slices > 1). Index order is slice-major (session.py).
        # After an elastic shrink the mesh spec carries the SURVIVING
        # gangs' original slice ids in active_slices; this host's slice id
        # becomes its dense rank among them (so e.g. losing slice 0 of 3
        # leaves survivors as slices 0..1 of 2, not 1..2 of 2).
        slice_spec = json.loads(
            self.bootstrap["mesh_spec"] or "{}").get("slice_spec", {})
        mine = slice_spec.get(self.job_name)
        if mine:
            orig = self.task_index // int(mine["hosts_per_slice"])
            active = mine.get("active_slices")
            try:
                sid = active.index(orig) if active else orig
            except ValueError:      # defensive: not listed — keep static id
                sid = orig
            env[constants.SLICE_ID] = str(sid)
            env[constants.NUM_SLICES] = str(mine["slices"])
        # Tracing plumbing for the user process: spans recorded there
        # mirror to the spool file (the heartbeater tails it onto beats);
        # the flight recorder dumps land in the job dir. TONY_TRACE_CTX
        # (the job root trace) is inherited from this executor's own
        # launch environment untouched.
        env[constants.TONY_TRACE_SPOOL] = self.trace_spool
        env[constants.TONY_TRACE_PROC] = self.task_id
        env[constants.TONY_TRACE_SAMPLE_RATE] = str(self._trace_sample)
        env[constants.TONY_TRACE_RING] = str(self._trace_ring)
        env[constants.TONY_FLIGHT_DIR] = os.getcwd()
        env[constants.TONY_FLIGHT_RING] = str(self._flight_ring)
        # Goodput bridge: the user process's ledger publishes its
        # cumulative snapshot here; goodput_snapshot() merges it into
        # the host ledger on each beat.
        env[constants.TONY_GOODPUT_SPOOL] = self.goodput_spool
        if self.conf.get_bool(K.TASK_PROFILE_ENABLED_KEY, False):
            env[constants.TONY_PROFILE_ENABLED] = "true"
            profile_dir = self.conf.get(K.TASK_PROFILE_DIR_KEY) or ""
            if profile_dir:
                env[constants.TONY_PROFILE_DIR] = profile_dir
        framework = (self.conf.get(K.APPLICATION_FRAMEWORK_KEY) or
                     constants.FRAMEWORK_JAX).lower()
        if framework == constants.FRAMEWORK_JAX:
            env[constants.JAX_COORDINATOR_ADDRESS] = self.bootstrap["coordinator_address"]
            env[constants.JAX_PROCESS_ID] = str(self.bootstrap["process_id"])
            env[constants.JAX_NUM_PROCESSES] = str(self.bootstrap["num_processes"])
            env[constants.MESH_SPEC] = self.bootstrap["mesh_spec"]
            if mine:
                # libtpu's DCN-transport contract (what GKE /
                # queued-resources multislice injects): coordinator =
                # slice 0's first host. JAX-only — libtpu reads these at
                # init regardless of framework, and a TF/PT job has no
                # megascale coordinator to point at.
                hosts = cluster.get(self.job_name) or []
                if hosts:
                    env[constants.MEGASCALE_COORDINATOR_ADDRESS] = \
                        hosts[0].rsplit(":", 1)[0]
                env[constants.MEGASCALE_NUM_SLICES] = str(mine["slices"])
                env[constants.MEGASCALE_SLICE_ID] = env[constants.SLICE_ID]
        elif framework == constants.FRAMEWORK_TENSORFLOW:
            # TF_CONFIG assembly (reference: Utils.constructTFConfig:383)
            env[constants.TF_CONFIG] = json.dumps({
                "cluster": cluster,
                "task": {"type": self.job_name, "index": self.task_index},
            })
        elif framework == constants.FRAMEWORK_PYTORCH:
            # tcp:// rendezvous at the first worker (reference:
            # Utils.parseClusterSpecForPytorch:447)
            workers = cluster.get(constants.WORKER_JOB_NAME) or next(
                iter(cluster.values()))
            env[constants.INIT_METHOD] = f"tcp://{workers[0]}"
            env[constants.RANK] = str(self.bootstrap["process_id"])
            env[constants.WORLD] = str(self.bootstrap["num_processes"])
        else:
            raise ValueError(f"unsupported framework: {framework}")
        return env

    # ------------------------------------------------------------------
    @staticmethod
    def _user_process_preexec() -> None:
        """Child-side setup: own session (so the executor can group-kill on
        timeout) + parent-death signal (so the user process dies even when
        the executor itself is SIGKILLed by the backend — without this, a
        coordinator kill_all would orphan the actual training processes,
        which keep the TPU chips and reserved ports busy). Runs in the
        fork→exec window: only syscall wrappers and the pre-resolved libc
        handle, no imports/allocations (fork-safety with Heartbeater live)."""
        os.setsid()
        if _LIBC is not None:
            _LIBC.prctl(_PR_SET_PDEATHSIG, signal.SIGKILL)

    def run_user_process(self, extra_env: dict[str, str]) -> int:
        """Fork-exec the user command via the shell, stream output, wait.
        (reference: Utils.executeShell:263 — 'bash -c <cmd>' with timeout)."""
        env = dict(os.environ)
        env.update(self.shell_env)
        env.update(extra_env)
        timeout_s = self.conf.get_int(K.TASK_EXECUTION_TIMEOUT_KEY, 0) / 1000.0
        log.info("launching user process: %s", self.task_command)
        proc = subprocess.Popen(["bash", "-c", self.task_command], env=env,
                                preexec_fn=self._user_process_preexec)
        # Publish the live proc for the resync interrupter, then re-check
        # the flag: an epoch bump landing between the resync check in
        # run() and the Popen above would otherwise leave a stale-epoch
        # process running forever (the observer only fires on CHANGES).
        with self._user_proc_lock:
            self._user_proc = proc
            resync_raced = self._resync.is_set()
        if resync_raced:
            self._interrupt_user_process()

        def _forward_kill(signum, frame):
            # Backend kills send SIGTERM to the executor's group; the user
            # process lives in its own session, so forward explicitly.
            log.warning("signal %d — killing user process group", signum)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            os._exit(128 + signum)

        prev = signal.signal(signal.SIGTERM, _forward_kill)
        try:
            return proc.wait(timeout=timeout_s if timeout_s > 0 else None)
        except subprocess.TimeoutExpired:
            log.error("user process exceeded %.0fs timeout — killing", timeout_s)
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()
            return constants.EXIT_FAILURE
        finally:
            signal.signal(signal.SIGTERM, prev)
            with self._user_proc_lock:
                self._user_proc = None

    # ------------------------------------------------------------------
    def apply_chaos_after_training(self) -> None:
        """TEST_TASK_EXECUTOR_SKEW='job#idx#ms' and TEST_TASK_EXECUTOR_HANG
        (reference: TaskExecutor.java:301-340)."""
        skew = os.environ.get(constants.TEST_TASK_EXECUTOR_SKEW, "")
        if skew:
            try:
                job, idx, ms = skew.split("#")
                if job == self.job_name and int(idx) == self.task_index:
                    log.info("chaos: skew sleep %sms", ms)
                    time.sleep(int(ms) / 1000.0)
            except ValueError:
                log.warning("malformed %s: %r",
                            constants.TEST_TASK_EXECUTOR_SKEW, skew)
        if os.environ.get(constants.TEST_TASK_EXECUTOR_HANG):
            log.info("chaos: hanging 20s before exit")
            time.sleep(20)

    def _prepare_venv(self) -> str | None:
        """Unzip the staged venv once per host (reference: TaskExecutor.java:
        96-105 unzips venv.zip before exec). All executors of a job share
        the job dir as cwd, so the extraction is crash-safe by atomic
        rename: each racer extracts into its own temp dir and renames it
        into place; losers discard theirs. A winner dying mid-extract leaves
        only a temp dir — never a wedged lock or a partial venv. Returns the
        venv bin dir to prepend to PATH, or None."""
        zip_path = os.path.join(os.getcwd(), constants.TONY_VENV_ZIP)
        if not os.path.exists(zip_path):
            return None
        venv_dir = os.path.join(os.getcwd(), constants.TONY_VENV_DIR)
        if not os.path.isdir(venv_dir):
            import shutil
            tmp = f"{venv_dir}.tmp-{os.getpid()}"
            log.info("unzipping %s → %s", zip_path, venv_dir)
            try:
                self._extract_zip_with_symlinks(zip_path, tmp)
                # Zips built without unix mode bits (plain archivers) leave
                # venv binaries non-executable; ensure bin/* are runnable.
                tmp_bin = os.path.join(tmp, "bin")
                if os.path.isdir(tmp_bin):
                    for name in os.listdir(tmp_bin):
                        p = os.path.join(tmp_bin, name)
                        if os.path.isfile(p) and not os.path.islink(p):
                            os.chmod(p, os.stat(p).st_mode | 0o755)
                os.rename(tmp, venv_dir)
            except OSError:
                if not os.path.isdir(venv_dir):
                    raise      # real extraction failure, not a lost race
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        bin_dir = os.path.join(venv_dir, "bin")
        return bin_dir if os.path.isdir(bin_dir) else None

    @staticmethod
    def _extract_zip_with_symlinks(zip_path: str, dest: str) -> None:
        """ZipFile.extractall writes symlink entries (a real venv's
        bin/python) as text files and drops unix mode bits; extract
        manually, restoring both from external_attr."""
        import stat
        import zipfile
        real_dest = os.path.realpath(dest)
        with zipfile.ZipFile(zip_path) as zf:
            for zi in zf.infolist():
                mode = zi.external_attr >> 16
                target = os.path.join(dest, zi.filename)
                real_target = os.path.realpath(target)
                # prefix check alone would pass sibling dirs sharing the
                # dest prefix ('<dest>x/evil') — require path containment
                if os.path.commonpath([real_dest, real_target]) != real_dest:
                    raise ValueError(f"zip entry escapes dest: {zi.filename}")
                if zi.is_dir():
                    os.makedirs(target, exist_ok=True)
                elif stat.S_ISLNK(mode):
                    os.makedirs(os.path.dirname(target), exist_ok=True)
                    os.symlink(zf.read(zi).decode(), target)
                else:
                    zf.extract(zi, dest)
                    if mode:
                        os.chmod(target, stat.S_IMODE(mode))

    def run(self) -> int:
        log.info("task %s registering with coordinator %s",
                 self.task_id, self.am_address)
        with self._ledger.enter("provision"):
            self.register_and_get_cluster_spec()
        token_file = (self._publish_gcs_token()
                      if os.environ.get(constants.TONY_GCS_TOKEN) else None)
        heartbeater = Heartbeater(self.rpc, self.task_id, self.hb_interval_s,
                                  gcs_token_file=token_file,
                                  snapshot_fn=self.metrics_snapshot,
                                  on_epoch=self._on_cluster_epoch,
                                  spans_fn=self.trace_batch,
                                  reattach_timeout_s=self.reattach_timeout_s,
                                  refresh_rpc=self._refresh_rpc,
                                  on_reattach=self._on_coordinator_restart,
                                  goodput_fn=self.goodput_snapshot)
        heartbeater.incarnation = self.bootstrap.get("incarnation", 0)
        self._heartbeater = heartbeater
        heartbeater.start()
        if (self.job_name == constants.WORKER_JOB_NAME and self.task_index == 0):
            try:
                host = socket.gethostname()
                self.rpc.register_tensorboard_url(f"http://{host}:{self.tb_port}")
            except Exception:
                log.warning("TensorBoard URL registration failed", exc_info=True)
        elif self.notebook_port:
            # Notebook jobs register their HTTP endpoint as the tracking URL
            # so the submitter can proxy to it (reference:
            # NotebookSubmitter.java:93-106 splits the task URL host:port).
            try:
                host = socket.gethostname()
                self.rpc.register_tensorboard_url(
                    f"http://{host}:{self.notebook_port}")
            except Exception:
                log.warning("notebook URL registration failed", exc_info=True)
        with self._ledger.enter("stage"):
            venv_bin = self._prepare_venv()

        def user_env() -> dict[str, str]:
            extra_env = self.framework_env()
            if venv_bin:
                # venv binaries take precedence; the base PATH must honor
                # a user-provided --shell_env PATH (it wins over
                # os.environ in run_user_process's merge).
                base_path = self.shell_env.get("PATH") or os.environ.get(
                    "PATH", "")
                extra_env["PATH"] = venv_bin + os.pathsep + base_path
            return extra_env

        # The elastic resync loop: a cluster-epoch bump (observed on the
        # heartbeat ack) interrupts the user process, re-runs the gang
        # handshake — the barrier holds until every survivor has torn its
        # old jax.distributed world down — and relaunches the user command
        # under the new cluster spec; the trainer restores from its latest
        # completed checkpoint and resumes. The EXECUTOR never exits for a
        # resync, so the slice keeps its staged state and the coordinator
        # keeps its liveness view.
        flight = tracing.get_flight()
        tracer = tracing.get_tracer()
        job_ctx = tracing.parse_env_ctx()
        while True:
            # lifecycle span per user-process GENERATION (elastic
            # resyncs relaunch): coarse, parented on the job root trace
            gen_span = tracer.start_span(
                "executor.user_process", ctx=job_ctx, coarse=True,
                task=self.task_id,
                epoch=self.bootstrap.get("cluster_epoch", 0))
            with self._ledger.enter(goodput_mod.USER_CATEGORY):
                exit_code = self.run_user_process(user_env())
            gen_span.end(exit_code=exit_code)
            flight.record("child_exit", task=self.task_id, code=exit_code,
                          epoch=self.bootstrap.get("cluster_epoch", 0))
            if exit_code == constants.EXIT_GANG_LOST \
                    and not self._resync.is_set():
                # The trainer observed its gang die (collective failure)
                # possibly BEFORE the coordinator's resync directive
                # reached us. Hold the report: under elastic training the
                # epoch bump arrives within a heartbeat or two and we
                # relaunch instead of failing the job; without it (elastic
                # off, or the loss was not absorbable) the wait expires
                # and the exit reports normally — the coordinator has
                # usually decided the session by then anyway.
                wait_s = float(os.environ.get("TONY_GANG_LOST_WAIT_S", "30"))
                log.warning("user process reports gang lost (exit %d) — "
                            "holding up to %.0fs for an elastic resync",
                            exit_code, wait_s)
                self._resync.wait(timeout=wait_s)
            if not self._resync.is_set():
                break
            self._resync.clear()
            flight.record("elastic_resync", task=self.task_id,
                          exit_code=exit_code,
                          target_epoch=self._resync_target)
            log.info("elastic resync: user process stopped (exit %d) — "
                     "re-running the registration handshake", exit_code)
            with self._ledger.enter("resync"):
                self.register_and_get_cluster_spec()
            log.info("elastic resync: re-registered at epoch %d "
                     "(%d processes)",
                     self.bootstrap.get("cluster_epoch", 0),
                     self.bootstrap["num_processes"])
            # A resync raised for an epoch the fresh payload already
            # covers is satisfied — clearing it here stops the loop from
            # killing the about-to-launch process over a stale signal.
            if self._resync.is_set() and self._resync_target <= \
                    self.bootstrap.get("cluster_epoch", 0):
                self._resync.clear()
        metrics_mod.get_default().counter(
            "tony_executor_child_exits_total",
            help="user-process exits by code",
            code=str(exit_code)).inc()
        if exit_code != 0:
            # Abnormal exit: dump the flight ring to the job dir (the
            # postmortem artifact) and stage the tail for the final beat
            # so the coordinator can attach it to the incident's
            # TASK_FINISHED event.
            dump_path = flight.dump(f"child_exit:{exit_code}",
                                    task=self.task_id, code=exit_code)
            self._flight_tail = flight.ship_tail(
                f"child_exit:{exit_code}", dump_path=dump_path)
        self.apply_chaos_after_training()
        heartbeater.stop_event.set()
        # Join before the final beat: an in-flight periodic beat (whose
        # snapshot predates the exit-code counter) landing AFTER the
        # final one would overwrite it in the coordinator's last-
        # snapshot table. Bounded wait — the beat's own RPC deadline.
        heartbeater.join(timeout=15)
        # One explicit final beat so the exit-code counter, the last
        # host stats, the remaining spans AND the incident flight tail
        # reach the coordinator even though the periodic heartbeater is
        # stopping — best-effort, like the result report below. The
        # span batch is drained ONCE and resent verbatim on the second
        # attempt (the coordinator's batch-id dedup makes a double
        # delivery safe; rebuilding would lose the popped flight tail
        # to the first failure — the exact artifact this beat exists to
        # ship). Same back-compat guard as the periodic path: a
        # pre-trace RPC surface gets the metrics-only call instead of a
        # TypeError that would silently lose the beat.
        final_spans = self.trace_batch() if heartbeater._rpc_takes_trace \
            else ""
        for attempt in range(2):
            try:
                if heartbeater._rpc_takes_goodput:
                    # the final ledger snapshot is cumulative, so
                    # rebuilding it per attempt is safe (unlike the
                    # drained span batch)
                    self.rpc.task_executor_heartbeat(
                        self.task_id, self.metrics_snapshot(),
                        spans=final_spans,
                        client_rtt=heartbeater.last_rtt,
                        goodput=self.goodput_snapshot())
                elif heartbeater._rpc_takes_trace:
                    self.rpc.task_executor_heartbeat(
                        self.task_id, self.metrics_snapshot(),
                        spans=final_spans,
                        client_rtt=heartbeater.last_rtt)
                else:
                    self.rpc.task_executor_heartbeat(
                        self.task_id, self.metrics_snapshot())
                break
            except Exception:
                log.debug("final metrics heartbeat failed (attempt %d)",
                          attempt + 1, exc_info=True)
                time.sleep(0.5)
        try:
            self.rpc.register_execution_result(
                exit_code, self.job_name, str(self.task_index), self.session_id)
        except Exception:
            # Informational only — the process exit code is authoritative
            # (reference: TaskExecutor.java:160-163).
            log.warning("could not report execution result", exc_info=True)
        return exit_code


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    parser = argparse.ArgumentParser(prog="tony-task-executor")
    parser.add_argument("--am_address", required=True)
    parser.add_argument("--task_command", required=True)
    parser.add_argument("--conf_file", default=constants.TONY_FINAL_XML)
    parser.add_argument("--shell_env", action="append", default=[],
                        help="k=v pairs forwarded into the user process")
    args = parser.parse_args(argv)
    conf = (TonyConfig.from_file(args.conf_file)
            if os.path.exists(args.conf_file) else TonyConfig())
    shell_env = {}
    for pair in args.shell_env:
        k, _, v = pair.partition("=")
        shell_env[k] = v
    executor = TaskExecutor(args.am_address, args.task_command, conf, shell_env)
    return executor.run()


if __name__ == "__main__":
    code = main()
    # Container exit status is the authoritative task result
    # (reference: TaskExecutor.java:163 System.exit(exitCode)).
    sys.exit(code & 0xFF if code < 0 else min(code, 255))
