"""Cluster scheduling policy: a warm slice pool + a gang job queue.

This module is the PURE core of the multi-tenant daemon
(:mod:`tony_tpu.cluster.daemon`): no threads, no sockets, no clocks of
its own.  Every method takes ``now`` explicitly, so the same policy
code runs under the real daemon loop, the virtual-time SimCluster
harness (:mod:`tony_tpu.cluster.simcluster`), and the bench arm —
1000-job schedules replay deterministically in milliseconds.

Policy (docs/cluster.md §Scheduling policy):

- **Gang scheduling, all-or-nothing.**  A job asks for N slices and is
  granted all N atomically or nothing — a partially-grantable job never
  strands slices it cannot use (``SlicePool.acquire`` is transactional).
- **Priority, then FIFO.**  The queue orders by descending priority,
  then submission sequence.  The head of the queue blocks lower
  entries (head-of-line reservation): freed slices accumulate for the
  blocked head instead of leaking to smaller jobs behind it, so large
  gangs cannot starve.  Quota-blocked jobs are the exception — they
  are skipped, not blocking.
- **Per-user quota.**  A cap on concurrently *granted* slices per user
  (0 = unlimited).  Quota is checked at grant time, so queued jobs of
  an over-quota user simply wait.
- **Warm-pool affinity.**  A freed slice returns to the pool tagged
  with the staging digest of its last occupant (PR 4's
  content-addressed stage).  ``acquire`` prefers digest-matching
  slices, so a back-to-back job with the same artifacts pays ~0.5s
  ALREADY_EXISTS warm adoption instead of full bring-up.
- **Preemption is an induced shrink, never a kill.**  When the blocked
  head outranks running elastic work, the scheduler asks victims to
  *shrink* (PR 6 elastic machinery): a checkpoint fence commits, the
  named slices drain, and only then do they return to the pool.  A
  victim shrunk to zero is requeued with its fence step as the resume
  point — zero committed steps are ever lost.

Every grant runs :meth:`ClusterScheduler.check_invariant` — the
no-slice-double-granted property is asserted on every transition, not
just in tests.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

# -- job states --------------------------------------------------------------
QUEUED = "QUEUED"
RUNNING = "RUNNING"
PREEMPTING = "PREEMPTING"     # checkpoint fence in flight; slices still held
COMPLETED = "COMPLETED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"

TERMINAL_STATES = frozenset({COMPLETED, FAILED, CANCELLED})


class SchedulerError(RuntimeError):
    """Request-scoped scheduling failure (queue full, unknown job)."""


class QueueFullError(SchedulerError):
    """Submission rejected: queue is at ``tony.daemon.queue-limit``."""


class DoubleGrantError(AssertionError):
    """A slice was about to be (or found) granted to two jobs at once.

    This is an invariant violation, not an operational error — it means
    the scheduler's bookkeeping is corrupt, and it is raised eagerly at
    the offending grant so the SimCluster chaos suite (and production)
    fail at the cause, not at a downstream symptom.
    """


@dataclass
class PoolSlice:
    """One TPU slice owned by the daemon's pool.

    ``digest`` is the staging digest of the last occupant — the warm
    tag.  ``job_id`` is the current occupant ("" = free).
    """

    slice_id: str
    digest: str = ""
    job_id: str = ""
    idle_since: float = 0.0


class SlicePool:
    """The daemon's slice inventory with digest-affinity acquisition.

    Not thread-safe by itself — the owning scheduler/daemon serializes
    access.  ``acquire`` is all-or-nothing: it either marks N slices
    busy and returns them, or touches nothing and returns ``None``.
    """

    def __init__(self) -> None:
        self._slices: dict[str, PoolSlice] = {}
        #: cumulative digest-matching grants (mirrors
        #: tony_pool_warm_hits_total)
        self.warm_hits = 0
        #: cumulative granted slices that did NOT match the digest
        self.cold_grants = 0

    # -- inventory ----------------------------------------------------------
    def add(self, slice_id: str, digest: str = "", now: float = 0.0) -> None:
        if slice_id in self._slices:
            raise SchedulerError(f"slice {slice_id!r} already pooled")
        self._slices[slice_id] = PoolSlice(slice_id, digest=digest,
                                           idle_since=now)

    def remove(self, slice_id: str) -> PoolSlice:
        s = self._slices.get(slice_id)
        if s is None:
            raise SchedulerError(f"slice {slice_id!r} not pooled")
        if s.job_id:
            raise SchedulerError(
                f"slice {slice_id!r} is granted to {s.job_id!r}; "
                "cannot remove a busy slice")
        return self._slices.pop(slice_id)

    def get(self, slice_id: str) -> PoolSlice | None:
        return self._slices.get(slice_id)

    def slices(self) -> list[PoolSlice]:
        return list(self._slices.values())

    def size(self) -> int:
        return len(self._slices)

    def free_count(self) -> int:
        return sum(1 for s in self._slices.values() if not s.job_id)

    # -- grant / release ----------------------------------------------------
    def acquire(self, job_id: str, n: int, digest: str = "",
                now: float = 0.0) -> tuple[list[str], int] | None:
        """All-or-nothing: mark ``n`` free slices busy for ``job_id``.

        Preference order: digest-matching first (warm), then the
        longest-idle non-matching slices (so recently-warmed slices
        stay warm for the jobs that can use them).  Returns
        ``(slice_ids, warm_hits)`` or ``None`` when fewer than ``n``
        slices are free (nothing is touched).
        """
        if n <= 0:
            raise SchedulerError(f"job {job_id!r} requested {n} slices")
        free = [s for s in self._slices.values() if not s.job_id]
        if len(free) < n:
            return None
        free.sort(key=lambda s: (
            0 if digest and s.digest == digest else 1,   # warm first
            s.idle_since,                                # then stalest
            s.slice_id))
        picked = free[:n]
        warm = sum(1 for s in picked if digest and s.digest == digest)
        for s in picked:
            if s.job_id:                 # cannot happen unless corrupt
                raise DoubleGrantError(
                    f"slice {s.slice_id!r} already granted to "
                    f"{s.job_id!r} while granting {job_id!r}")
            s.job_id = job_id
        self.warm_hits += warm
        self.cold_grants += n - warm
        return [s.slice_id for s in picked], warm

    def release(self, slice_id: str, digest: str = "",
                now: float = 0.0) -> None:
        s = self._slices.get(slice_id)
        if s is None:
            raise SchedulerError(f"slice {slice_id!r} not pooled")
        s.job_id = ""
        if digest:
            s.digest = digest
        s.idle_since = now

    def reap_idle(self, now: float, idle_s: float) -> list[str]:
        """Remove (and return) free slices idle longer than ``idle_s``.

        The daemon turns these into real teardowns
        (:meth:`~tony_tpu.backend.tpu.TpuSliceBackend.delete_slice_command`);
        busy slices are never reaped.
        """
        reaped = [s.slice_id for s in self._slices.values()
                  if not s.job_id and now - s.idle_since >= idle_s]
        for sid in reaped:
            del self._slices[sid]
        return reaped


@dataclass
class Job:
    """One submitted job as the scheduler sees it.

    ``payload`` is opaque to the policy — the runner (real coordinator
    launch, or the oracle) interprets it.  ``resume_step`` is the
    checkpoint fence a preempted job resumes from; the SimCluster pin
    asserts committed work is never re-done or lost across it.
    """

    job_id: str
    user: str
    slices: int
    priority: int = 0
    digest: str = ""
    elastic: bool = False
    payload: dict = field(default_factory=dict)
    # -- scheduler-owned state ----------------------------------------------
    seq: int = -1
    submitted_at: float = 0.0
    enqueued_at: float = 0.0
    state: str = QUEUED
    granted: list[str] = field(default_factory=list)
    pending_release: list[str] = field(default_factory=list)
    warm_hits: int = 0
    queue_wait_s: float = 0.0
    granted_at: float = 0.0
    finished_at: float = 0.0
    resume_step: int = 0
    preemptions: int = 0

    def snapshot(self) -> dict:
        """JSON-safe status dict (the wire/status/dashboard view)."""
        return {
            "job_id": self.job_id, "user": self.user,
            "slices": self.slices, "priority": self.priority,
            "digest": self.digest, "elastic": self.elastic,
            "state": self.state, "granted": list(self.granted),
            "warm_hits": self.warm_hits,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "resume_step": self.resume_step,
            "preemptions": self.preemptions,
            "submitted_at": self.submitted_at,
        }


@dataclass
class Grant:
    """One gang grant decided by :meth:`ClusterScheduler.tick`."""

    job: Job
    slice_ids: list[str]
    warm_hits: int
    wait_s: float                 # this queued episode's wait


@dataclass
class Shrink:
    """A preemption request: ``job`` must fence a checkpoint, then
    drain ``release_ids``.  ``requeue`` means the job shrinks to zero
    (full preemption) and goes back to the queue with its fence step."""

    job: Job
    release_ids: list[str]
    requeue: bool


class ClusterScheduler:
    """Priority+FIFO gang scheduler over a :class:`SlicePool`.

    Drive it with :meth:`submit` / :meth:`tick` / :meth:`complete` /
    :meth:`preemption_complete`; every mutation is synchronous and
    deterministic.  The owner provides serialization and clocks.
    """

    def __init__(self, pool: SlicePool, queue_limit: int = 1000,
                 user_quota: int = 0) -> None:
        self.pool = pool
        self.queue_limit = queue_limit
        self.user_quota = user_quota
        self.jobs: dict[str, Job] = {}
        self._seq = itertools.count()
        #: cumulative shrink requests issued (mirrors
        #: tony_sched_preemptions_total)
        self.preemptions_total = 0

    # -- queries -------------------------------------------------------------
    def queued_jobs(self) -> list[Job]:
        q = [j for j in self.jobs.values() if j.state == QUEUED]
        q.sort(key=lambda j: (-j.priority, j.seq))
        return q

    def running_jobs(self) -> list[Job]:
        return [j for j in self.jobs.values()
                if j.state in (RUNNING, PREEMPTING)]

    def _user_granted(self, user: str) -> int:
        return sum(len(j.granted) for j in self.jobs.values()
                   if j.user == user and j.state in (RUNNING, PREEMPTING))

    def stats(self) -> dict:
        states: dict[str, int] = {}
        for j in self.jobs.values():
            states[j.state] = states.get(j.state, 0) + 1
        return {
            "queue_depth": states.get(QUEUED, 0),
            "states": states,
            "pool_size": self.pool.size(),
            "pool_free": self.pool.free_count(),
            "warm_hits": self.pool.warm_hits,
            "cold_grants": self.pool.cold_grants,
            "preemptions": self.preemptions_total,
        }

    # -- submission ----------------------------------------------------------
    def submit(self, job: Job, now: float) -> int:
        """Enqueue ``job``; returns its queue position (0-based).

        Raises :class:`QueueFullError` past ``queue_limit`` and
        :class:`SchedulerError` on duplicate ids or gangs larger than
        the whole pool (which could never be granted).
        """
        if job.job_id in self.jobs:
            raise SchedulerError(f"duplicate job id {job.job_id!r}")
        depth = sum(1 for j in self.jobs.values() if j.state == QUEUED)
        if depth >= self.queue_limit:
            raise QueueFullError(
                f"queue is full ({depth}/{self.queue_limit})")
        if job.slices > self.pool.size():
            raise SchedulerError(
                f"job {job.job_id!r} wants {job.slices} slices; pool "
                f"has {self.pool.size()} total — it would queue forever")
        if job.seq < 0:
            job.seq = next(self._seq)
        job.submitted_at = job.enqueued_at = now
        job.state = QUEUED
        self.jobs[job.job_id] = job
        return self.queued_jobs().index(job)

    def cancel(self, job_id: str) -> Job:
        """Cancel a QUEUED job.  Running jobs are cancelled through the
        daemon (which must fence/stop the runner first, then call
        :meth:`complete` with CANCELLED)."""
        job = self._job(job_id)
        if job.state != QUEUED:
            raise SchedulerError(
                f"job {job_id!r} is {job.state}, not QUEUED")
        job.state = CANCELLED
        return job

    # -- the scheduling pass --------------------------------------------------
    def tick(self, now: float) -> tuple[list[Grant], list[Shrink]]:
        """One scheduling pass: grant what fits, shrink what must yield.

        Head-of-line semantics: the first non-quota-blocked queued job
        that cannot be granted blocks everything behind it.  If running
        lower-priority elastic work could cover the shortfall, shrink
        requests are issued (once — a fence already in flight is not
        re-requested); otherwise the head simply waits for completions.
        """
        grants: list[Grant] = []
        shrinks: list[Shrink] = []
        for job in self.queued_jobs():
            if (self.user_quota > 0
                    and self._user_granted(job.user) + job.slices
                    > self.user_quota):
                continue                      # quota-blocked: skip, not block
            res = self.pool.acquire(job.job_id, job.slices,
                                    digest=job.digest, now=now)
            if res is not None:
                ids, warm = res
                wait = now - job.enqueued_at
                job.state = RUNNING
                job.granted = ids
                job.warm_hits += warm
                job.queue_wait_s += wait
                job.granted_at = now
                grants.append(Grant(job, ids, warm, wait))
                self.check_invariant()
                continue
            shrinks.extend(self._cover_shortfall(job))
            break                             # head-of-line reservation
        return grants, shrinks

    def _cover_shortfall(self, head: Job) -> list[Shrink]:
        """Pick shrink victims so ``head`` can eventually be granted.

        Victims are RUNNING elastic jobs of strictly lower priority,
        lowest priority first, youngest first within a priority.  Each
        victim gives up whole slices; the last victim shrinks partially
        when that covers the shortfall (it keeps running at its elastic
        floor of one slice), otherwise it shrinks to zero and requeues
        from its checkpoint fence.  Fences already in flight count
        toward the shortfall, so a slow fence is never double-issued.
        """
        pending = sum(len(j.pending_release) for j in self.jobs.values()
                      if j.state == PREEMPTING)
        needed = head.slices - self.pool.free_count() - pending
        if needed <= 0:
            return []                         # enough already draining
        victims = [j for j in self.jobs.values()
                   if j.state == RUNNING and j.elastic
                   and j.priority < head.priority]
        victims.sort(key=lambda j: (j.priority, -j.seq))
        available = sum(len(j.granted) for j in victims)
        if available < needed:
            return []                         # cannot unblock by preempting
        shrinks: list[Shrink] = []
        for v in victims:
            if needed <= 0:
                break
            if needed < len(v.granted):
                take, requeue = needed, False  # partial: keep elastic floor
            else:
                take, requeue = len(v.granted), True
            release = v.granted[-take:]
            v.state = PREEMPTING
            v.pending_release = list(release)
            v.preemptions += 1
            self.preemptions_total += 1
            shrinks.append(Shrink(v, list(release), requeue))
            needed -= take
        return shrinks

    # -- transitions reported back by the runner ------------------------------
    def preemption_complete(self, job_id: str, now: float,
                            fence_step: int) -> Job:
        """The victim's checkpoint fence committed and its
        ``pending_release`` slices drained: return them to the pool
        (warm-tagged) and either resume the shrunken job or requeue it
        from ``fence_step``."""
        job = self._job(job_id)
        if job.state != PREEMPTING:
            raise SchedulerError(
                f"job {job_id!r} is {job.state}, not PREEMPTING")
        released = job.pending_release
        job.pending_release = []
        for sid in released:
            job.granted.remove(sid)
            self.pool.release(sid, digest=job.digest, now=now)
        job.resume_step = max(job.resume_step, fence_step)
        if job.granted:
            job.state = RUNNING               # partial shrink: keeps running
        else:
            job.state = QUEUED                # full preemption: requeue
            job.enqueued_at = now
        return job

    def complete(self, job_id: str, now: float,
                 status: str = COMPLETED) -> Job:
        """Terminal transition: release every held slice warm-tagged."""
        if status not in TERMINAL_STATES:
            raise SchedulerError(f"not a terminal status: {status!r}")
        job = self._job(job_id)
        if job.state in TERMINAL_STATES:
            raise SchedulerError(f"job {job_id!r} already {job.state}")
        for sid in job.granted:
            self.pool.release(sid, digest=job.digest, now=now)
        job.granted = []
        job.pending_release = []
        job.state = status
        job.finished_at = now
        return job

    # -- invariants -----------------------------------------------------------
    def check_invariant(self) -> None:
        """No slice is ever granted to two jobs; pool and job views
        agree.  Raises :class:`DoubleGrantError` — called at every
        grant and freely callable from tests/chaos harnesses."""
        owners: dict[str, str] = {}
        for job in self.jobs.values():
            if job.state in TERMINAL_STATES:
                continue
            for sid in job.granted:
                prev = owners.get(sid)
                if prev is not None:
                    raise DoubleGrantError(
                        f"slice {sid!r} granted to both {prev!r} and "
                        f"{job.job_id!r}")
                owners[sid] = job.job_id
        for s in self.pool.slices():
            want = owners.pop(s.slice_id, "")
            if s.job_id != want:
                raise DoubleGrantError(
                    f"slice {s.slice_id!r}: pool says occupant "
                    f"{s.job_id!r}, jobs say {want!r}")
        if owners:
            sid, jid = next(iter(owners.items()))
            raise DoubleGrantError(
                f"job {jid!r} holds slice {sid!r} that is not pooled")

    def _job(self, job_id: str) -> Job:
        job = self.jobs.get(job_id)
        if job is None:
            raise SchedulerError(f"unknown job {job_id!r}")
        return job
