"""Durable session journal: the coordinator's write-ahead log.

A coordinator crash used to kill the whole job — every live slice
(minutes of provisioning + staging) was forgotten with the process.
This module makes the expensive-to-rediscover state durable: the
coordinator appends one fsync'd, checksummed record per state
transition (launches, registrations, completions, elastic epochs,
checkpoint watermarks), and a restarted coordinator replays the file
to rebuild its :class:`~tony_tpu.cluster.session.Session` and re-adopt
the still-running executors instead of relaunching them.

Format — one record per line::

    crc32hex SP json LF

where ``crc32hex`` is the zero-padded lowercase CRC-32 of the JSON
bytes, and the JSON is compact with sorted keys (so identical records
are byte-identical). Every append is written in one ``write`` call,
flushed, and ``fsync``'d before the caller proceeds.

Torn-tail policy: because appends are single writes, a crash can only
corrupt the FINAL record (a partial line). Replay therefore tolerates
an invalid final record — it is dropped (and physically truncated when
``truncate_torn=True``) — but an invalid record with valid records
AFTER it cannot be explained by a crash mid-append: that is real
corruption, and replay fails loudly with the byte offset so the fsck
(``python -m tony_tpu.cluster.journal --verify <job_dir>``) can point
at it.

Record kinds (unknown kinds are ignored on fold, so old coordinators
can replay journals written by newer ones):

- ``coordinator_start`` — one per coordinator process; the count IS the
  incarnation id served to executors
- ``rpc_bound`` — the control-plane port, re-bound on restart so
  executors' cached addresses stay valid
- ``launch`` — a task submitted to the backend (allocation id + local
  pid when the backend knows one; the pid is what LocalBackend adopts)
- ``task_registered`` — worker spec + channel port (first registration
  of each task generation)
- ``completion`` / ``task_restart`` — the completion reduction's
  durable shadow
- ``elastic_shrink`` / ``regrow_armed`` / ``regrow_activated`` — the
  elastic plane's epoch transitions
- ``session_reset`` — whole-job retry: per-task state starts over
- ``watermark`` — committed-checkpoint watermarks (named monotonic
  values; the persistent-daemon roadmap item resumes from these)
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import zlib
from dataclasses import dataclass, field

log = logging.getLogger("tony_tpu.journal")

JOURNAL_FILE = "session.journal"


class JournalCorruptError(RuntimeError):
    """An invalid NON-final record: not explicable by a torn append."""

    def __init__(self, path: str, offset: int, reason: str) -> None:
        super().__init__(
            f"{path}: corrupt journal record at byte offset {offset}: "
            f"{reason}")
        self.path = path
        self.offset = offset
        self.reason = reason


def journal_path(job_dir: str) -> str:
    return os.path.join(job_dir, JOURNAL_FILE)


def encode_record(record: dict) -> bytes:
    payload = json.dumps(record, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return b"%08x " % (zlib.crc32(payload) & 0xFFFFFFFF) + payload + b"\n"


def _decode_line(line: bytes) -> tuple[dict | None, str]:
    """(record, "") for a valid line, (None, reason) otherwise."""
    if len(line) < 10 or line[8:9] != b" ":
        return None, "malformed header"
    try:
        want = int(line[:8], 16)
    except ValueError:
        return None, "malformed checksum"
    payload = line[9:]
    if zlib.crc32(payload) & 0xFFFFFFFF != want:
        return None, "checksum mismatch"
    try:
        record = json.loads(payload)
    except ValueError:
        return None, "invalid JSON payload"
    if not isinstance(record, dict) or "k" not in record:
        return None, "record is not a keyed object"
    return record, ""


def scan(path: str) -> tuple[list[dict], int | None, str]:
    """Decode every record; returns (records, torn_offset, torn_reason).

    ``torn_offset`` is None for a clean file, else the byte offset of an
    invalid FINAL record (recoverable by truncation). An invalid record
    with valid data after it raises :class:`JournalCorruptError`.
    """
    with open(path, "rb") as f:
        data = f.read()
    records: list[dict] = []
    offset = 0
    while offset < len(data):
        nl = data.find(b"\n", offset)
        end = nl if nl >= 0 else len(data)
        record, reason = _decode_line(data[offset:end])
        if record is None:
            if nl >= 0 and nl != len(data) - 1:
                raise JournalCorruptError(path, offset, reason)
            return records, offset, reason
        records.append(record)
        if nl < 0:
            break       # valid checksum, just no trailing newline
        offset = nl + 1
    return records, None, ""


def replay(path: str, truncate_torn: bool = False) -> list[dict]:
    """Decode the journal, tolerating (and optionally truncating) a torn
    final record. Raises :class:`JournalCorruptError` on interior
    corruption and propagates ``FileNotFoundError`` for a missing file."""
    records, torn_offset, reason = scan(path)
    if torn_offset is not None:
        log.warning("%s: dropping torn final record at byte offset %d "
                    "(%s)%s", path, torn_offset, reason,
                    " — truncating" if truncate_torn else "")
        if truncate_torn:
            with open(path, "r+b") as f:
                f.truncate(torn_offset)
    return records


@dataclass
class TaskRecord:
    """Folded per-task state (one journaled task generation)."""
    task_id: str
    spec: str = ""
    channel_port: int = 0
    allocation_id: int = -1
    pid: int = 0
    registered: bool = False
    completed: bool = False
    exit_code: int = 0
    restarts: int = 0
    detached: bool = False


@dataclass
class RecoveredState:
    """The deterministic fold of a journal: same records, same state."""
    incarnation: int = 0
    app_id: str = ""
    session_id: int = 0
    cluster_epoch: int = 0
    rpc_port: int = 0
    tasks: dict[str, TaskRecord] = field(default_factory=dict)
    regrow_pending: set[str] = field(default_factory=set)
    watermarks: dict[str, float] = field(default_factory=dict)
    #: coordinator-attributed goodput seconds (task -> category ->
    #: cumulative seconds): launch provision/stage walls, elastic resync
    #: and crash-recovery walls. Restored so a recovered coordinator's
    #: GOODPUT events keep the pre-crash attribution without
    #: re-measuring (= without double-counting) it.
    goodput_extra: dict[str, dict[str, float]] = field(default_factory=dict)

    def live_tasks(self) -> list[TaskRecord]:
        """Tasks whose executor may still be running: registered, not
        completed, not detached — the re-adoption set."""
        return [t for t in self.tasks.values()
                if t.registered and not t.completed and not t.detached]


def fold(records: list[dict]) -> RecoveredState:
    """Reduce a record list to the recovered session state. Pure and
    deterministic: the replay-determinism test pins that the same journal
    always folds to the same state. Unknown record kinds are skipped."""
    state = RecoveredState()

    def task(tid: str) -> TaskRecord:
        return state.tasks.setdefault(tid, TaskRecord(task_id=tid))

    for r in records:
        kind = r.get("k")
        if kind == "coordinator_start":
            state.incarnation += 1
            state.app_id = r.get("app_id", state.app_id)
        elif kind == "rpc_bound":
            state.rpc_port = int(r.get("port", 0))
        elif kind == "session_reset":
            state.session_id = int(r.get("session_id", 0))
            state.cluster_epoch = 0
            state.tasks.clear()
            state.regrow_pending.clear()
            state.goodput_extra.clear()
        elif kind == "launch":
            t = task(r["task_id"])
            t.allocation_id = int(r.get("allocation_id", -1))
            t.pid = int(r.get("pid", 0))
        elif kind == "task_registered":
            t = task(r["task_id"])
            t.spec = r.get("spec", "")
            t.channel_port = int(r.get("channel_port", 0))
            t.registered = True
        elif kind == "completion":
            t = task(r["task_id"])
            t.completed = True
            t.exit_code = int(r.get("exit_code", 0))
        elif kind == "task_restart":
            t = task(r["task_id"])
            t.restarts += 1
            t.registered = False
            t.completed = False
            t.spec = ""
            t.pid = 0
        elif kind == "elastic_shrink":
            state.cluster_epoch = int(r.get("epoch", state.cluster_epoch))
            for tid in r.get("lost", []):
                t = task(tid)
                t.detached = True
                t.completed = True
                t.exit_code = int(r.get("exit_code", -1))
        elif kind == "regrow_armed":
            for tid in r.get("task_ids", []):
                t = task(tid)
                t.registered = False
                t.completed = False
                t.spec = ""
                t.pid = 0
                state.regrow_pending.add(tid)
        elif kind == "regrow_activated":
            state.cluster_epoch = int(r.get("epoch", state.cluster_epoch))
            for tid in r.get("task_ids", []):
                task(tid).detached = False
                state.regrow_pending.discard(tid)
        elif kind == "watermark":
            state.watermarks[r.get("name", "checkpoint")] = r.get("value")
        elif kind == "goodput_extra":
            try:
                cats = state.goodput_extra.setdefault(r["task"], {})
                cat = r["category"]
                cats[cat] = cats.get(cat, 0.0) + float(r["seconds"])
            except (KeyError, TypeError, ValueError):
                pass            # malformed attribution: skip, don't fail replay
    return state


class Journal:
    """Append-side handle. Durability is best-effort-but-loud: an append
    that hits an OSError logs once and disables further journaling (the
    job keeps running — it just loses restartability), instead of
    turning a full disk into a job failure."""

    def __init__(self, job_dir: str, filename: str = JOURNAL_FILE) -> None:
        # ``filename`` lets other planes ride the same WAL format — the
        # cluster daemon keeps its queue/pool/grant log as
        # ``daemon.journal`` next to (never mixed with) job sessions.
        self.path = os.path.join(job_dir, filename)
        self._lock = threading.Lock()
        self._f = None
        self._dead = False

    def append(self, kind: str, **payload) -> None:
        record = dict(payload)
        record["k"] = kind
        with self._lock:
            if self._dead:
                return
            try:
                if self._f is None:
                    self._f = open(self.path, "ab")
                self._f.write(encode_record(record))
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                log.error("session journal append failed — journaling "
                          "disabled (job keeps running, restart recovery "
                          "lost)", exc_info=True)
                self._dead = True
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def main(argv: list[str] | None = None) -> int:
    """Journal fsck: ``python -m tony_tpu.cluster.journal --verify DIR``.

    Exit 0: clean (a recoverable torn tail still counts as clean, and is
    reported). Exit 1: usage / missing file. Exit 2: interior corruption
    — the offset in the message is where recovery would have to stop.
    """
    parser = argparse.ArgumentParser(
        prog="python -m tony_tpu.cluster.journal",
        description="Verify a job dir's session journal.")
    parser.add_argument("--verify", metavar="JOB_DIR", required=True,
                        help="job dir (or journal file) to check")
    args = parser.parse_args(argv)
    path = args.verify
    if os.path.isdir(path):
        path = journal_path(path)
    try:
        records, torn_offset, torn_reason = scan(path)
    except FileNotFoundError:
        print(f"ERROR: no journal at {path}")
        return 1
    except JournalCorruptError as e:
        print(f"CORRUPT: {e}")
        return 2
    state = fold(records)
    print(f"OK: {len(records)} record(s), incarnation {state.incarnation}, "
          f"session {state.session_id}, cluster epoch {state.cluster_epoch},"
          f" rpc port {state.rpc_port}")
    if torn_offset is not None:
        print(f"torn final record at byte offset {torn_offset} "
              f"({torn_reason}) — recoverable by truncation")
    kinds: dict[str, int] = {}
    for r in records:
        kinds[r.get("k", "?")] = kinds.get(r.get("k", "?"), 0) + 1
    for kind in sorted(kinds):
        print(f"  {kind}: {kinds[kind]}")
    for tid in sorted(state.tasks):
        t = state.tasks[tid]
        phase = ("completed" if t.completed and not t.detached
                 else "detached" if t.detached
                 else "running" if t.registered
                 else "launched")
        extra = f" exit={t.exit_code}" if t.completed else ""
        print(f"  task {tid}: {phase} pid={t.pid} "
              f"alloc={t.allocation_id}{extra}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
