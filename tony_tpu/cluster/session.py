"""In-coordinator job state machine.

TPU-native rebuild of the reference's ``TonySession`` (reference: tony-core/
src/main/java/com/linkedin/tony/tensorflow/TonySession.java:1-539). Keeps the
load-bearing semantics intact:

- job-type → task-array bookkeeping, built from config-discovered job types
  (``getContainersRequests:162`` → :meth:`Session.task_requests`)
- cluster-spec assembly from registered host:port specs (``getClusterSpec:227``)
- the registration **gang barrier**: registration returns nothing until every
  expected task has registered (AM-side ``registerWorkerSpec:822-856``)
- per-task exit status + final-status reduction (``onTaskCompleted:252``,
  ``updateSessionStatus:281``)
- chief-failure/-completion short-circuit (``:266-271``, ``isChief:365``)
- untracked job types (ps) excluded from completion counting
- sessions are rebuilt with ``session_id + 1`` on whole-job retry so stale
  events from a previous attempt are ignored (``sessionId`` plumbing)

TPU-first additions: on barrier release the session assigns **dense, stable
JAX process ids** and derives the ``jax.distributed`` coordinator address from
process 0's registered endpoint — the direct replacement for TF_CONFIG
assembly — plus a mesh spec (axes layout) shipped to every task.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from dataclasses import dataclass, field
from enum import Enum

from tony_tpu import constants
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TaskRequest, TonyConfig

log = logging.getLogger(__name__)


class TaskStatus(Enum):
    NEW = "NEW"
    SCHEDULED = "SCHEDULED"
    REGISTERED = "REGISTERED"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"


class SessionStatus(Enum):
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"


@dataclass
class SessionTask:
    """One task of one job type (reference: TonySession.TonyTask:419)."""
    job_type: str
    index: int
    session_id: int
    spec: str = ""                  # "host:port" registered by the executor
    channel_port: int = 0           # inter-gang tensor-channel hub port (0 = none)
    status: TaskStatus = TaskStatus.NEW
    exit_code: int | None = None
    url: str = ""
    process_id: int = -1            # dense JAX process id, assigned at barrier
    allocation_id: int = -1         # backend allocation handle
    registered_at: float = 0.0      # monotonic time of first registration
    completed_at: float = 0.0       # monotonic time of completion report
    restarts: int = 0               # in-session single-task relaunches
    regrows: int = 0                # elastic regrow relaunches
    prior_uptime_s: float = 0.0     # uptime accumulated before restarts
    #: lost to preemption while the session keeps running elastically:
    #: excluded from the cluster spec, the gang barrier, process-id
    #: assignment and the completion reduction — but kept in the task
    #: table (indices are identities) and in uptime accounting, so the
    #: lost capacity stays visible. A regrow re-arms the task and clears
    #: the flag once its replacement registers.
    detached: bool = False

    @property
    def task_id(self) -> str:
        return f"{self.job_type}:{self.index}"

    @property
    def registered(self) -> bool:
        return bool(self.spec)

    @property
    def completed(self) -> bool:
        return self.status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)


class Session:
    """State machine for one attempt of one job."""

    def __init__(self, conf: TonyConfig, session_id: int = 0) -> None:
        self.conf = conf
        self.session_id = session_id
        self.status = SessionStatus.RUNNING
        self.started_at = time.monotonic()
        self.failure_message: str | None = None
        self._lock = threading.RLock()
        self._chief_regex = re.compile(conf.get(K.CHIEF_REGEX_KEY) or "$^")
        self._chief_index = conf.get_int(K.CHIEF_INDEX_KEY, 0)
        self._untracked = conf.untracked_job_types()
        self.requests: dict[str, TaskRequest] = conf.task_requests()
        self.tasks: dict[str, list[SessionTask]] = {
            jt: [SessionTask(jt, i, session_id) for i in range(req.instances)]
            for jt, req in self.requests.items()
        }
        #: cluster-spec generation: bumped on every elastic shrink/regrow;
        #: the heartbeat plane fans the current value out and executors
        #: resync (kill the user process, re-run the handshake) on a bump
        self.cluster_epoch = 0
        #: detached tasks armed for an elastic regrow, awaiting their
        #: replacement's registration before activation
        self._regrow_pending: set[str] = set()
        #: cross-slice MPMD pipeline: job types in stage order
        #: (tony.pipeline.stages); the channel registry wires their
        #: gangs' tensor channels at every barrier release
        self.pipeline_stages: list[str] = conf.pipeline_stages() \
            if hasattr(conf, "pipeline_stages") else []
        #: virtual stages per gang + wire codec, stamped into every
        #: channel spec so stage trainers agree without coordination
        self.pipeline_interleave: int = conf.pipeline_interleave() \
            if hasattr(conf, "pipeline_interleave") else 1
        self.channel_compression: str = conf.channel_compression() \
            if hasattr(conf, "channel_compression") else "none"
        #: task_id → channel-spec dict, rebuilt at each barrier release
        #: (endpoints are only knowable once every stage task registered
        #: its hub port)
        self._channel_specs: dict[str, dict] = {}
        self._mesh_spec = self._build_mesh_spec()
        # allocation-id → task binding (getAndInitMatchingTask:209 analog)
        self._next_allocation_id = 0

    def _build_mesh_spec(self) -> str:
        """Mesh layout + multi-slice topology, shipped opaquely to every
        task (mesh_spec is a JSON string end to end, so slice metadata
        rides the existing RPC field). Task index i of a job type with S
        slices of H hosts each belongs to slice i // H — index order is
        slice-major, matching the dense process-id assignment, so
        in-slice processes are contiguous and ICI-minor mesh axes land on
        ICI neighbors. After an elastic shrink, ``slices`` counts only
        the SURVIVING gangs and ``active_slices`` lists their original
        slice ids (executors map their static index-derived slice id to a
        dense rank among survivors); both recompute on every epoch."""
        slice_spec = {}
        for jt, req in self.requests.items():
            if req.slices <= 1:
                continue
            h = req.instances // req.slices
            active = sorted({t.index // h for t in self.tasks.get(jt, ())
                             if not t.detached})
            entry = {"slices": len(active), "hosts_per_slice": h}
            if active != list(range(req.slices)):
                entry["active_slices"] = active
            slice_spec[jt] = entry
        return json.dumps({
            "axes": self.conf.mesh_axes(),
            "dcn_axes": self.conf.mesh_dcn_axes(),
            **({"slice_spec": slice_spec} if slice_spec else {}),
        })

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------
    def all_tasks(self) -> list[SessionTask]:
        return [t for tasks in self.tasks.values() for t in tasks]

    def participants(self) -> list[SessionTask]:
        """Tasks that make up the CURRENT gang: everything not detached by
        an elastic shrink. The cluster spec, the gang barrier, process-id
        assignment and the completion reduction all run over this set."""
        return [t for t in self.all_tasks() if not t.detached]

    def get_task(self, job_type: str, index: int | str) -> SessionTask:
        return self.tasks[job_type][int(index)]

    def get_task_by_id(self, task_id: str) -> SessionTask:
        jt, _, idx = task_id.partition(":")
        return self.get_task(jt, idx)

    def total_tasks(self) -> int:
        return sum(len(v) for v in self.tasks.values())

    def is_chief(self, job_type: str, index: int | str) -> bool:
        """Reference: TonySession.isChief:365 — the configured chief job name
        (regex, default ^(chief|master)$) at the chief index, or worker:0 when
        no explicit chief type exists."""
        if self._chief_regex.match(job_type):
            return int(index) == self._chief_index
        has_explicit_chief = any(self._chief_regex.match(jt) for jt in self.tasks)
        return (not has_explicit_chief and job_type == constants.WORKER_JOB_NAME
                and int(index) == self._chief_index)

    def is_tracked(self, job_type: str) -> bool:
        return job_type not in self._untracked

    # ------------------------------------------------------------------
    # Registration / gang barrier
    # ------------------------------------------------------------------
    def register_task_spec(self, task_id: str, spec: str,
                           channel_port: int = 0) -> dict | None:
        """Record a task's data-plane endpoint (and, for pipeline jobs,
        its tensor-channel hub port). Returns None until ALL participant
        tasks registered; then a dict with cluster spec + JAX bootstrap.
        Idempotent: re-registration overwrites the spec and re-returns
        the payload. A DETACHED task's registration (its elastic-regrow
        replacement coming up) records the spec but never releases a
        barrier — the coordinator activates the regrow (new epoch,
        everyone re-registers) once every replacement is in."""
        with self._lock:
            task = self.get_task_by_id(task_id)
            task.spec = spec
            if channel_port:
                task.channel_port = channel_port
            if task.status in (TaskStatus.NEW, TaskStatus.SCHEDULED):
                task.status = TaskStatus.REGISTERED
                task.registered_at = time.monotonic()
            if task.detached or not self.barrier_released():
                return None
            self._assign_process_ids()
            # every endpoint is now known — (re)wire the channel registry
            # for this epoch's participant set
            self._channel_specs = self._build_channel_specs()
            for t in self.participants():
                if t.status is TaskStatus.REGISTERED:
                    t.status = TaskStatus.RUNNING
            return self.bootstrap_payload()

    def _build_channel_specs(self) -> dict[str, dict]:
        """The coordinator-owned channel registry: per-task stage
        identity + peer hub endpoints, derived from the registered specs
        (host) and channel ports — see channels/registry.py for the
        pairing rules."""
        if not self.pipeline_stages:
            return {}
        from tony_tpu.channels.registry import build_channel_specs

        def tasks_of(jt: str):
            for t in sorted(self.tasks.get(jt, ()), key=lambda t: t.index):
                if t.detached:
                    continue
                host = t.spec.rsplit(":", 1)[0] if t.spec else ""
                yield t.task_id, host, t.channel_port
        return build_channel_specs(self.pipeline_stages, tasks_of,
                                   interleave=self.pipeline_interleave,
                                   compression=self.channel_compression)

    def channel_spec_for(self, task_id: str) -> str:
        """This worker's channel-registry entry as wire JSON ("" when the
        job has no pipeline or the task is not a stage member)."""
        with self._lock:
            entry = self._channel_specs.get(task_id)
            return json.dumps(entry) if entry else ""

    def barrier_released(self) -> bool:
        return all(t.registered for t in self.participants())

    def _assign_process_ids(self) -> None:
        """Dense, deterministic process ids over the CURRENT participants:
        chief task first (JAX process 0 hosts the distributed coordinator
        service), then remaining tasks in (job_type, index) order. Stable
        across re-registration; reassigned on elastic epoch changes so the
        shrunk/regrown gang stays dense. Detached tasks hold -1."""
        ordered = sorted(
            self.participants(),
            key=lambda t: (not self.is_chief(t.job_type, t.index),
                           t.job_type, t.index))
        for pid, task in enumerate(ordered):
            task.process_id = pid
        for task in self.all_tasks():
            if task.detached:
                task.process_id = -1

    def cluster_spec(self) -> dict[str, list[str]]:
        """{"worker": ["host:port", ...], ...} (getClusterSpec:227) —
        detached tasks' dead endpoints are excluded."""
        return {jt: [t.spec for t in tasks if not t.detached]
                for jt, tasks in self.tasks.items()}

    def coordinator_address(self) -> str:
        """The jax.distributed coordinator endpoint = process 0's registered
        spec (that process starts the coordination service)."""
        for t in self.participants():
            if t.process_id == 0:
                return t.spec
        return ""

    def bootstrap_payload(self) -> dict:
        return {
            "cluster_spec": json.dumps(self.cluster_spec()),
            "coordinator_address": self.coordinator_address(),
            "num_processes": len(self.participants()),
            "mesh_spec": self._mesh_spec,
            "cluster_epoch": self.cluster_epoch,
        }

    def process_id_of(self, task_id: str) -> int:
        return self.get_task_by_id(task_id).process_id

    # ------------------------------------------------------------------
    # Allocation matching (backend → task binding)
    # ------------------------------------------------------------------
    def next_allocation(self, job_type: str) -> SessionTask | None:
        """Bind the next unscheduled task of ``job_type`` to a new allocation
        (reference: getAndInitMatchingTask:209, matching by allocation
        request id; slices/processes arrive per-job-type here)."""
        with self._lock:
            for t in self.tasks.get(job_type, ()):
                if t.status == TaskStatus.NEW:
                    t.status = TaskStatus.SCHEDULED
                    t.allocation_id = self._next_allocation_id
                    self._next_allocation_id += 1
                    return t
            return None

    # ------------------------------------------------------------------
    # Completion reduction
    # ------------------------------------------------------------------
    def on_task_completed(self, job_type: str, index: int | str,
                          exit_code: int, session_id: int | None = None,
                          via_rpc: bool = False) -> None:
        """Record a task exit. Mirrors TonySession.onTaskCompleted:252-276:
        - events from a stale session (previous attempt) are ignored
        - first failure of a *tracked* task fails the whole session
        - chief completion short-circuits the session with the chief's status

        ``via_rpc`` disambiguates the lost-coordinator exit code: a result
        DELIVERED over RPC proves executor->coordinator connectivity, so
        exit 75 from a user process that happens to use EX_TEMPFAIL is not
        mislabeled as a heartbeat loss.
        """
        with self._lock:
            if session_id is not None and session_id != self.session_id:
                log.info("ignoring stale completion from session %s (now %s)",
                         session_id, self.session_id)
                return
            task = self.get_task(job_type, index)
            if task.completed:  # duplicate report (RPC + process exit race)
                return
            task.exit_code = exit_code
            task.status = (TaskStatus.SUCCEEDED if exit_code == 0
                           else TaskStatus.FAILED)
            task.completed_at = time.monotonic()
            if exit_code != 0 and self.is_tracked(job_type):
                self.status = SessionStatus.FAILED
                if (exit_code == constants.EXIT_LOST_COORDINATOR
                        and not via_rpc):
                    # Distinct triage cause: the executor suicided because
                    # heartbeat sends kept failing — infrastructure between
                    # host and coordinator, not the user's training code.
                    self.failure_message = (
                        f"task {task.task_id} lost contact with the "
                        f"coordinator (heartbeat send failures; exit code "
                        f"{exit_code})")
                else:
                    self.failure_message = (
                        f"task {task.task_id} failed with exit code "
                        f"{exit_code}")
            if self.is_chief(job_type, index):
                # Chief done ⇒ job done, with the chief's status
                # (reference :266-271).
                if self.status is SessionStatus.RUNNING:
                    self.status = (SessionStatus.SUCCEEDED if exit_code == 0
                                   else SessionStatus.FAILED)

    def reset_task_for_restart(self, job_type: str,
                               index: int | str) -> SessionTask:
        """Arm a single failed task for an IN-SESSION relaunch — the
        capability the reference marks TODO and answers with a whole-job
        kill (TonyApplicationMaster.java:1158-1159 'so we just kill the
        job'). The task rebinds to a fresh allocation DIRECTLY (SCHEDULED
        — routing through next_allocation could hand the slot to a
        different NEW task), its spec clears so the gang barrier holds new
        registrants until it re-registers, and its finished uptime
        accumulates into prior_uptime_s so the blip stays visible in
        uptime_metrics. The caller (coordinator) owns the budget and the
        non-chief guard."""
        with self._lock:
            t = self.get_task(job_type, index)
            if t.registered_at:
                t.prior_uptime_s += ((t.completed_at or time.monotonic())
                                     - t.registered_at)
            t.restarts += 1
            t.status = TaskStatus.SCHEDULED
            t.allocation_id = self._next_allocation_id
            self._next_allocation_id += 1
            t.spec = ""
            t.exit_code = None
            t.registered_at = 0.0
            t.completed_at = 0.0
            return t

    # ------------------------------------------------------------------
    # Elastic shrink / regrow (epoch transitions)
    # ------------------------------------------------------------------
    def gang_task_ids(self, task_id: str) -> list[str]:
        """Every task id of ``task_id``'s gang (same job type, same slice).
        The slice is the preemption unit — a gang cannot lose one host and
        keep the rest, so elastic detach always operates on this set."""
        jt, _, idx = task_id.partition(":")
        req = self.requests.get(jt)
        if req is None:
            return [task_id]
        h = max(1, req.instances // max(1, req.slices))
        s = int(idx) // h
        return [t.task_id for t in self.tasks.get(jt, ())
                if t.index // h == s]

    def detach_for_preemption(self, task_id: str, exit_code: int = -1) -> None:
        """Record a task as lost to preemption WITHOUT failing the session:
        it leaves the participant set (cluster spec, barrier, reduction)
        but keeps its FAILED status and uptime so the loss stays visible
        in history. The caller owns eligibility (budget, chief, minimum
        survivors) and the subsequent epoch bump."""
        with self._lock:
            task = self.get_task_by_id(task_id)
            if not task.completed:
                task.exit_code = exit_code
                task.status = TaskStatus.FAILED
                task.completed_at = time.monotonic()
            task.detached = True
            task.spec = ""
            self._mesh_spec = self._build_mesh_spec()

    def begin_elastic_resync(self) -> int:
        """Cut a new cluster-spec epoch over the current participants:
        bump the epoch and re-hold the gang barrier by clearing every
        live participant's spec, so no one receives the new payload until
        ALL survivors have stopped their old user process and
        re-registered (their endpoints don't change — the executor keeps
        its reserved data port — but the re-registration IS the proof the
        old jax.distributed world is torn down, so process 0's service
        port is free to rebind). Returns the new epoch."""
        with self._lock:
            self.cluster_epoch += 1
            for t in self.participants():
                if not t.completed:
                    t.spec = ""
            self._mesh_spec = self._build_mesh_spec()
            return self.cluster_epoch

    def arm_regrow(self, task_ids: list[str]) -> list[SessionTask]:
        """Arm detached tasks for relaunch: fresh allocation, cleared
        registration, still DETACHED (their registration must not gate
        the degraded gang's barrier) until :meth:`activate_regrow`."""
        armed = []
        with self._lock:
            for task_id in task_ids:
                t = self.get_task_by_id(task_id)
                if not t.detached:
                    continue
                if t.registered_at:
                    t.prior_uptime_s += ((t.completed_at or time.monotonic())
                                         - t.registered_at)
                t.regrows += 1
                t.status = TaskStatus.SCHEDULED
                t.allocation_id = self._next_allocation_id
                self._next_allocation_id += 1
                t.spec = ""
                t.exit_code = None
                t.registered_at = 0.0
                t.completed_at = 0.0
                self._regrow_pending.add(t.task_id)
                armed.append(t)
        return armed

    def regrow_ready(self) -> bool:
        """True once every armed replacement has registered its spec —
        the moment the coordinator can activate the grow-back epoch."""
        with self._lock:
            if not self._regrow_pending:
                return False
            return all(self.get_task_by_id(tid).registered
                       for tid in self._regrow_pending)

    def activate_regrow(self) -> int:
        """Fold the registered replacements back into the participant set
        and cut the grow-back epoch: replacements keep their fresh specs
        (they are already parked at the barrier, polling), survivors'
        specs clear so they resync — the barrier releases as soon as
        every survivor re-registers. Returns the new epoch."""
        with self._lock:
            pending = self._regrow_pending
            self._regrow_pending = set()
            for tid in pending:
                self.get_task_by_id(tid).detached = False
            self.cluster_epoch += 1
            for t in self.participants():
                if t.task_id not in pending and not t.completed:
                    t.spec = ""
            self._mesh_spec = self._build_mesh_spec()
            return self.cluster_epoch

    def regrow_pending_ids(self) -> set[str]:
        with self._lock:
            return set(self._regrow_pending)

    def abort_regrow(self, task_id: str, exit_code: int = -1) -> None:
        """A replacement died before activation: un-arm it (still
        detached, FAILED again) so a half-dead regrow can never gate the
        grow-back barrier. The coordinator owns requeue/give-up policy."""
        with self._lock:
            self._regrow_pending.discard(task_id)
            t = self.get_task_by_id(task_id)
            t.exit_code = exit_code
            t.status = TaskStatus.FAILED
            t.completed_at = time.monotonic()
            t.spec = ""

    def on_task_deemed_dead(self, task_id: str) -> None:
        """Missed-heartbeat expiry fails the task and thus the session
        (reference: onTaskDeemedDead:1155-1165 — 'we just kill the job')."""
        with self._lock:
            task = self.get_task_by_id(task_id)
            if not task.completed:
                task.status = TaskStatus.FAILED
                task.exit_code = -1
                task.completed_at = time.monotonic()
            self.status = SessionStatus.FAILED
            self.failure_message = f"task {task_id} missed heartbeats, deemed dead"

    def uptime_metrics(self) -> dict:
        """Per-task uptime (registration -> completion/now) and the overall
        tracked-task uptime fraction — the north-star ">90% worker-task
        uptime" metric. The reference's metrics channel existed but was
        always written empty (TonyApplicationMaster.java:408-410); here it
        carries real numbers."""
        with self._lock:
            now = time.monotonic()
            uptimes = {}
            for t in self.all_tasks():
                # prior_uptime_s: runs before an in-session restart — the
                # dead gap between them shows up as a fraction below 1.0
                uptimes[t.task_id] = t.prior_uptime_s + (
                    (t.completed_at or now) - t.registered_at
                    if t.registered_at else 0.0)
            # Uptime fraction is measured over the TRAINING window — first
            # tracked registration to last tracked completion — so scheduler
            # startup latency does not dilute it (a task that died mid-run
            # still shows as a gap). Tracked tasks that NEVER registered
            # count as zero uptime in the denominator: a gang stuck at the
            # barrier because one worker died is 0% training, not 100%.
            tracked = [t for t in self.all_tasks()
                       if self.is_tracked(t.job_type)]
            registered = [t for t in tracked if t.registered_at]
            if registered:
                start = min(t.registered_at for t in registered)
                end = max((t.completed_at or now) for t in registered)
                window = max(end - start, 1e-9)
                fraction = sum(
                    min(uptimes[t.task_id] / window, 1.0)
                    for t in tracked) / len(tracked)
            else:
                window = 0.0
                fraction = 0.0
            metrics = {
                "session_wall_s": round(now - self.started_at, 3),
                "tracked_window_s": round(window, 3),
                "task_uptime_s": {k: round(v, 3)
                                  for k, v in uptimes.items()},
            }
            restarts = {t.task_id: t.restarts for t in self.all_tasks()
                        if t.restarts}
            if restarts:
                metrics["task_restarts"] = restarts
            regrows = {t.task_id: t.regrows for t in self.all_tasks()
                       if t.regrows}
            if regrows:
                metrics["task_regrows"] = regrows
            # Single-node/notebook jobs schedule no tracked tasks; a
            # fraction of 0.0 would render as a misleading "0.0%" uptime
            # for a succeeded job, so the metric is omitted entirely.
            if tracked:
                metrics["tracked_uptime_fraction"] = round(fraction, 4)
            return metrics

    def update_session_status(self) -> SessionStatus:
        """Reduce task states to a final status once all *tracked* tasks are
        done (reference: updateSessionStatus:281)."""
        with self._lock:
            if self.status is not SessionStatus.RUNNING:
                return self.status
            # Detached tasks (lost to preemption, absorbed elastically) are
            # excluded: their FAILED status is capacity accounting, not a
            # job verdict — the surviving participants decide the outcome.
            tracked = [t for t in self.participants()
                       if self.is_tracked(t.job_type)]
            if tracked and all(t.completed for t in tracked):
                failed = [t for t in tracked if t.status is TaskStatus.FAILED]
                self.status = (SessionStatus.FAILED if failed
                               else SessionStatus.SUCCEEDED)
                if failed:
                    self.failure_message = (
                        f"{len(failed)} tracked task(s) failed: "
                        + ", ".join(t.task_id for t in failed))
            return self.status

    def training_finished(self) -> bool:
        return self.update_session_status() is not SessionStatus.RUNNING

    # ------------------------------------------------------------------
    # Task URLs
    # ------------------------------------------------------------------
    def set_task_url(self, job_type: str, index: int | str, url: str) -> None:
        with self._lock:
            self.get_task(job_type, index).url = url

    def task_urls(self) -> list[tuple[str, str, str]]:
        return [(t.job_type, str(t.index), t.url)
                for t in self.all_tasks() if t.url]


def next_session(prev: Session) -> Session:
    """Build the retry session: same conf, session_id + 1 (reference:
    TonyApplicationMaster.reset:570-585 rebuilds the session so stale
    container events are ignored via the id check)."""
    return Session(prev.conf, prev.session_id + 1)
