"""Persistent multi-tenant cluster daemon: one scheduler, many jobs.

The coordinator (:mod:`tony_tpu.cluster.coordinator`) lives and dies
with a single job; this daemon is the long-lived tenant above it.  It
owns a pool of TPU slices and a job queue, grants gangs all-or-nothing,
induces elastic shrinks for cross-job preemption, and keeps freed
slices *warm* (tagged with their staging digest) so back-to-back jobs
pay ~0.5s ALREADY_EXISTS adoption instead of full bring-up — cluster
throughput is scheduling-bound, not bring-up-bound (docs/cluster.md).

Three planes, cleanly separated:

- **Policy** lives in :mod:`tony_tpu.cluster.scheduler` (pure,
  virtual-clock friendly — SimCluster replays 1000-job schedules in
  milliseconds).
- **Wire** rides the TONYS1 framing discipline
  (:mod:`tony_tpu.serving.protocol`): one persistent connection per
  client, rid-multiplexed ``OP``/``REPLY`` JSON frames.  A malformed
  frame is connection-scoped; a bad op (queue full, unknown job) is
  request-scoped.
- **Durability** rides the PR 15 journal format: every queue/pool/grant
  transition is an fsync'd record in ``<home>/daemon.journal``, and a
  SIGKILLed daemon replays it to rebuild its queue (original order),
  its grants (same slice ids), and its pool — zero re-provisioning,
  exactly the coordinator's recovery discipline one level up.

Job execution is behind :class:`JobRunner`: production plugs in real
coordinator launches; tests, bench, and the SIGKILL e2e use
:class:`OracleRunner` — deterministic simulated jobs whose committed
step watermark makes "a preemption loses zero committed steps"
checkable to the step.

Run it::

    python -m tony_tpu.cluster.daemon --home /var/tony --slices 4

The bound port is written to ``<home>/daemon.port`` for clients
(:class:`DaemonClient`, ``python -m tony_tpu.client.cli cluster-*``).
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import os
import threading
import time

from tony_tpu.cluster import journal as J
from tony_tpu.cluster import scheduler as S
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events import events as ev
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.serving import protocol as P
from tony_tpu.serving.server import FrameConn, FrameServerBase

log = logging.getLogger("tony_tpu.daemon")

#: the daemon's WAL, next to (never mixed with) per-job session journals
DAEMON_JOURNAL_FILE = "daemon.journal"
#: where the bound submission port is published for clients
PORT_FILE = "daemon.port"

# Daemon-plane frame types. Same TONYS1 framing (magic, u32 length, u8
# type + u64 rid header) and the same HELLO preamble as the serving
# plane; OP/REPLY live in a distinct type range — the two planes never
# share a connection.
DF_OP = 82          # client -> server: {"op": ..., ...}
DF_REPLY = 83       # server -> client: {"ok": true, ...} | {"ok": false,
#                     "error": str} (request-scoped failure)
DF_NAMES = {P.HELLO: "HELLO", DF_OP: "OP", DF_REPLY: "REPLY"}

WIRE_VERSION = 1


class DaemonError(RuntimeError):
    """Request-scoped daemon-op failure reported over the wire."""


# ---------------------------------------------------------------------------
# Job runners
# ---------------------------------------------------------------------------
class RunnerEvent:
    """One thing the runner observed: a job completed, failed, or
    committed its preemption fence (``step`` = the committed
    watermark)."""

    __slots__ = ("job_id", "kind", "step")
    COMPLETED = "completed"
    FAILED = "failed"
    FENCED = "fenced"

    def __init__(self, job_id: str, kind: str, step: int = 0) -> None:
        self.job_id = job_id
        self.kind = kind
        self.step = step


class JobRunner:
    """Execution adapter: the daemon decides *what* runs where; the
    runner makes it so.  Production wires coordinator launches here;
    :class:`OracleRunner` simulates them deterministically."""

    def start(self, job_id: str, slice_ids: list[str], payload: dict,
              resume_step: int, warm: bool, adopted: bool = False) -> None:
        raise NotImplementedError

    def preempt(self, job_id: str, release_ids: list[str],
                grace_s: float) -> None:
        """Induce a shrink: fence a checkpoint within ``grace_s``, drain
        ``release_ids``, then report a ``FENCED`` event via poll()."""
        raise NotImplementedError

    def stop_job(self, job_id: str) -> None:
        raise NotImplementedError

    def poll(self, now: float) -> list[RunnerEvent]:
        raise NotImplementedError

    def stop(self) -> None:
        """Tear down runner-held resources (daemon shutdown)."""


class _OracleJob:
    __slots__ = ("job_id", "total_steps", "rate", "resume", "run_start",
                 "fence_at", "done")

    def __init__(self, job_id: str, total_steps: int, rate: float,
                 resume: int, run_start: float) -> None:
        self.job_id = job_id
        self.total_steps = total_steps
        self.rate = rate
        self.resume = resume
        self.run_start = run_start       # bring-up already added
        self.fence_at: float | None = None
        self.done = False


class OracleRunner(JobRunner):
    """Deterministic simulated jobs (the SimFleet oracle applied to
    scheduling).

    A job's payload names ``duration_steps`` and ``steps_per_s``; the
    committed watermark at time t is ``resume + floor((t - run_start) *
    steps_per_s)`` (clamped) — a pure function, so every pin about lost
    or re-done work is exact.  Bring-up costs ``warm_adopt_s`` when the
    whole gang matched the staging digest, ``cold_bringup_s`` otherwise
    (PR 4's measured contrast, collapsed to two constants).

    The runner also *asserts the fence contract*: a job restarted after
    a full preemption must resume from exactly the fence step it
    reported — anything else lost or re-did committed work and raises.
    """

    def __init__(self, cold_bringup_s: float = 0.0,
                 warm_adopt_s: float = 0.0,
                 clock=time.time) -> None:
        self.cold_bringup_s = cold_bringup_s
        self.warm_adopt_s = warm_adopt_s
        self._clock = clock
        self._lock = threading.Lock()
        self._jobs: dict[str, _OracleJob] = {}     # guarded-by: _lock
        self._fences: dict[str, int] = {}          # guarded-by: _lock

    def committed(self, job: _OracleJob, now: float) -> int:
        if now <= job.run_start:
            return job.resume
        steps = job.resume + int((now - job.run_start) * job.rate)
        return min(steps, job.total_steps)

    def start(self, job_id: str, slice_ids: list[str], payload: dict,
              resume_step: int, warm: bool, adopted: bool = False) -> None:
        total = int(payload.get("duration_steps", 100))
        rate = float(payload.get("steps_per_s", 1000.0))
        bringup = 0.0 if adopted else (
            self.warm_adopt_s if warm else self.cold_bringup_s)
        now = self._clock()
        with self._lock:
            fence = self._fences.get(job_id)
            if fence is not None and resume_step != fence:
                raise AssertionError(
                    f"job {job_id!r} resumed from step {resume_step}, "
                    f"but its checkpoint fence committed step {fence} — "
                    "committed work was lost or re-done")
            self._jobs[job_id] = _OracleJob(
                job_id, total, rate, resume_step, now + bringup)

    def preempt(self, job_id: str, release_ids: list[str],
                grace_s: float) -> None:
        now = self._clock()
        with self._lock:
            job = self._jobs.get(job_id)
            if job is not None and not job.done:
                job.fence_at = now + grace_s

    def stop_job(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)

    def poll(self, now: float) -> list[RunnerEvent]:
        out: list[RunnerEvent] = []
        with self._lock:
            for job in list(self._jobs.values()):
                if job.done:
                    continue
                if job.fence_at is not None and now >= job.fence_at:
                    step = self.committed(job, job.fence_at)
                    job.fence_at = None
                    self._fences[job.job_id] = step
                    out.append(RunnerEvent(job.job_id,
                                           RunnerEvent.FENCED, step))
                    continue
                if (job.fence_at is None
                        and self.committed(job, now) >= job.total_steps):
                    job.done = True
                    out.append(RunnerEvent(job.job_id,
                                           RunnerEvent.COMPLETED,
                                           job.total_steps))
        return out


# ---------------------------------------------------------------------------
# Journal recovery
# ---------------------------------------------------------------------------
def daemon_journal_path(home_dir: str) -> str:
    return os.path.join(home_dir, DAEMON_JOURNAL_FILE)


def fold_daemon(records: list[dict]) -> dict:
    """Replay daemon journal records into the current queue/pool/grant
    state.  Unknown kinds are ignored (older daemons replay newer
    journals).  Returns ``{"pool", "jobs", "incarnations",
    "preemptions", "max_seq"}`` — everything :class:`ClusterDaemon`
    needs to resume without re-provisioning a single slice."""
    pool = S.SlicePool()
    jobs: dict[str, S.Job] = {}
    incarnations = 0
    preemptions = 0
    max_seq = -1
    for r in records:
        k = r.get("k")
        t = float(r.get("t", 0.0))
        if k == "daemon_start":
            incarnations += 1
        elif k == "slice_added":
            pool.add(r["slice_id"], digest=r.get("digest", ""), now=t)
        elif k == "slice_reaped":
            pool.remove(r["slice_id"])
        elif k == "job_submitted":
            job = S.Job(job_id=r["job_id"], user=r.get("user", ""),
                        slices=int(r["slices"]),
                        priority=int(r.get("priority", 0)),
                        digest=r.get("digest", ""),
                        elastic=bool(r.get("elastic", False)),
                        payload=r.get("payload", {}))
            job.seq = int(r.get("seq", 0))
            job.submitted_at = job.enqueued_at = t
            max_seq = max(max_seq, job.seq)
            jobs[job.job_id] = job
        elif k == "job_granted":
            job = jobs[r["job_id"]]
            job.state = S.RUNNING
            job.granted = list(r["slice_ids"])
            job.warm_hits += int(r.get("warm", 0))
            job.queue_wait_s += float(r.get("wait_s", 0.0))
            job.granted_at = t
            for sid in job.granted:
                slot = pool.get(sid)
                if slot is None or slot.job_id:
                    raise J.JournalCorruptError(
                        "<daemon>", 0,
                        f"job_granted names slice {sid!r} that is "
                        f"{'busy' if slot else 'unknown'}")
                slot.job_id = job.job_id
        elif k == "shrink_requested":
            job = jobs[r["job_id"]]
            job.state = S.PREEMPTING
            job.pending_release = list(r["release_ids"])
            job.preemptions += 1
            preemptions += 1
        elif k == "job_preempted":
            job = jobs[r["job_id"]]
            for sid in job.pending_release:
                job.granted.remove(sid)
                pool.release(sid, digest=job.digest, now=t)
            job.pending_release = []
            job.resume_step = max(job.resume_step,
                                  int(r.get("fence_step", 0)))
            if job.granted:
                job.state = S.RUNNING
            else:
                job.state = S.QUEUED
                job.enqueued_at = t
        elif k in ("job_completed", "job_cancelled"):
            job = jobs[r["job_id"]]
            for sid in job.granted:
                pool.release(sid, digest=job.digest, now=t)
            job.granted = []
            job.pending_release = []
            job.state = r.get("status", S.CANCELLED if
                              k == "job_cancelled" else S.COMPLETED)
            job.finished_at = t
    return {"pool": pool, "jobs": jobs, "incarnations": incarnations,
            "preemptions": preemptions, "max_seq": max_seq}


# ---------------------------------------------------------------------------
# The daemon
# ---------------------------------------------------------------------------
class ClusterDaemon:
    """Owns the pool, the queue, the journal, and the submission wire.

    One loop thread (``tony-daemon-loop``) drives scheduling; RPC
    threads only submit/cancel/read under the same lock.  Every state
    transition is journaled *inside* the lock (append order == state
    order), while runner calls and frame sends happen outside it.
    """

    def __init__(self, home_dir: str, conf: TonyConfig | None = None,
                 slices: int | list[str] = 0,
                 runner: JobRunner | None = None,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 bind_host: str = "127.0.0.1", port: int = 0,
                 history_dir: str | None = None,
                 tick_interval_s: float = 0.02,
                 on_slice_reaped=None,
                 clock=time.time) -> None:
        self.home_dir = home_dir
        self.conf = conf or TonyConfig()
        self.queue_limit = self.conf.get_int(K.DAEMON_QUEUE_LIMIT_KEY, 1000)
        self.user_quota = self.conf.get_int(K.DAEMON_USER_QUOTA_KEY, 0)
        self.preemption_grace_s = self.conf.get_int(
            K.DAEMON_PREEMPTION_GRACE_MS_KEY, 5000) / 1000.0
        self.idle_reap_s = self.conf.get_int(
            K.DAEMON_POOL_IDLE_REAP_MS_KEY, 300000) / 1000.0
        self._initial_slices = slices
        self.runner = runner or OracleRunner(clock=clock)
        self.registry = registry or metrics_mod.MetricsRegistry()
        self._clock = clock
        self._tick_interval_s = tick_interval_s
        self._on_slice_reaped = on_slice_reaped
        #: serializes scheduler/pool mutation between the loop thread
        #: and RPC threads (start() runs before either exists)
        self._lock = threading.Lock()
        self.pool: S.SlicePool | None = None
        self.sched: S.ClusterScheduler | None = None
        self.incarnation = 0
        self.recovered = False
        self._job_ids = 0
        self._journal: J.Journal | None = None
        self._events: ev.EventHandler | None = None
        self._history_dir = history_dir
        self._server = _DaemonServer(self, bind_host, port)
        self._loop_thread: threading.Thread | None = None
        self._stopping = threading.Event()
        self.port = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> int:
        os.makedirs(self.home_dir, exist_ok=True)
        self._recover_or_bootstrap()
        self._journal = J.Journal(self.home_dir,
                                  filename=DAEMON_JOURNAL_FILE)
        self._journal.append("daemon_start", t=self._clock(),
                             incarnation=self.incarnation)
        if self._history_dir:
            # "i<no>" (not a bare number): a trailing pure-digit
            # segment would be stolen by the jhist filename regex as a
            # timestamp
            self._events = ev.EventHandler(
                self._history_dir, f"cluster-daemon-i{self.incarnation}",
                "daemon")
            self._events.start()
        if self.recovered:
            self._readopt_running()
        self.port = self._server.start()
        with open(os.path.join(self.home_dir, PORT_FILE), "w") as f:
            f.write(str(self.port))
        self._loop_thread = threading.Thread(
            target=self._loop, name="tony-daemon-loop", daemon=True)
        self._loop_thread.start()
        log.info("cluster daemon up: port=%d incarnation=%d pool=%d "
                 "(recovered=%s)", self.port, self.incarnation,
                 self.pool.size(), self.recovered)
        return self.port

    def _recover_or_bootstrap(self) -> None:
        path = daemon_journal_path(self.home_dir)
        records: list[dict] = []
        if os.path.exists(path):
            records = J.replay(path, truncate_torn=True)
        if records:
            state = fold_daemon(records)
            self.pool = state["pool"]
            self.sched = S.ClusterScheduler(
                self.pool, queue_limit=self.queue_limit,
                user_quota=self.user_quota)
            self.sched.jobs = state["jobs"]
            self.sched.preemptions_total = state["preemptions"]
            self.sched._seq = itertools.count(state["max_seq"] + 1)
            self._job_ids = len(state["jobs"])
            self.incarnation = state["incarnations"] + 1
            self.recovered = True
            self.sched.check_invariant()
        else:
            self.pool = S.SlicePool()
            self.sched = S.ClusterScheduler(
                self.pool, queue_limit=self.queue_limit,
                user_quota=self.user_quota)
            self.incarnation = 1
            now = self._clock()
            slices = self._initial_slices
            ids = ([f"slice-{i}" for i in range(slices)]
                   if isinstance(slices, int) else list(slices))
            # bootstrap slices are journaled BEFORE daemon_start so a
            # replayed pool is complete by the time grants appear
            boot = J.Journal(self.home_dir, filename=DAEMON_JOURNAL_FILE)
            for sid in ids:
                self.pool.add(sid, now=now)
                boot.append("slice_added", slice_id=sid, digest="", t=now)
            boot.close()

    def _readopt_running(self) -> None:
        """Re-adopt journaled RUNNING/PREEMPTING jobs into the runner —
        their slices exist and their processes are the backend's to
        re-find (PR 15 discipline); the daemon re-provisions nothing."""
        for job in self.sched.running_jobs():
            self.runner.start(job.job_id, list(job.granted), job.payload,
                              job.resume_step, warm=True, adopted=True)
            if job.state == S.PREEMPTING:
                self.runner.preempt(job.job_id, list(job.pending_release),
                                    self.preemption_grace_s)

    def stop(self) -> None:
        self._stopping.set()
        self._server.shutdown()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
        self.runner.stop()
        if self._events is not None:
            self._events.stop("SUCCEEDED")
        if self._journal is not None:
            self._journal.close()

    # -- the scheduling loop --------------------------------------------------
    def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                self.tick_once()
            except Exception:
                # the loop must survive a bad tick — the failure is
                # logged with stack for the postmortem, state stays
                # consistent (transitions are atomic under the lock)
                log.exception("daemon tick failed")
            self._stopping.wait(self._tick_interval_s)

    def tick_once(self) -> None:
        """One scheduling pass — public so tests and the bench arm can
        drive the daemon synchronously."""
        now = self._clock()
        runner_events = self.runner.poll(now)
        emits: list[tuple[str, dict]] = []
        starts: list[S.Grant] = []
        preempts: list[S.Shrink] = []
        stops: list[str] = []
        with self._lock:
            for re_ in runner_events:
                job = self.sched.jobs.get(re_.job_id)
                if job is None or job.state in S.TERMINAL_STATES:
                    continue
                if re_.kind == RunnerEvent.FENCED:
                    requeued = len(job.pending_release) == len(job.granted)
                    released = list(job.pending_release)
                    self.sched.preemption_complete(job.job_id, now,
                                                   re_.step)
                    self._journal.append("job_preempted",
                                         job_id=job.job_id,
                                         fence_step=re_.step, t=now)
                    emits.append((ev.JOB_PREEMPTED, {
                        "job_id": job.job_id, "fence_step": re_.step,
                        "released": released, "requeued": requeued}))
                    if requeued:
                        stops.append(job.job_id)
                else:
                    status = (S.COMPLETED if re_.kind ==
                              RunnerEvent.COMPLETED else S.FAILED)
                    self.sched.complete(job.job_id, now, status)
                    self._journal.append("job_completed",
                                         job_id=job.job_id,
                                         status=status, t=now)
                    emits.append((ev.JOB_COMPLETED, {
                        "job_id": job.job_id, "status": status,
                        "queue_wait_s": round(job.queue_wait_s, 6),
                        "warm_hits": job.warm_hits,
                        "preemptions": job.preemptions}))
            if self.idle_reap_s > 0:
                for sid in self.pool.reap_idle(now, self.idle_reap_s):
                    self._journal.append("slice_reaped", slice_id=sid,
                                         t=now)
                    if self._on_slice_reaped is not None:
                        self._on_slice_reaped(sid)
            grants, shrinks = self.sched.tick(now)
            for g in grants:
                self._journal.append("job_granted", job_id=g.job.job_id,
                                     slice_ids=g.slice_ids, warm=g.warm_hits,
                                     wait_s=round(g.wait_s, 6), t=now)
                emits.append((ev.JOB_GRANTED, {
                    "job_id": g.job.job_id, "slice_ids": g.slice_ids,
                    "warm_hits": g.warm_hits,
                    "queue_wait_s": round(g.wait_s, 6)}))
                starts.append(g)
            for s in shrinks:
                self._journal.append("shrink_requested",
                                     job_id=s.job.job_id,
                                     release_ids=s.release_ids,
                                     requeue=s.requeue, t=now)
                preempts.append(s)
            depth = self.sched.stats()["queue_depth"]
            free = self.pool.free_count()
        # blocking/side-effectful calls happen OUTSIDE the lock
        for g in starts:
            self._observe_grant(g)
            self.runner.start(g.job.job_id, g.slice_ids, g.job.payload,
                              g.job.resume_step,
                              warm=g.warm_hits == len(g.slice_ids))
        for s in preempts:
            self.registry.counter(
                "tony_sched_preemptions_total",
                "Cross-job preemption (induced shrink) requests").inc()
            self.runner.preempt(s.job.job_id, s.release_ids,
                                self.preemption_grace_s)
        for job_id in stops:
            self.runner.stop_job(job_id)
        for etype, payload in emits:
            self._emit(etype, payload)
        self.registry.gauge("tony_sched_queue_depth",
                            "Jobs waiting in the daemon queue").set(depth)
        self.registry.gauge("tony_pool_free_slices",
                            "Free slices in the warm pool").set(free)

    def _observe_grant(self, g: S.Grant) -> None:
        self.registry.histogram(
            "tony_sched_queue_wait_seconds",
            "Queue wait per granted episode").observe(g.wait_s)
        if g.warm_hits:
            self.registry.counter(
                "tony_pool_warm_hits_total",
                "Granted slices whose staging digest matched"
            ).inc(g.warm_hits)
        # queue wait is badput with a name: it joins the goodput
        # ledger's category space so cluster dashboards see one
        # accounting (docs/observability.md §Goodput categories)
        self.registry.counter(
            "tony_goodput_seconds_total",
            "Cumulative attributed seconds by category",
            category="queue_wait").inc(g.wait_s)

    def _emit(self, etype: str, payload: dict) -> None:
        if self._events is not None:
            self._events.emit(etype, **payload)

    # -- ops (wire + in-process) ----------------------------------------------
    def handle_op(self, op: dict) -> dict:
        """Dispatch one client op; raises :class:`DaemonError` for
        request-scoped failures (the server turns those into ok=false
        replies)."""
        kind = op.get("op")
        if kind == "submit":
            return self._op_submit(op)
        if kind == "status":
            return {"job": self._snapshot(op.get("job_id", ""))}
        if kind == "cancel":
            return self._op_cancel(op)
        if kind == "list":
            with self._lock:
                jobs = sorted(self.sched.jobs.values(),
                              key=lambda j: j.seq)
                return {"jobs": [j.snapshot() for j in jobs]}
        if kind == "stats":
            with self._lock:
                st = self.sched.stats()
            st["incarnation"] = self.incarnation
            return {"stats": st}
        raise DaemonError(f"unknown op {kind!r}")

    def _op_submit(self, op: dict) -> dict:
        now = self._clock()
        slices = int(op.get("slices", 1))
        with self._lock:
            job_id = op.get("job_id")
            if not job_id:          # generated ids skip recovered jobs
                while not job_id or job_id in self.sched.jobs:
                    job_id = f"job-{self._job_ids}"
                    self._job_ids += 1
            job = S.Job(job_id=job_id, user=str(op.get("user", "anon")),
                        slices=slices,
                        priority=int(op.get("priority", 0)),
                        digest=str(op.get("digest", "")),
                        elastic=bool(op.get("elastic", False)),
                        payload=dict(op.get("payload") or {}))
            try:
                position = self.sched.submit(job, now)
            except S.SchedulerError as e:
                raise DaemonError(str(e)) from e
            self._journal.append("job_submitted", job_id=job.job_id,
                                 user=job.user, slices=job.slices,
                                 priority=job.priority, digest=job.digest,
                                 elastic=job.elastic, payload=job.payload,
                                 seq=job.seq, t=now)
        self._emit(ev.JOB_QUEUED, {
            "job_id": job.job_id, "user": job.user,
            "priority": job.priority, "slices": job.slices,
            "digest": job.digest})
        return {"job_id": job.job_id, "position": position}

    def _op_cancel(self, op: dict) -> dict:
        job_id = op.get("job_id", "")
        now = self._clock()
        stop_runner = False
        with self._lock:
            job = self.sched.jobs.get(job_id)
            if job is None:
                raise DaemonError(f"unknown job {job_id!r}")
            if job.state == S.QUEUED:
                self.sched.cancel(job_id)
                self._journal.append("job_cancelled", job_id=job_id,
                                     status=S.CANCELLED, t=now)
            elif job.state in (S.RUNNING, S.PREEMPTING):
                self.sched.complete(job_id, now, S.CANCELLED)
                self._journal.append("job_completed", job_id=job_id,
                                     status=S.CANCELLED, t=now)
                stop_runner = True
            else:
                raise DaemonError(f"job {job_id!r} already {job.state}")
            snap = job.snapshot()
        if stop_runner:
            self.runner.stop_job(job_id)
        self._emit(ev.JOB_COMPLETED, {"job_id": job_id,
                                      "status": S.CANCELLED,
                                      "queue_wait_s": snap["queue_wait_s"],
                                      "warm_hits": snap["warm_hits"],
                                      "preemptions": snap["preemptions"]})
        return {"job": snap}

    def _snapshot(self, job_id: str) -> dict:
        with self._lock:
            job = self.sched.jobs.get(job_id)
            if job is None:
                raise DaemonError(f"unknown job {job_id!r}")
            return job.snapshot()


# ---------------------------------------------------------------------------
# Wire: server + client
# ---------------------------------------------------------------------------
class _DaemonServer(FrameServerBase):
    """The submission plane: OP in, REPLY out, rid-multiplexed.  Op
    failures are request-scoped (ok=false with the rid); malformed
    frames are connection-scoped (FrameServerBase closes the
    offender)."""

    def __init__(self, daemon: ClusterDaemon, bind_host: str,
                 port: int) -> None:
        super().__init__(bind_host, port)
        self.daemon = daemon

    def _hello_payload(self) -> dict:
        return {"v": WIRE_VERSION, "daemon_id": "cluster-daemon",
                "incarnation": self.daemon.incarnation}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype != DF_OP:
            raise P.ProtocolError(
                f"unexpected frame type {ftype} on the daemon plane")
        op = P.unpack_json(payload)
        try:
            reply = self.daemon.handle_op(op)
        except DaemonError as e:
            conn.send(DF_REPLY, rid, P.pack_json(
                {"ok": False, "error": str(e)}))
            return
        reply["ok"] = True
        conn.send(DF_REPLY, rid, P.pack_json(reply))

    def _on_conn_closed(self, conn: FrameConn) -> None:
        pass      # submissions are durable server-side; nothing to undo

    def shutdown(self) -> None:
        self._stopping.set()
        self._close_listener()
        self._close_conns()


class DaemonClient:
    """Blocking client for the daemon plane (CLI, tests, bench).

    One socket, sequential rids.  Request-scoped failures raise
    :class:`DaemonError`; transport/protocol failures raise
    :class:`~tony_tpu.serving.protocol.ProtocolError`/``OSError``.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        import socket as socket_mod
        self._sock = socket_mod.create_connection((host, port),
                                                  timeout=timeout_s)
        P.set_nodelay(self._sock)
        self._sock.sendall(P.MAGIC)
        self._rid = 0
        frame = P.recv_frame(self._sock)
        if frame is None or frame[0] != P.HELLO:
            raise P.ProtocolError("daemon sent no HELLO")
        self.hello = P.unpack_json(frame[2])

    @classmethod
    def from_home(cls, home_dir: str, host: str = "127.0.0.1",
                  timeout_s: float = 10.0) -> "DaemonClient":
        with open(os.path.join(home_dir, PORT_FILE)) as f:
            port = int(f.read().strip())
        return cls(host, port, timeout_s)

    def _op(self, **op) -> dict:
        self._rid += 1
        rid = self._rid
        self._sock.sendall(P.encode_frame(DF_OP, rid, P.pack_json(op)))
        while True:
            frame = P.recv_frame(self._sock)
            if frame is None:
                raise P.ProtocolError("daemon closed mid-request")
            ftype, got_rid, payload = frame
            if ftype != DF_REPLY or got_rid != rid:
                continue          # stale reply from a prior timeout
            reply = P.unpack_json(payload)
            if not reply.get("ok"):
                raise DaemonError(reply.get("error", "daemon error"))
            return reply

    def submit(self, user: str = "anon", slices: int = 1,
               priority: int = 0, digest: str = "",
               elastic: bool = False, payload: dict | None = None,
               job_id: str | None = None) -> dict:
        op = {"op": "submit", "user": user, "slices": slices,
              "priority": priority, "digest": digest, "elastic": elastic,
              "payload": payload or {}}
        if job_id:
            op["job_id"] = job_id
        return self._op(**op)

    def status(self, job_id: str) -> dict:
        return self._op(op="status", job_id=job_id)["job"]

    def cancel(self, job_id: str) -> dict:
        return self._op(op="cancel", job_id=job_id)["job"]

    def list_jobs(self) -> list[dict]:
        return self._op(op="list")["jobs"]

    def stats(self) -> dict:
        return self._op(op="stats")["stats"]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "DaemonClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Entry point: python -m tony_tpu.cluster.daemon
# ---------------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tony_tpu.cluster.daemon",
        description="Run the persistent multi-tenant cluster daemon.")
    parser.add_argument("--home", required=True,
                        help="daemon home dir (journal, port file)")
    parser.add_argument("--slices", type=int, default=4,
                        help="bootstrap pool size (fresh start only; a "
                             "recovered daemon replays its pool)")
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--history-dir", default=None,
                        help="emit JOB_* jhist events here for the "
                             "history server's /cluster dashboard")
    parser.add_argument("--conf", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="tony.daemon.* overrides (repeatable)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    conf = TonyConfig()
    for kv in args.conf:
        key, _, value = kv.partition("=")
        conf.set(key, value)
    daemon = ClusterDaemon(args.home, conf=conf, slices=args.slices,
                           bind_host=args.bind, port=args.port,
                           history_dir=args.history_dir)
    daemon.start()
    print(json.dumps({"port": daemon.port,
                      "incarnation": daemon.incarnation,
                      "recovered": daemon.recovered}), flush=True)
    import signal
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.is_set():
        stop.wait(0.2)
    daemon.stop()
    return 0


if __name__ == "__main__":
    sys_exit = main()
    raise SystemExit(sys_exit)
