"""The job coordinator: schedules, monitors, retries the distributed job.

TPU-native rebuild of the reference's ``TonyApplicationMaster`` (reference:
tony-core/src/main/java/com/linkedin/tony/TonyApplicationMaster.java:200-1183).
Structure kept one-for-one where it is load-bearing:

- ``init``/``prepare``: load the frozen config, build the session, start the
  control-plane RPC server (random 10000-15000 port) and event handler
  (:200, :420-463)
- ``start``/``schedule_tasks``: bind tasks to backend allocations and launch
  executors (:520-566); the YARN AMRMClient/NMClient pair collapses into the
  pluggable SchedulerBackend
- ``monitor``: the 0.5s control loop breaking on timeout / client stop /
  training finished / missed heartbeat / all-tracked-done (:591-646)
- retry loop: on failure with retries left, kill everything, rebuild the
  session with session_id+1, relaunch (:351-377, reset:570-585)
- ``stop``: emit APPLICATION_FINISHED, wait up to 30s for the client's
  finishApplication signal, write the final-status file (:669-694)

The coordinator's RPC address is published to the client via
``coordinator.addr`` in the job dir (the YARN application-report channel the
reference used does not exist here). Chaos hooks TEST_AM_CRASH and
TEST_WORKER_TERMINATION are honored in production code (reference :352-357,
:1169-1180)."""

from __future__ import annotations

import argparse
import json
import logging
import os
import shlex
import socket
import sys
import threading
import time

from tony_tpu import constants
from tony_tpu.backend.base import CompletionEvent, LaunchSpec, SchedulerBackend
from tony_tpu.backend.local import LocalBackend
from tony_tpu.cluster import journal as journal_mod
from tony_tpu.cluster.liveness import HeartbeatMonitor
from tony_tpu.cluster.session import (Session, SessionStatus, TaskStatus,
                                      next_session)
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
from tony_tpu.events import events as ev
from tony_tpu.rpc.server import ApplicationRpcServer
from tony_tpu.runtime import goodput as goodput_mod
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.runtime import tracing
from tony_tpu.utils.docker import docker_wrap
from tony_tpu.rpc.service import (ApplicationRpc, ApplicationStatus,
                                  HeartbeatAck, TaskUrl, WorkerSpecResponse)

log = logging.getLogger("tony_tpu.coordinator")

# Re-exported from constants (the backend's stage-digest exclusions need
# the names without importing this module); client code imports them here.
COORDINATOR_ADDR_FILE = constants.COORDINATOR_ADDR_FILE
FINAL_STATUS_FILE = constants.FINAL_STATUS_FILE


def make_backend(conf: TonyConfig, app_id: str = "app") -> SchedulerBackend:
    name = (conf.get(K.SCHEDULER_BACKEND_KEY) or "local").lower()
    if name == "local":
        return LocalBackend()
    if name == "tpu":
        from tony_tpu.backend.tpu import TpuSliceBackend
        return TpuSliceBackend(conf, app_id=app_id)
    raise ValueError(f"unknown scheduler backend: {name}")


class CoordinatorRpc(ApplicationRpc):
    """RPC facade over the coordinator (reference: inner RpcForClient:772)."""

    def __init__(self, coordinator: "Coordinator") -> None:
        self.co = coordinator

    def get_task_urls(self) -> list[TaskUrl]:
        urls = [TaskUrl(n, i, u) for n, i, u in self.co.session.task_urls()]
        if self.co.tensorboard_url:
            # Surface the tracking URL the way YARN surfaced the AM's
            # tracking URL in application reports (reference:
            # TonyApplicationMaster.java:890-906) — the notebook submitter
            # proxies to it (NotebookSubmitter.java:93-106).
            urls.append(TaskUrl(constants.TRACKING_URL_TASK_NAME, "0",
                                self.co.tensorboard_url))
        return urls

    def get_cluster_spec(self, task_id: str) -> str:
        if not self.co.session.barrier_released():
            return ""
        return self.co.session.bootstrap_payload()["cluster_spec"]

    def register_worker_spec(self, worker: str, spec: str,
                             channel_port: int = 0) -> WorkerSpecResponse:
        return self.co.on_register_worker_spec(worker, spec, channel_port)

    def register_tensorboard_url(self, spec: str) -> str:
        self.co.tensorboard_url = spec
        log.info("TensorBoard URL registered: %s", spec)
        return spec

    def register_execution_result(self, exit_code: int, job_name: str,
                                  job_index: str, session_id: str) -> str:
        # Informational early signal; process exit stays authoritative
        # (reference: RpcForClient.registerExecutionResult + container
        # completion both feed onTaskCompleted).
        self.co.record_completion(
            job_name, job_index, exit_code, via_rpc=True,
            session_id=int(session_id) if session_id else None)
        return "RECEIVED"

    def finish_application(self) -> str:
        self.co.client_signalled_finish.set()
        return self.co.final_status or "RUNNING"

    def task_executor_heartbeat(self, task_id: str, metrics: str = "",
                                spans: str = "", client_time: float = 0.0,
                                client_rtt: float = 0.0,
                                goodput: str = "") -> HeartbeatAck:
        self.co.hb_monitor.ping(task_id)
        # A beat from a task the RESTARTED coordinator re-adopted closes
        # that task's recovery wait (no-op outside recovery).
        self.co.note_reattach(task_id)
        if metrics:
            # Telemetry rides the liveness channel but must never break
            # it: ingest validates and drops malformed snapshots (keeping
            # the task's previous good one) instead of raising into the
            # RPC handler.
            self.co.metrics_table.ingest(task_id, metrics)
        # Trace piggyback: clock-offset estimate + span batch. The same
        # discipline — anything malformed is dropped inside, never
        # raised into the handler; the ping above already counted.
        self.co.on_trace_beat(task_id, spans, client_time, client_rtt)
        # Goodput-ledger piggyback: last-snapshot-wins like the metrics
        # table (the wire is cumulative, so retries re-ingest cleanly).
        self.co.on_goodput_beat(task_id, goodput)
        # The ack fans out BOTH slow-moving control values: the current
        # GCS token (renewal) and the cluster-spec epoch — an executor
        # seeing an epoch ahead of its own stops its user process and
        # re-runs the registration handshake (the elastic resync path).
        return HeartbeatAck(
            gcs_token=os.environ.get(constants.TONY_GCS_TOKEN, ""),
            cluster_epoch=self.co.session.cluster_epoch,
            incarnation=self.co.incarnation)

    def renew_gcs_token(self, token: str) -> None:
        # Client-pushed replacement for the expiring impersonation token:
        # landing it in this process's env refreshes the coordinator's own
        # storage calls, future executor launches, AND the value served on
        # every heartbeat response (executors pick it up within one
        # heartbeat interval).
        if token:
            os.environ[constants.TONY_GCS_TOKEN] = token
            log.info("per-job GCS token renewed by client")

    def get_application_status(self) -> ApplicationStatus:
        if self.co.final_status:
            return ApplicationStatus(self.co.final_status,
                                     self.co.failure_message or "",
                                     self.co.session.session_id)
        return ApplicationStatus("RUNNING", "", self.co.session.session_id)


class Coordinator:
    MONITOR_PERIOD_S = 0.2

    def __init__(self, conf: TonyConfig, app_id: str, job_dir: str) -> None:
        self.conf = conf
        self.app_id = app_id
        self.job_dir = os.path.abspath(job_dir)
        self.log_dir = (conf.get(K.CONTAINER_LOG_DIR_KEY) or
                        os.path.join(self.job_dir, constants.TONY_LOG_DIR))
        os.makedirs(self.log_dir, exist_ok=True)
        self.session = Session(conf, session_id=0)
        self.backend = make_backend(conf, app_id)
        # Crash recovery (the session journal): every expensive or
        # undiscoverable transition is journaled write-ahead; a journal
        # left behind by a predecessor WITHOUT a final-status file means
        # that predecessor died mid-job — replay it and re-adopt the
        # still-running gang instead of reprovisioning it.
        self.journal_enabled = conf.get_bool(
            K.COORDINATOR_JOURNAL_ENABLED_KEY, True)
        self.reattach_grace_s = conf.get_int(
            K.COORDINATOR_REATTACH_TIMEOUT_KEY, 30000) / 1000.0
        self._recovered: journal_mod.RecoveredState | None = None
        self._recovery_t0 = 0.0
        #: re-adopted live tasks still silent since the restart; drains as
        #: their executors re-attach (heartbeat or re-registration)
        self._recovery_awaiting: set[str] = set()
        #: everything re-adopted this incarnation (kept after the awaiting
        #: set drains — the goodput recovery-wall attribution set)
        self._recovery_adopted: list[str] = []
        jpath = journal_mod.journal_path(self.job_dir)
        if (self.journal_enabled and os.path.exists(jpath)
                and not os.path.exists(
                    os.path.join(self.job_dir, constants.FINAL_STATUS_FILE))):
            # A torn FINAL record is the only damage a crash mid-append
            # can do — truncated and recovery proceeds. Interior
            # corruption raises out of __init__: restarting on garbage
            # state is worse than failing loudly with the byte offset
            # (the journal fsck points at it).
            state = journal_mod.fold(
                journal_mod.replay(jpath, truncate_torn=True))
            if state.incarnation >= 1:
                self._recovered = state
                self._recovery_t0 = time.monotonic()
        #: coordinator process generation served to executors on every
        #: registration response and heartbeat ack (1 = first process; a
        #: mid-job CHANGE tells executors to re-run the handshake)
        self.incarnation = (self._recovered.incarnation + 1
                            if self._recovered else 1)
        self.journal = (journal_mod.Journal(self.job_dir)
                        if self.journal_enabled else None)
        if self._recovered is not None:
            self._restore_session(self._recovered)
        self.tensorboard_url: str | None = None
        self.final_status: str | None = None
        self.failure_message: str | None = None
        self.client_signalled_finish = threading.Event()
        self.task_missed_hb = threading.Event()
        self._completion_lock = threading.Lock()
        # stop() re-entrancy latch: an Event, NOT a lock — the SIGTERM
        # handler runs on the main thread, possibly while that same
        # thread is already inside stop(), and a lock would self-deadlock
        self._stopping = threading.Event()
        self.retries_left = conf.get_int(K.AM_RETRY_COUNT_KEY, 0)
        # Slice preemption is infrastructure failure: retried from its own
        # budget so user-failure retries (tony.am.retry-count) keep their
        # meaning (SURVEY.md §7 hard part (d)).
        self.preemption_retries_left = conf.get_int(
            K.TPU_PREEMPTION_RETRIES_KEY, 3)
        # Elastic training (tony.elastic.*): a gang lost to preemption (or
        # liveness expiry) is DETACHED instead of failing the session —
        # survivors checkpoint-sync, re-handshake over a bumped
        # cluster-spec epoch, and resume from the latest completed async
        # checkpoint while the lost capacity reprovisions in the
        # background. Losses accumulate for a quiesce window (a preempted
        # slice surfaces as several per-task events) before ONE shrink
        # epoch is cut; losses that fail the eligibility gate (chief gang,
        # minimum survivors, exhausted elastic budget) fall back to the
        # stop-the-world preemption retry path unchanged.
        self.elastic_enabled = conf.get_bool(K.ELASTIC_ENABLED_KEY, False)
        self.elastic_min_tasks = conf.get_int(K.ELASTIC_MIN_TASKS_KEY, 1)
        self.elastic_budget_left = conf.get_int(K.ELASTIC_BUDGET_KEY, 3)
        self.elastic_regrow = conf.get_bool(K.ELASTIC_REGROW_KEY, True)
        self._elastic_regrow_backoff_s = conf.get_int(
            K.ELASTIC_REGROW_BACKOFF_KEY, 1000) / 1000.0
        self._elastic_quiesce_s = conf.get_int(
            K.ELASTIC_QUIESCE_KEY, 300) / 1000.0
        #: task_id → (exit code, preemption-sourced) of completions held
        #: for the quiesce window (guarded by _completion_lock; drained by
        #: the monitor tick). With elastic on, abnormal exits are held too
        #: and triaged as a SET: collateral deaths racing a preemption
        #: event (a survivor crashing on the dead gang's collective) are
        #: charged to the incident, not to user code.
        self._elastic_pending: dict[str, tuple[int, bool]] = {}
        self._elastic_pending_since = 0.0
        #: barrier re-release watch after a shrink/regrow epoch
        self._elastic_awaiting_resume = False
        self._elastic_resume_t0 = 0.0
        #: lost task ids queued for a background regrow relaunch
        self._elastic_regrow_queue: list[str] = []
        self._elastic_regrow_deadline = 0.0
        self._elastic_regrow_attempts: dict[str, int] = {}
        #: losses routed back to stop-the-world: their re-recorded
        #: completions must not re-enter the elastic absorption gate
        self._elastic_bypass: set[str] = set()
        #: detached tasks whose OLD generation's exit report is still in
        #: flight (liveness-absorbed losses and gang-mates seeded without
        #: a completion event): the first post-detach report is that
        #: straggler, not a regrow replacement dying — swallowed exactly
        #: once so it can never abort a healthy regrow
        self._elastic_awaiting_exit: set[str] = set()
        # In-session single-task relaunch budget (tony.task.restart-count):
        # the capability the reference marks TODO and answers with a
        # whole-job kill (TonyApplicationMaster.java:1158-1159).
        self.task_restarts_left = conf.get_int(K.TASK_RESTART_COUNT_KEY, 0)
        #: task_id → (exit code, via_rpc) of a restart-consumed failure:
        #: completions arrive from TWO channels (executor RPC + backend
        #: process exit), and the restart path bypasses the completed-flag
        #: dedupe, so the twin report — same code, the OTHER channel —
        #: must be swallowed once (see record_completion).
        self._restart_dup: dict[str, tuple[int, bool]] = {}
        self._user_command: str = ""
        self._session_preempted = False
        self._session_real_failure = False
        self.timeout_s = conf.get_int(K.APPLICATION_TIMEOUT_KEY, 0) / 1000.0
        self.hb_monitor = HeartbeatMonitor(
            conf.get_int(K.TASK_HEARTBEAT_INTERVAL_KEY, 1000),
            conf.get_int(K.TASK_MAX_MISSED_HEARTBEATS_KEY, 25),
            self._on_task_dead)
        # Per-job auth (ClientToAMToken analog): the client generates the
        # secret at submission and passes it via env; when set, every RPC
        # (client and executors) must present it.
        self.secret = os.environ.get(constants.TONY_SECRET) or None
        # Per-job TLS (rpc/tls.py): the client generated key+cert at
        # submission and passes the staged paths via env; the server side
        # needs both, executors get the cert only.
        self.tls_cert = os.environ.get(constants.TONY_TLS_CERT) or None
        self.tls_key = os.environ.get(constants.TONY_TLS_KEY) or None
        tls = (self.tls_key, self.tls_cert) \
            if self.tls_cert and self.tls_key else None
        # Port continuity across restarts: executors cache the coordinator
        # address, so a recovered coordinator first tries the journaled
        # port — re-attaching executors then never even notice the address
        # changed. If something else grabbed the port during the outage,
        # fall back to a fresh one; executors recover via the re-published
        # coordinator.addr file (_refresh_rpc on their side).
        self.rpc_server = None
        if self._recovered is not None and self._recovered.rpc_port:
            try:
                self.rpc_server = ApplicationRpcServer(
                    CoordinatorRpc(self), port=self._recovered.rpc_port,
                    secret=self.secret, tls=tls)
            except OSError:
                log.warning(
                    "journaled RPC port %d is taken — binding a fresh one "
                    "(executors will re-resolve via %s)",
                    self._recovered.rpc_port, COORDINATOR_ADDR_FILE)
        if self.rpc_server is None:
            self.rpc_server = ApplicationRpcServer(CoordinatorRpc(self),
                                                   secret=self.secret,
                                                   tls=tls)
        history_dir = ev.HistoryDirs.from_conf(conf).intermediate
        self.events = ev.EventHandler(history_dir, app_id,
                                      os.environ.get("USER", "unknown"))
        self._workers_terminated = False
        self._preprocess_proc = None
        self._session_metrics: list[dict] = []   # prior attempts' uptimes
        # Per-task last heartbeat-shipped metrics snapshot (the
        # TaskMonitor table analog), folded into METRICS_SNAPSHOT jhist
        # events on the configured cadence by the monitor loop.
        self.metrics_table = metrics_mod.SnapshotTable()
        self._metrics_interval_s = conf.get_int(
            K.METRICS_SNAPSHOT_INTERVAL_KEY, 5000) / 1000.0
        self._metrics_last_emit = time.monotonic()
        # Tracing plane: the coordinator's own tracer (bring-up spans,
        # elastic incidents, the job root) plus the fold point for every
        # executor's heartbeat-shipped span batches. Per-task clock
        # offsets (heartbeat-RTT-midpoint estimates) are applied to span
        # timestamps AT EXPORT, so the jhist trace is on the
        # coordinator's clock.
        try:
            trace_sample = float(
                conf.get(K.TRACE_SAMPLE_RATE_KEY) or "1.0")
        except ValueError:
            trace_sample = 1.0
        self.tracer = tracing.configure(
            proc=f"{constants.COORDINATOR_JOB_NAME}:0",
            sample_rate=trace_sample,
            ring_size=conf.get_int(K.TRACE_RING_KEY, 2048),
            flight_dir=self.job_dir,
            flight_ring=conf.get_int(K.FLIGHT_RING_KEY, 256))
        self.job_span: tracing.Span | None = None
        self._trace_lock = threading.Lock()
        #: (task_id, [span wire dicts]) batches awaiting a TRACE_SPAN emit
        self._trace_pending: list[tuple[str, list[dict]]] = []
        self._trace_pending_spans = 0
        #: task_id -> last ingested batch id (heartbeat-retry dedup)
        self._trace_last_batch: dict[str, str] = {}
        self.clock_offsets: dict[str, float] = {}
        self.trace_rejects = 0
        # Goodput plane: last heartbeat-shipped ledger wire per task
        # (cumulative → last-snapshot-wins, like the metrics table) plus
        # the seconds only the COORDINATOR can attribute (launch
        # provision/stage walls, elastic resync, crash recovery), which
        # are journaled so a restarted coordinator keeps them without
        # re-measuring. Folded into GOODPUT jhist events on the metrics
        # cadence; the straggler detector ticks on its own window.
        self._goodput_lock = threading.Lock()
        self._goodput_wires: dict[str, dict] = {}
        # _restore_session (above) may already have repopulated the
        # journaled attributions — keep them.
        self._goodput_extra: dict[str, dict[str, float]] = getattr(
            self, "_goodput_extra", {})
        self.goodput_rejects = 0
        self._goodput_window_s = conf.get_int(
            K.GOODPUT_WINDOW_MS_KEY, 2000) / 1000.0
        self._goodput_last_tick = time.monotonic()
        try:
            straggler_factor = float(
                conf.get(K.STRAGGLER_FACTOR_KEY) or "2.0")
        except ValueError:
            straggler_factor = 2.0
        self.straggler = goodput_mod.StragglerDetector(
            factor=straggler_factor,
            windows=conf.get_int(K.STRAGGLER_WINDOWS_KEY, 3))
        #: task_id -> last flight-recorder tail shipped on a beat; popped
        #: into the task's incident TASK_FINISHED event
        self._flight_tails: dict[str, dict] = {}
        #: open elastic-recovery span (shrink -> barrier re-release)
        self._elastic_span: tracing.Span | None = None
        # Launch fan-out (tony.launch.max-concurrent): schedule_tasks
        # dispatches backend launches on semaphore-bounded DAEMON threads
        # so an N-gang bring-up costs max-of-gangs wall, not sum. Daemon
        # on purpose — ThreadPoolExecutor's non-daemon workers are joined
        # by an atexit hook, which would hold a killed coordinator
        # process hostage to a minutes-long in-flight gcloud create even
        # after stop()'s bounded drain gave up. The inflight counter +
        # condition lets session resets / teardown drain launches before
        # the kill sweep.
        self._launch_sema: threading.BoundedSemaphore | None = None
        self._launch_lock = threading.Lock()
        self._launch_cv = threading.Condition(self._launch_lock)
        self._launch_inflight = 0
        self._launch_errors: list[str] = []

    # ------------------------------------------------------------------
    # Crash recovery (session journal)
    # ------------------------------------------------------------------
    def _journal_append(self, kind: str, **payload) -> None:
        if self.journal is not None:
            self.journal.append(kind, **payload)

    def _restore_session(self, state: journal_mod.RecoveredState) -> None:
        """Rebuild the session from the journal fold (__init__ time —
        nothing else is running yet). Each task comes back in the phase
        it was journaled in: completed, registered-live (RUNNING, spec
        intact, so the gang barrier stays released), launched-but-silent
        (SCHEDULED), or detached. Restored tasks KEEP their allocations
        — next_allocation only binds NEW tasks, so the recovered session
        launches nothing: zero slice re-provisions."""
        now = time.monotonic()
        self.session = Session(self.conf, session_id=state.session_id)
        self.session.cluster_epoch = state.cluster_epoch
        max_alloc = -1
        for tid in sorted(state.tasks):
            rec = state.tasks[tid]
            try:
                task = self.session.get_task_by_id(tid)
            except (KeyError, IndexError, ValueError):
                log.warning("journaled task %s is not in the current "
                            "config — skipped", tid)
                continue
            max_alloc = max(max_alloc, rec.allocation_id)
            task.allocation_id = rec.allocation_id
            task.restarts = rec.restarts
            if rec.detached:
                task.detached = True
                task.exit_code = rec.exit_code
                task.status = TaskStatus.FAILED
                task.completed_at = now
            elif rec.completed:
                task.exit_code = rec.exit_code
                task.status = (TaskStatus.SUCCEEDED if rec.exit_code == 0
                               else TaskStatus.FAILED)
                task.completed_at = now
            elif rec.registered:
                task.spec = rec.spec
                task.channel_port = rec.channel_port
                task.status = TaskStatus.RUNNING
                # nonzero registered_at: the executor's re-registration
                # takes the NON-first path (no duplicate TASK_REGISTERED
                # event, the barrier stays released)
                task.registered_at = now
            elif rec.allocation_id >= 0:
                task.status = TaskStatus.SCHEDULED
        self.session._next_allocation_id = max_alloc + 1
        self.session._regrow_pending = set(state.regrow_pending)
        # Journaled goodput attributions come back as-is (set directly,
        # NOT via _note_goodput_extra — re-journaling them would double
        # the seconds on the next replay).
        self._goodput_extra = {tid: dict(cats) for tid, cats
                               in state.goodput_extra.items()}
        if self.session.barrier_released():
            self.session._assign_process_ids()
            self.session._channel_specs = self.session._build_channel_specs()
            self.session._mesh_spec = self.session._build_mesh_spec()
        log.info("journal replay: restored session %d at cluster epoch %d "
                 "(%d journaled task(s), %d live)", state.session_id,
                 state.cluster_epoch, len(state.tasks),
                 len(state.live_tasks()))

    def _adopt_recovered(self) -> None:
        """Re-adopt the predecessor's live tasks (run() time — events,
        RPC server and liveness monitor are all up). Backend adoption
        where the backend supports it (LocalBackend probes the journaled
        pid), liveness registration with one full re-attach window of
        grace — the outage was OURS, a silent executor is still backing
        off toward us — and the COORDINATOR_RESTART history event, after
        which zero TASK_SCHEDULED events is the history-visible proof
        that recovery launched nothing."""
        state = self._recovered
        assert state is not None
        live = sorted(t.task_id for t in state.live_tasks())
        completed = sum(1 for t in state.tasks.values()
                        if t.completed and not t.detached)
        metrics_mod.get_default().counter(
            "tony_coordinator_restarts_total",
            help="coordinator processes that recovered a prior session "
                 "from the journal").inc()
        tracing.get_flight().record(
            "coordinator_restart", incarnation=self.incarnation,
            adopted=",".join(live), completed=completed)
        self.events.emit(ev.COORDINATOR_RESTART,
                         incarnation=self.incarnation, adopted=live,
                         completed=completed,
                         session_id=self.session.session_id)
        adopt = getattr(self.backend, "adopt", None)
        for tid in sorted(state.tasks):
            rec = state.tasks[tid]
            if rec.completed or rec.detached:
                continue
            if adopt is not None and rec.pid:
                adopt(tid, rec.pid)
            if rec.registered:
                self.hb_monitor.register(tid, grace_s=self.reattach_grace_s)
                self._recovery_awaiting.add(tid)
                self._recovery_adopted.append(tid)
        log.warning(
            "coordinator restart (incarnation %d): recovered session %d "
            "at epoch %d — re-adopted %d live task(s) %s, %d already "
            "completed; awaiting executor re-attach", self.incarnation,
            self.session.session_id, self.session.cluster_epoch,
            len(live), live, completed)

    def note_reattach(self, task_id: str) -> None:
        """An executor from before the restart made contact (heartbeat
        or re-registration). When the last awaited one arrives, the
        recovery wall — coordinator start to full re-attachment — is
        recorded. Set ops are GIL-atomic; no lock needed."""
        if task_id not in self._recovery_awaiting:
            return
        self._recovery_awaiting.discard(task_id)
        remaining = len(self._recovery_awaiting)
        log.info("executor %s re-attached (%d still awaited)", task_id,
                 remaining)
        if remaining:
            return
        wall = time.monotonic() - self._recovery_t0
        log.info("all executors re-attached %.2fs after coordinator start",
                 wall)
        metrics_mod.get_default().gauge(
            "tony_coordinator_recovery_seconds",
            help="wall seconds from coordinator restart to every live "
                 "executor re-attaching (last recovery)").set(wall)
        tracing.get_flight().record("coordinator_recovered",
                                    wall_s=round(wall, 3))
        # Goodput: each adopted task paid the recovery wall (coordinator
        # start → full re-attachment). Attributed (and journaled) ONCE,
        # here — a later coordinator restart replays the journal record
        # instead of re-measuring, so the window never double-counts.
        for tid in self._recovery_adopted:
            self._note_goodput_extra(tid, "recovery", wall)

    # ------------------------------------------------------------------
    # RPC-driven hooks
    # ------------------------------------------------------------------
    def on_register_worker_spec(self, worker: str, spec: str,
                                channel_port: int = 0) -> WorkerSpecResponse:
        try:
            task = self.session.get_task_by_id(worker)
        except (KeyError, IndexError):
            log.warning("registration from unknown task %r ignored", worker)
            return WorkerSpecResponse()
        # First registration of this task GENERATION: keyed on
        # registered_at (reset by restart/regrow arming), not on the spec
        # — elastic resyncs clear every survivor's spec to re-hold the
        # barrier, and re-running the first-registration side effects
        # (TASK_REGISTERED events, monitor registration) once per epoch
        # would double-count registrations in the history timeline.
        first_registration = task.registered_at == 0.0
        # The relaunched generation is registering: its predecessor's twin
        # report either arrived already or was discarded by the backend on
        # relaunch — retire the marker so it can never swallow THIS
        # generation's own failure report.
        with self._completion_lock:
            self._restart_dup.pop(worker, None)
            # a restarted/regrown generation starts with a clean elastic
            # slate — its earlier replayed failure must not block a later
            # genuine absorption
            self._elastic_bypass.discard(worker)
        payload = self.session.register_task_spec(worker, spec,
                                                  channel_port)
        if not first_registration:
            # Barrier re-polls count as liveness: an executor waiting at the
            # gang barrier has no Heartbeater yet, and slow allocations
            # elsewhere must not expire it.
            self.hb_monitor.ping(worker)
            # A re-registration from a task restored as already-registered
            # is the re-attach handshake after a coordinator restart.
            self.note_reattach(worker)
        else:
            self.hb_monitor.register(worker)
            self._journal_append("task_registered", task_id=worker,
                                 spec=spec, channel_port=channel_port)
            self.events.emit(ev.TASK_REGISTERED, task=worker, spec=spec,
                             session_id=self.session.session_id)
            self.session.set_task_url(
                task.job_type, task.index,
                "file://" + os.path.join(
                    self.log_dir,
                    f"{constants.task_log_stem(worker)}.stdout"))
            # Chaos: kill the non-chief workers once the chief registers
            # (reference: TonyApplicationMaster.java:1169-1180) — simulates
            # losing part of the gang.
            if (os.environ.get(constants.TEST_WORKER_TERMINATION)
                    and self.session.is_chief(task.job_type, task.index)
                    and not self._workers_terminated):
                self._workers_terminated = True
                threading.Thread(target=self._terminate_workers,
                                 name="tony-terminate-workers",
                                 daemon=True).start()
        if payload is None:
            return WorkerSpecResponse()
        return WorkerSpecResponse(
            spec=payload["cluster_spec"],
            coordinator_address=payload["coordinator_address"],
            process_id=self.session.process_id_of(worker),
            num_processes=payload["num_processes"],
            mesh_spec=payload["mesh_spec"],
            cluster_epoch=payload.get("cluster_epoch", 0),
            channel_spec=self.session.channel_spec_for(worker),
            incarnation=self.incarnation)

    def _terminate_workers(self) -> None:
        time.sleep(0.5)
        for task in self.session.all_tasks():
            if not self.session.is_chief(task.job_type, task.index) \
                    and self.session.is_tracked(task.job_type):
                log.info("chaos: terminating %s", task.task_id)
                self.backend.kill_task(task.task_id)

    def _on_task_dead(self, task_id: str) -> None:
        """Missed-heartbeat expiry (reference: onTaskDeemedDead:1155-1165).
        Recorded into the coordinator's flight ring either way — expiry
        is exactly the kind of incident a postmortem wants sequenced.
        With elastic training on, a tracked task going silent is treated
        as its GANG being lost (a slice dies as a unit — the silent host
        took its co-hosts' ICI domain with it): the whole gang is killed
        and absorbed into the shrink path instead of failing the job."""
        tracing.get_flight().record("missed_heartbeat", task=task_id)
        with self._completion_lock:
            absorb = self._elastic_can_absorb(task_id)
            if absorb:
                self._elastic_note_gang_loss(task_id, exit_code=-1,
                                             from_completion=False)
        if absorb:
            # kills run OUTSIDE the lock (backend kill paths can block)
            for tid in self.session.gang_task_ids(task_id):
                self.backend.kill_task(tid)
            return
        self.session.on_task_deemed_dead(task_id)
        self.task_missed_hb.set()

    # ------------------------------------------------------------------
    # Elastic shrink / regrow
    # ------------------------------------------------------------------
    def _elastic_can_absorb(self, task_id: str) -> bool:
        """Cheap gate at loss-report time (callers hold _completion_lock);
        the full eligibility check (chief gang, per-type survivors,
        minimum tasks) runs once per shrink epoch over the accumulated
        set, falling back to stop-the-world when it fails."""
        if not self.elastic_enabled or self.elastic_budget_left <= 0:
            return False
        try:
            task = self.session.get_task_by_id(task_id)
        except (KeyError, IndexError, ValueError):
            return False
        return (self.session.is_tracked(task.job_type)
                and not task.completed and not task.detached
                and self.session.status is SessionStatus.RUNNING
                and not self.task_missed_hb.is_set()
                and self.final_status is None
                and not self.client_signalled_finish.is_set())

    def _elastic_note_gang_loss(self, task_id: str, exit_code: int,
                                from_completion: bool = True) -> None:
        """Queue the whole gang of ``task_id`` for the next shrink epoch
        (callers hold _completion_lock). Gang-mates' own completion events
        land here too and just refresh their recorded exit code. Tasks
        queued WITHOUT a consumed completion event (liveness expiries,
        seeded gang-mates) are marked awaiting-exit: their old
        generation's report is still in flight and must not be mistaken
        for a regrow replacement dying later."""
        if not self._elastic_pending:
            self._elastic_pending_since = time.monotonic()
        for tid in self.session.gang_task_ids(task_id):
            try:
                t = self.session.get_task_by_id(tid)
            except (KeyError, IndexError):
                continue
            if t.detached or t.completed:
                continue
            if tid not in self._elastic_pending:
                self._elastic_pending[tid] = (exit_code, True)
                self._elastic_awaiting_exit.add(tid)
            self.hb_monitor.unregister(tid)
        if task_id in self._elastic_pending:
            # the reporting task's own exit code wins over the placeholder
            # its gang-mate's report seeded
            self._elastic_pending[task_id] = (exit_code, True)
            if from_completion:
                self._elastic_awaiting_exit.discard(task_id)

    def _elastic_note_abnormal(self, task_id: str, exit_code: int) -> None:
        """Hold a NON-preempted abnormal exit for the quiesce window
        (callers hold _completion_lock): if a preemption incident
        materializes in the same window, this death was collateral (the
        survivor's collectives failed on the dead gang) and is charged to
        the incident; otherwise the tick replays it as the ordinary user
        failure it was, delayed by at most the quiesce interval. Only the
        task itself is held — a PURE user failure must not take its
        healthy gang-mates with it (if the window does turn into an
        incident, the shrink expands every loss to its gang closure:
        slices are atomic)."""
        if not self._elastic_pending:
            self._elastic_pending_since = time.monotonic()
        self._elastic_pending[task_id] = (exit_code, False)
        self.hb_monitor.unregister(task_id)

    def _on_detached_completion(self, task, exit_code: int) -> None:
        """A detached task completed (callers hold _completion_lock): if it
        was a regrow replacement dying before activation, un-arm it and
        requeue the regrow with backoff (bounded — after 3 failed
        replacement launches the job just keeps running degraded)."""
        if task.task_id in self._elastic_awaiting_exit:
            # the killed OLD generation's exit report finally landing —
            # expected exactly once per detach; it must not be mistaken
            # for the regrow replacement dying (which would abort a
            # healthy regrow and burn a give-up attempt)
            self._elastic_awaiting_exit.discard(task.task_id)
            return
        if task.task_id not in self.session.regrow_pending_ids():
            return      # straggler report of the already-detached loss
        self.session.abort_regrow(task.task_id, exit_code)
        attempts = self._elastic_regrow_attempts.get(task.task_id, 0) + 1
        self._elastic_regrow_attempts[task.task_id] = attempts
        if attempts >= 3:
            log.warning("elastic regrow of %s failed %d times — giving up; "
                        "the job continues on the shrunk gang",
                        task.task_id, attempts)
            return
        log.warning("elastic regrow replacement %s died with exit %d — "
                    "requeueing (attempt %d)", task.task_id, exit_code,
                    attempts)
        self._elastic_regrow_queue.append(task.task_id)
        self._elastic_regrow_deadline = (time.monotonic()
                                         + self._elastic_regrow_backoff_s)

    def _elastic_tick(self) -> None:
        """Monitor-loop driver for the elastic state machine: cut a shrink
        epoch once the loss quiesce window closes, watch the barrier for
        resume, launch background regrows after their backoff, and
        activate a regrow once every replacement has registered."""
        now = time.monotonic()
        with self._completion_lock:
            cut = (self._elastic_pending
                   and now - self._elastic_pending_since
                   >= self._elastic_quiesce_s)
            # snapshot WITHOUT clearing: the entries stay held until the
            # transition finishes, so a completion report racing the
            # shrink refreshes its held entry instead of slipping through
            # the gate as a spurious second incident
            lost = dict(self._elastic_pending) if cut else None
        if lost:
            if any(p for _, p in lost.values()):
                self._elastic_shrink(lost)
            else:
                # no preemption materialized in the window: these were
                # ordinary failures — replay them through the normal
                # completion path (restart budgets, chief short-circuit,
                # session retries all behave exactly as without elastic)
                with self._completion_lock:
                    self._elastic_bypass.update(lost)
                    self._elastic_retire_pending(lost)
                for tid, (code, _) in lost.items():
                    jt, _, idx = tid.partition(":")
                    self.record_completion(jt, idx, code)
        if self._elastic_awaiting_resume and self.session.barrier_released():
            self._elastic_awaiting_resume = False
            wall = time.monotonic() - self._elastic_resume_t0
            active = len([t for t in self.session.participants()
                          if not t.completed])
            log.info("elastic: barrier re-released at epoch %d after %.2fs "
                     "(%d active tasks)", self.session.cluster_epoch, wall,
                     active)
            metrics_mod.get_default().gauge(
                "tony_elastic_recovery_seconds",
                help="wall seconds from gang loss to the survivors' "
                     "barrier re-releasing (last transition)").set(wall)
            if self._elastic_span is not None:
                self._elastic_span.end(epoch=self.session.cluster_epoch,
                                       active=active)
                self._elastic_span = None
            tracing.get_flight().record(
                "elastic_resumed", epoch=self.session.cluster_epoch,
                active=active, recovery_wall_s=round(wall, 3))
            self.events.emit(ev.ELASTIC_RESUMED,
                             epoch=self.session.cluster_epoch,
                             active=active,
                             recovery_wall_s=round(wall, 3),
                             session_id=self.session.session_id)
            # every survivor paid the shrink→barrier wall as resync
            # time. The executor's own ledger sees part of this wall
            # (its re-registration wait) too — the overlap makes the
            # goodput fraction CONSERVATIVE during elastic incidents,
            # never optimistic.
            for t in self.session.participants():
                if not t.completed:
                    self._note_goodput_extra(t.task_id, "resync", wall)
        if (self._elastic_regrow_queue
                and now >= self._elastic_regrow_deadline):
            queue, self._elastic_regrow_queue = \
                self._elastic_regrow_queue, []
            self._elastic_launch_regrow(queue)
        if self.session.regrow_ready():
            regrown = sorted(self.session.regrow_pending_ids())
            epoch = self.session.activate_regrow()
            self._journal_append("regrow_activated", epoch=epoch,
                                 task_ids=regrown)
            for tid in regrown:
                # a successful regrow wipes the task's attempt history —
                # the give-up counter is per INCIDENT, not per job
                self._elastic_regrow_attempts.pop(tid, None)
            active = len(self.session.participants())
            log.info("elastic: regrow activated — epoch %d, %s rejoined "
                     "(%d active tasks)", epoch, regrown, active)
            metrics_mod.get_default().counter(
                "tony_elastic_regrows_total",
                help="elastic grow-back epochs activated").inc()
            metrics_mod.get_default().gauge(
                "tony_elastic_active_tasks",
                help="participant tasks in the current cluster epoch"
                ).set(active)
            self.events.emit(ev.ELASTIC_REGROW, epoch=epoch,
                             regrown=regrown, active=active,
                             session_id=self.session.session_id)
            self._elastic_resume_t0 = time.monotonic()
            self._elastic_awaiting_resume = True

    def _elastic_retire_pending(self, keys) -> None:
        """Drop transitioned losses from the pending table (callers hold
        _completion_lock); entries noted DURING the transition keep their
        own quiesce window, restarted from now."""
        for tid in keys:
            self._elastic_pending.pop(tid, None)
        if self._elastic_pending:
            self._elastic_pending_since = time.monotonic()

    def _elastic_shrink(self, lost: dict[str, tuple[int, bool]]) -> None:
        """Cut one shrink epoch over the accumulated losses (monitor
        thread). At least one entry is preemption-sourced; non-preempted
        entries in the same window are collateral and charged to the
        incident. Ineligible loss sets fall back to the stop-the-world
        preemption path: every loss is recorded as an ordinary preempted
        completion and the session retry machinery takes over."""
        # Gang atomicity: a collateral abnormal exit was held as a single
        # task, but a slice cannot lose one host and keep the rest — the
        # detach set is the gang CLOSURE of every loss, so the resized
        # mesh's slice topology stays consistent with its participants.
        # Closure-added mates are still ALIVE (killed below): their exit
        # report is outstanding, so mark them awaiting-exit like any
        # eventless loss.
        with self._completion_lock:
            for tid in list(lost):
                code, preempted = lost[tid]
                for mate in self.session.gang_task_ids(tid):
                    try:
                        t = self.session.get_task_by_id(mate)
                    except (KeyError, IndexError):
                        continue
                    if mate not in lost and not t.detached \
                            and not t.completed:
                        lost[mate] = (-1, preempted)
                        self._elastic_awaiting_exit.add(mate)
        with self._completion_lock:
            survivors = [t for t in self.session.participants()
                         if t.task_id not in lost and not t.completed
                         and self.session.is_tracked(t.job_type)]
            chief_lost = any(
                self.session.is_chief(*tid.split(":", 1)) for tid in lost)
            type_starved = any(
                not any(t.job_type == jt for t in survivors)
                for jt in {tid.split(":", 1)[0] for tid in lost}
                if self.session.is_tracked(jt))
            # A pipeline STAGE gang is never shrinkable: it holds layers,
            # not a data-parallel replica — the survivors cannot compute
            # the model without it. Losing one falls back to the
            # stop-the-world preemption retry (reprovision + session
            # re-run), which CAN bring the stage back.
            stage_types = set(self.session.pipeline_stages)
            stage_lost = any(tid.split(":", 1)[0] in stage_types
                             for tid in lost)
            eligible = (self.elastic_budget_left > 0
                        and not chief_lost and not type_starved
                        and not stage_lost
                        and len(survivors) >= max(1, self.elastic_min_tasks)
                        and self.session.status is SessionStatus.RUNNING
                        and self.final_status is None
                        and not self.client_signalled_finish.is_set())
        if not eligible:
            log.warning(
                "elastic: loss of %s not absorbable (chief_lost=%s, "
                "stage_lost=%s, survivors=%d, budget=%d) — falling back to "
                "stop-the-world preemption handling", sorted(lost),
                chief_lost, stage_lost, len(survivors),
                self.elastic_budget_left)
            metrics_mod.get_default().counter(
                "tony_elastic_fallbacks_total",
                help="gang losses routed back to stop-the-world").inc()
            with self._completion_lock:
                self._elastic_bypass.update(lost)
                self._elastic_retire_pending(lost)
            for tid, (code, _) in lost.items():
                jt, _, idx = tid.partition(":")
                self.record_completion(jt, idx, code, preempted=True)
            return
        self.elastic_budget_left -= 1
        # The incident's postmortem artifact: the coordinator has the
        # richest causal view of a gang loss (the victims were
        # SIGKILLed and cannot dump their own rings) — its flight ring
        # dumps to the job dir and the ELASTIC_SHRINK event references
        # the file.
        flight = tracing.get_flight()
        flight.record("gang_lost", lost=",".join(sorted(lost)),
                      survivors=len(survivors),
                      budget_left=self.elastic_budget_left)
        flight_dump = flight.dump("elastic_shrink",
                                  lost=",".join(sorted(lost)))
        if self._elastic_span is not None:
            # a second loss landing before the first recovery's barrier
            # re-released: close the open span (superseded) so it still
            # reaches the exported trace — cascading preemptions are
            # exactly when the postmortem matters
            self._elastic_span.end(superseded=True)
        self._elastic_span = self.tracer.start_span(
            "elastic.recovery", parent=self.job_span, coarse=True,
            lost=",".join(sorted(lost)))
        for tid, (code, _) in lost.items():
            self.backend.kill_task(tid)      # straggler processes
            self.hb_monitor.unregister(tid)
            self.session.detach_for_preemption(tid, code)
            self.events.emit(ev.TASK_FINISHED, task=tid, exit_code=code,
                             preempted=True, detached=True,
                             session_id=self.session.session_id)
        with self._completion_lock:
            self._elastic_retire_pending(lost)
        epoch = self.session.begin_elastic_resync()
        self._journal_append("elastic_shrink", epoch=epoch,
                             lost=sorted(lost))
        active = len([t for t in self.session.participants()
                      if not t.completed])
        log.warning("elastic: gang(s) %s lost — shrinking to %d task(s), "
                    "cluster epoch %d (%d elastic shrinks left)",
                    sorted(lost), active, epoch, self.elastic_budget_left)
        reg = metrics_mod.get_default()
        reg.counter("tony_elastic_shrinks_total",
                    help="elastic shrink epochs cut").inc()
        reg.gauge("tony_elastic_active_tasks",
                  help="participant tasks in the current cluster epoch"
                  ).set(active)
        self.events.emit(ev.ELASTIC_SHRINK, epoch=epoch,
                         lost=sorted(lost), active=active,
                         flight_dump=flight_dump or "",
                         session_id=self.session.session_id)
        self._elastic_resume_t0 = time.monotonic()
        self._elastic_awaiting_resume = True
        if self.elastic_regrow:
            self._elastic_regrow_queue.extend(sorted(lost))
            self._elastic_regrow_deadline = (
                time.monotonic() + self._elastic_regrow_backoff_s)

    def _elastic_launch_regrow(self, task_ids: list[str]) -> None:
        """Relaunch lost tasks in the background (the backend reprovisions
        a dead gang's slice on launch — tpu.py's dead-gang path — or
        adopts a surviving one via ALREADY_EXISTS). The relaunched
        executors register as still-detached tasks; activation happens in
        the tick once all of them are in."""
        armed = self.session.arm_regrow(task_ids)
        if not armed:
            return
        self._journal_append("regrow_armed",
                             task_ids=sorted(t.task_id for t in armed))
        log.info("elastic: relaunching %s for regrow",
                 [t.task_id for t in armed])
        for t in armed:
            self._submit_launch(t, self.session.requests[t.job_type],
                                self._user_command)

    # ------------------------------------------------------------------
    # Scheduling
    # ------------------------------------------------------------------
    def _executor_command(self, user_command: str) -> str:
        """Build the executor launch command (reference: TonySession.
        getTaskCommand:72 builds 'java ... TaskExecutor --am_address ...
        --task_command ...').

        The conf path is RELATIVE to the task working dir: every backend
        runs executors with cwd = the (local or remote) job dir, so the
        same command works on this host and on a staged slice host whose
        job dir lives somewhere else entirely."""
        addr = f"{socket.gethostname()}:{self.rpc_server.port}"
        # Slice hosts run the TPU VM image's python3, not the submit
        # host's interpreter path.
        remote_backend = (self.conf.get(K.SCHEDULER_BACKEND_KEY) or
                          "local").lower() == "tpu"
        python = (self.conf.get(K.PYTHON_BINARY_PATH_KEY) or
                  ("python3" if remote_backend else sys.executable))
        opts = self.conf.get(K.TASK_EXECUTOR_PYTHON_OPTS_KEY) or ""
        return (f"{python} {opts + ' ' if opts else ''}"
                f"-m tony_tpu.cluster.executor "
                f"--am_address {addr} "
                f"--conf_file {constants.TONY_FINAL_XML} "
                f"--task_command {shlex.quote(user_command)}")

    def _localize_resources(self, request) -> None:
        """Copy per-job-type extra resources (tony.{job}.resources, comma-
        separated paths) into the job dir — the YARN localization analog
        (reference: ContainerLauncher.run:1090-1104 localizes job-type +
        global resources into each container)."""
        import filecmp
        import shutil

        def _same_tree(a: str, b: str) -> bool:
            # ignore=[], hide=[]: dircmp's DEFAULT_IGNORES would silently
            # exclude .git/__pycache__/... from the comparison, and
            # common_funny holds type mismatches (file vs dir) — both
            # must count as "different", or the dedup would hand one job
            # type another's tree.
            cmp = filecmp.dircmp(a, b, ignore=[], hide=[])
            if cmp.left_only or cmp.right_only or cmp.funny_files \
                    or cmp.common_funny:
                return False
            _, mismatch, errors = filecmp.cmpfiles(
                a, b, cmp.common_files, shallow=False)
            if mismatch or errors:
                return False
            return all(_same_tree(os.path.join(a, d), os.path.join(b, d))
                       for d in cmp.common_dirs)

        for path in filter(None, (request.resources or "").split(",")):
            path = path.strip()
            if not path:
                continue
            dst = os.path.join(self.job_dir, os.path.basename(path))
            if os.path.exists(dst):
                # Resources are flattened by basename; a silent skip would
                # hand one job type another's file. Identical content (the
                # same file OR directory tree listed by several job types)
                # is fine.
                if os.path.isfile(path) and os.path.isfile(dst) and \
                        filecmp.cmp(path, dst, shallow=False):
                    continue
                if os.path.isdir(path) and os.path.isdir(dst) and \
                        _same_tree(path, dst):
                    continue
                raise ValueError(
                    f"{request.job_type}: resource {path!r} collides with an "
                    f"already-localized different {os.path.basename(path)!r}")
            if os.path.isdir(path):
                shutil.copytree(path, dst)
            elif os.path.exists(path):
                shutil.copy2(path, dst)
            else:
                raise FileNotFoundError(
                    f"{request.job_type}: resource {path!r} does not exist")

    def schedule_tasks(self, user_command: str) -> None:
        """Bind every task to an allocation and fan the launches out
        through the bounded launch pool (reference: scheduleTasks:549 +
        ContainerLauncher.run:1080 — made concurrent: provisioning and
        staging one TPU gang takes minutes, and the backend's
        claim-or-wait gang logic already tolerates concurrent callers, so
        an N-gang job's bring-up wall is max-of-gangs instead of sum).
        Returns once every launch is SUBMITTED — the monitor loop starts
        while launches are still in flight, and a launch failure funnels
        into record_completion like any other task failure instead of
        aborting the scheduling pass."""
        self._user_command = user_command   # per-task restarts rebuild specs
        requests = self.session.requests
        bindings = []
        for job_type, request in requests.items():
            self._localize_resources(request)
            while True:
                task = self.session.next_allocation(job_type)
                if task is None:
                    break
                bindings.append((task, request))
        for task, request in bindings:
            self._submit_launch(task, request, user_command)

    def _submit_launch(self, task, request, user_command: str) -> None:
        if self._launch_sema is None:
            self._launch_sema = threading.BoundedSemaphore(
                max(1, self.conf.get_int(K.LAUNCH_MAX_CONCURRENT_KEY, 8)))
        with self._launch_cv:
            self._launch_inflight += 1

        def run():
            try:
                with self._launch_sema:
                    self._guarded_launch(task, request, user_command)
            finally:
                with self._launch_cv:
                    self._launch_inflight -= 1
                    self._launch_cv.notify_all()

        threading.Thread(target=run, daemon=True,
                         name=f"tony-launch-{task.task_id}").start()

    def _guarded_launch(self, task, request, user_command: str) -> None:
        """Pool-side launch wrapper: re-checks job liveness at launch time
        (the session verdict — or a client kill — may land while this
        launch waits for a pool slot) and funnels failures into
        record_completion, so a failed provision fails the TASK and the
        monitor's normal reduction/retry machinery takes over."""
        with self._completion_lock:
            live = (task.session_id == self.session.session_id
                    and self.session.status is SessionStatus.RUNNING
                    and self.final_status is None
                    and not self.client_signalled_finish.is_set())
        if not live:
            log.info("skipping launch of %s — session verdict landed first",
                     task.task_id)
            return
        try:
            self._launch_task(task, request, user_command)
        except Exception as e:
            log.exception("launch of %s failed", task.task_id)
            with self._launch_lock:
                self._launch_errors.append(
                    f"launch of {task.task_id} failed: {e}")
            self.record_completion(task.job_type, task.index, 1)

    def _drain_launches(self, timeout: float | None = None) -> None:
        """Wait out in-flight launches before a session reset or teardown:
        a launch landing AFTER the kill sweep would inject a zombie
        process (or a freshly provisioned slice) into the next session /
        past stop()."""
        deadline = (time.monotonic() + timeout) if timeout else None
        with self._launch_cv:
            while self._launch_inflight:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    log.warning("%d launch(es) still in flight after "
                                "drain — proceeding", self._launch_inflight)
                    return
                self._launch_cv.wait(timeout=remaining)

    def _launch_task(self, task, request, user_command: str) -> None:
        """Launch one bound task (shared by initial scheduling and
        in-session per-task restart). Per-gang PROGRAMS: a job type with
        tony.{job}.program runs THAT command instead of the job-wide one
        — how an MPMD pipeline job gives each stage gang its own trainer
        entry point on its own device set."""
        if request.program:
            user_command = request.program
        env = {
            constants.JOB_NAME: task.job_type,
            constants.TASK_INDEX: str(task.index),
            constants.TASK_NUM: str(request.instances),
            constants.SESSION_ID: str(self.session.session_id),
            constants.ATTEMPT_NUMBER: os.environ.get(
                constants.ATTEMPT_NUMBER, "0"),
        }
        if self.secret:
            env[constants.TONY_SECRET] = self.secret
        if self.job_span is not None and self.job_span.recording:
            # the job root trace context: executors parent their coarse
            # spans on it, and pipeline stage gangs derive deterministic
            # per-step trace ids from its trace id
            env[constants.TONY_TRACE_CTX] = tracing.format_env_ctx(
                self.job_span.context)
        gcs_token = os.environ.get(constants.TONY_GCS_TOKEN)
        if gcs_token:
            # the job's scoped GCS identity (tony.gcs.service-account),
            # re-exported explicitly so executors inherit it even when a
            # backend strips the coordinator environment
            env[constants.TONY_GCS_TOKEN] = gcs_token
        if self.tls_cert:
            env[constants.TONY_TLS_CERT] = self.tls_cert
        env.update(request.env)
        self.events.emit(ev.TASK_SCHEDULED, task=task.task_id,
                         session_id=self.session.session_id)
        # Docker passthrough (reference: TonyClient.java:340-349):
        # wrap the executor in `docker run`, forwarding the task's
        # assigned env into the container.
        # Session id AND restart count in the container name: a relaunched
        # task (of a retried session or an in-session restart) must not
        # collide with a straggler (or still-being---rm'd) container from
        # the old generation.
        suffix = (f"-s{self.session.session_id}"
                  + (f"-r{task.restarts}" if task.restarts else ""))
        command = docker_wrap(
            self._executor_command(user_command), self.conf,
            self.job_dir, env_keys=tuple(env),
            task_id=f"{task.task_id}{suffix}",
            app_id=self.app_id)
        self.backend.launch_task(LaunchSpec(
            task_id=task.task_id,
            command=command,
            env=env,
            log_dir=self.log_dir,
            cwd=self.job_dir,
            memory_mb=request.memory_mb,
            vcores=request.vcores,
            gpus=request.gpus,
            tpus=request.tpus,
            tpu_topology=request.tpu_topology))
        # Journaled AFTER the backend accepted the launch: the record's
        # count is the recovery e2e's zero-reprovision pin, and the pid
        # (where the backend tracks one) is what a restarted coordinator
        # adopts instead of relaunching.
        pid_of = getattr(self.backend, "pid_of", None)
        self._journal_append(
            "launch", task_id=task.task_id,
            allocation_id=task.allocation_id,
            pid=(pid_of(task.task_id) or 0) if pid_of else 0)

    # ------------------------------------------------------------------
    # Monitor loop
    # ------------------------------------------------------------------
    def record_completion(self, job_type: str, index: int | str,
                          exit_code: int, preempted: bool = False,
                          session_id: int | None = None,
                          via_rpc: bool = False) -> None:
        """Single funnel for task completion from BOTH sources — the
        executor's RPC result and the backend's process-exit observation —
        so state transition and the TASK_FINISHED event happen exactly once
        whichever arrives first. The check-then-act is serialized by
        ``_completion_lock`` (RPC threads race the monitor thread here).

        Exit codes are canonicalized to what the OS reports for the
        executor process (signal-killed → 128+sig as the executor's own
        ``code & 0xFF`` mapping, executor.py exit path): the RPC channel
        carries the raw (possibly negative) user returncode while the
        backend observes the executor's mapped exit, and the restart
        twin-dedupe below compares codes across the two channels."""
        if exit_code < 0:
            exit_code = exit_code & 0xFF
        elif exit_code > 255:
            exit_code = 255
        with self._completion_lock:
            try:
                task = self.session.get_task(job_type, index)
            except (KeyError, IndexError):
                return
            if session_id is not None and session_id != self.session.session_id:
                return
            # Elastic absorption: a loss already queued for a shrink epoch
            # just refreshes its recorded exit code; a DETACHED task's
            # late report (the dead gang's straggler events, a failed
            # regrow launch) must neither fail the session nor count as a
            # verdict. A fresh preempted loss of a tracked task routes
            # into the pending set instead of the completion reduction —
            # and so does ANY abnormal tracked exit while elastic is on:
            # the quiesce tick triages the accumulated set, charging
            # collateral deaths (a survivor crashing on the lost gang's
            # collectives) to the incident and replaying genuine user
            # failures through the ordinary path.
            if task.task_id in self._elastic_pending:
                code, was_preempted = self._elastic_pending[task.task_id]
                self._elastic_pending[task.task_id] = (
                    exit_code, was_preempted or preempted)
                # its generation's exit report has now been consumed
                self._elastic_awaiting_exit.discard(task.task_id)
                return
            if task.detached:
                self._on_detached_completion(task, exit_code)
                return
            if task.task_id not in self._elastic_bypass \
                    and self._elastic_can_absorb(task.task_id):
                if preempted:
                    self._elastic_note_gang_loss(task.task_id, exit_code)
                    return
                if exit_code != 0:
                    self._elastic_note_abnormal(task.task_id, exit_code)
                    return
            # Twin report of a restart-consumed failure: the SAME process
            # exit reaches us twice (executor RPC + backend process exit),
            # so after a restart the matching-code report from the OTHER
            # channel is swallowed exactly once. The marker retires when
            # the relaunched generation REGISTERS (on_register_worker_spec)
            # — the backend discards the old generation's exit event on
            # relaunch, so a marker that outlived registration could
            # otherwise swallow the new generation's own failure. Residual
            # corner: a relaunch that dies pre-registration with the same
            # code on the opposite channel consumes the marker — its other
            # report still surfaces the failure.
            dup = self._restart_dup.get(task.task_id)
            if (dup is not None and dup[0] == exit_code
                    and dup[1] != via_rpc and not task.completed):
                del self._restart_dup[task.task_id]
                return
            relaunch = None
            if self._restartable(task, exit_code, preempted):
                self.task_restarts_left -= 1
                self.hb_monitor.unregister(task.task_id)
                self._restart_dup[task.task_id] = (exit_code, via_rpc)
                t = self.session.reset_task_for_restart(job_type, index)
                log.warning(
                    "task %s failed with exit code %d — in-session restart "
                    "%d (%d restarts left)", task.task_id, exit_code,
                    t.restarts, self.task_restarts_left)
                self.events.emit(ev.TASK_RESTARTED, task=task.task_id,
                                 exit_code=exit_code, restarts=t.restarts,
                                 session_id=self.session.session_id)
                self._journal_append("task_restart", task_id=task.task_id,
                                     exit_code=exit_code)
                relaunch = t
            else:
                already_done = task.completed
                self.session.on_task_completed(job_type, index, exit_code,
                                               session_id=session_id,
                                               via_rpc=via_rpc)
                if not already_done and task.completed:
                    self._journal_append("completion", task_id=task.task_id,
                                         exit_code=task.exit_code)
                    if task.exit_code != 0 \
                            and self.session.is_tracked(job_type):
                        if preempted:
                            self._session_preempted = True
                        else:
                            self._session_real_failure = True
                    self.hb_monitor.unregister(task.task_id)
                    extra = {}
                    if task.exit_code != 0:
                        # the incident's jhist event carries the
                        # executor's final-beat flight tail (its last
                        # recorded moments), when one arrived
                        tracing.get_flight().record(
                            "task_failed", task=task.task_id,
                            code=task.exit_code, preempted=preempted)
                        tail = self._pop_flight_tail(task.task_id)
                        if tail is not None:
                            extra["flight"] = tail
                    self.events.emit(ev.TASK_FINISHED, task=task.task_id,
                                     exit_code=task.exit_code,
                                     preempted=preempted,
                                     session_id=self.session.session_id,
                                     **extra)
        # Launch OUTSIDE the completion lock: backend.launch_task can block
        # for seconds (old-process kill-and-wait, docker wrap, ssh), and
        # holding the lock would stall every other completion report.
        if relaunch is not None:
            with self._completion_lock:
                # Re-check liveness at launch time: the session verdict (or
                # a reset to a NEW session) may have landed between the
                # restart decision and here — launching then would inject a
                # zombie into the kill sweep / the next session's gang.
                live = (relaunch.session_id == self.session.session_id
                        and self.session.status is SessionStatus.RUNNING
                        and self.final_status is None
                        and not self.client_signalled_finish.is_set())
            if live:
                try:
                    self._launch_task(relaunch,
                                      self.session.requests[job_type],
                                      self._user_command)
                except Exception as e:
                    # A failed RELAUNCH funnels like any launch failure —
                    # each recursion consumes restart budget, so this
                    # terminates with the task marked FAILED. Raising
                    # instead would kill the calling launch/RPC/monitor
                    # thread and strand the task in SCHEDULED (never
                    # completed → the monitor loop would spin forever).
                    log.exception("relaunch of %s failed",
                                  relaunch.task_id)
                    with self._launch_lock:
                        self._launch_errors.append(
                            f"relaunch of {relaunch.task_id} failed: {e}")
                    self.record_completion(job_type, index, 1)
            else:
                log.info("skipping restart launch of %s — session verdict "
                         "landed first", relaunch.task_id)

    def _restartable(self, task, exit_code: int, preempted: bool) -> bool:
        """Eligibility for an in-session single-task relaunch: a failed,
        tracked, NON-CHIEF task (chief completion is the job's verdict —
        session.on_task_completed:266-271), with budget left, while the
        job is still live. Slice preemption keeps its own gang-level
        budget (the whole gang reprovisions, not one process)."""
        return (exit_code != 0 and not preempted
                and not task.completed
                and self.task_restarts_left > 0
                and self.session.is_tracked(task.job_type)
                and not self.session.is_chief(task.job_type, task.index)
                # the session verdict may land before stop() sets
                # final_status (chief short-circuit, heartbeat expiry) —
                # restarting after it is decided burns budget on a doomed
                # process that stop() immediately kills
                and self.session.status is SessionStatus.RUNNING
                and not self.task_missed_hb.is_set()
                and self.final_status is None
                and not self.client_signalled_finish.is_set())

    def _apply_completions(self, completions: list[CompletionEvent]) -> None:
        for c in completions:
            jt, _, idx = c.task_id.partition(":")
            log.info("task %s exited with code %d%s", c.task_id, c.exit_code,
                     " (preempted)" if c.preempted else "")
            self.hb_monitor.unregister(c.task_id)
            self.record_completion(jt, idx, c.exit_code, preempted=c.preempted)

    _STARTUP_PHASES = ("provision", "stage", "dispatch")

    def _drain_launch_timings(self) -> None:
        """Fold backend bring-up walls into per-gang
        ``tony_startup_<phase>_seconds`` gauges — they ride the
        coordinator's own registry into METRICS_SNAPSHOT as pseudo-task
        am:0, hence the history server's live /metrics exposition and the
        jhist replay — and emit each record as a LAUNCH jhist event so
        the history UI can show where bring-up time went."""
        for rec in self.backend.take_launch_timings():
            phase = rec.get("phase")
            if phase in self._STARTUP_PHASES:
                metrics_mod.get_default().gauge(
                    f"tony_startup_{phase}_seconds",
                    help=f"wall seconds this gang's last {phase} took",
                    gang=str(rec.get("gang", ""))).set(
                        float(rec.get("seconds", 0.0)))
                # bring-up spans under the job root trace: the timeline
                # the job page renders becomes causal in the exported
                # trace too (provision → stage → dispatch per gang)
                try:
                    self.tracer.record_span(
                        f"launch.{phase}",
                        float(rec.get("seconds", 0.0)),
                        parent=self.job_span,
                        gang=str(rec.get("gang", "")),
                        task=str(rec.get("task", "") or ""),
                        cached=bool(rec.get("cached")))
                except (TypeError, ValueError):
                    pass          # a malformed record already renders raw
                # Goodput attribution: backend bring-up walls happen
                # BEFORE the executor's own ledger exists, so only the
                # coordinator can account them. A task-tagged record
                # charges that task; a gang-level record charges every
                # task of the gang (each of them paid that wall).
                if phase in ("provision", "stage"):
                    try:
                        seconds = float(rec.get("seconds", 0.0))
                    except (TypeError, ValueError):
                        seconds = 0.0
                    tid = str(rec.get("task", "") or "")
                    if tid:
                        self._note_goodput_extra(tid, phase, seconds)
                    else:
                        gang = str(rec.get("gang", ""))
                        for task in self.session.tasks.get(gang, ()):
                            self._note_goodput_extra(task.task_id, phase,
                                                     seconds)
            self.events.emit(ev.LAUNCH,
                             session_id=self.session.session_id, **rec)

    #: pending-span bound across tasks; past it the OLDEST batches drop
    #: (the monitor loop normally drains well below this)
    _TRACE_PENDING_CAP = 20000

    def on_trace_beat(self, task_id: str, spans: str,
                      client_time: float, client_rtt: float) -> None:
        """Heartbeat trace piggyback (RPC handler threads): estimate the
        task's clock offset from the beat's send-time + RTT, and queue
        its span batch for the next TRACE_SPAN jhist emit. Malformed
        batches are dropped without costing the ping (the metrics-ingest
        discipline)."""
        if client_time > 0:
            offset = tracing.clock_offset(client_time, client_rtt)
            self.clock_offsets[task_id] = offset
            metrics_mod.get_default().gauge(
                "tony_clock_offset_seconds",
                help="estimated task clock offset vs the coordinator "
                     "(heartbeat RTT midpoint; add to task timestamps "
                     "to express them on the coordinator's clock)",
                task=task_id).set(offset)
        if not spans:
            return
        try:
            batch = tracing.parse_batch_json(spans)
        except (ValueError, TypeError):
            with self._trace_lock:
                self.trace_rejects += 1
            metrics_mod.get_default().counter(
                "tony_trace_batches_rejected_total",
                help="malformed heartbeat span batches dropped").inc()
            log.warning("dropping malformed span batch from %s", task_id,
                        exc_info=True)
            return
        tail = batch.get("f")
        with self._trace_lock:
            # retry re-delivery guard: a lost heartbeat ACK makes the
            # sender retry the SAME request; the batch id spots the
            # duplicate (batches append here, so it would double every
            # span — the last-snapshot metrics table is naturally
            # idempotent, this path is not)
            bid = batch.get("b", "")
            if bid and self._trace_last_batch.get(task_id) == bid:
                return
            if bid:
                self._trace_last_batch[task_id] = bid
            if batch.get("s"):
                self._trace_pending.append((task_id, batch["s"]))
                self._trace_pending_spans += len(batch["s"])
                while self._trace_pending_spans > self._TRACE_PENDING_CAP \
                        and self._trace_pending:
                    _, dropped = self._trace_pending.pop(0)
                    self._trace_pending_spans -= len(dropped)
            if tail:
                self._flight_tails[task_id] = tail

    def _pop_flight_tail(self, task_id: str) -> dict | None:
        """The task's last heartbeat-shipped flight tail, if any —
        attached to its incident TASK_FINISHED event (callers hold
        whatever locks they like; the dict op is atomic enough)."""
        return self._flight_tails.pop(task_id, None)

    # ------------------------------------------------------------------
    # Goodput plane
    # ------------------------------------------------------------------
    def on_goodput_beat(self, task_id: str, payload: str) -> None:
        """Heartbeat goodput piggyback (RPC handler threads): validate
        and keep the task's latest cumulative ledger wire. Malformed
        payloads are dropped without costing the ping."""
        if not payload:
            return
        wire = goodput_mod.from_wire_json(payload)
        if wire is None:
            self.goodput_rejects += 1
            metrics_mod.get_default().counter(
                "tony_goodput_beats_rejected_total",
                help="malformed heartbeat goodput snapshots dropped").inc()
            log.warning("dropping malformed goodput snapshot from %s",
                        task_id)
            return
        with self._goodput_lock:
            self._goodput_wires[task_id] = wire

    def _note_goodput_extra(self, task_id: str, category: str,
                            seconds: float) -> None:
        """Attribute *seconds* of *category* to a task on the
        coordinator's own authority (walls no executor ledger can see:
        backend provisioning, elastic resync, crash recovery). Journaled
        so a restarted coordinator replays the attribution instead of
        re-measuring it — the no-double-count guarantee."""
        if seconds <= 0:
            return
        with self._goodput_lock:
            cats = self._goodput_extra.setdefault(task_id, {})
            cats[category] = cats.get(category, 0.0) + seconds
        self._journal_append("goodput_extra", task=task_id,
                             category=category,
                             seconds=round(seconds, 6))

    def _goodput_payload(self) -> tuple[dict, float]:
        """The GOODPUT event payload: per-task entries (ledger wire with
        t0/now shifted onto the coordinator's clock via the task's
        offset estimate, plus the coordinator-attributed "extra"
        seconds) and the job-level goodput fraction — total step seconds
        over total attributed wall."""
        with self._goodput_lock:
            wires = {t: dict(w) for t, w in self._goodput_wires.items()}
            extras = {t: dict(e) for t, e in self._goodput_extra.items()}
        tasks: dict[str, dict] = {}
        total_step = total_wall = 0.0
        for tid in sorted(set(wires) | set(extras)):
            wire = wires.get(tid)
            offset = self.clock_offsets.get(tid, 0.0)
            if wire is not None:
                entry = {
                    "t0": round(float(wire.get("t0", 0.0)) + offset, 6),
                    "now": round(float(wire.get("now", 0.0)) + offset, 6),
                    "cat": {k: round(float(v), 6)
                            for k, v in wire.get("cat", {}).items()},
                    "cur": wire.get("cur", ""),
                    "n": wire.get("n", {}),
                    "sw": wire.get("sw", {"c": 0, "s": 0.0}),
                }
            else:           # extras-only task (e.g. died before a beat)
                entry = {"t0": 0.0, "now": 0.0, "cat": {}, "cur": "",
                         "n": {}, "sw": {"c": 0, "s": 0.0}}
            entry["extra"] = {k: round(v, 6)
                              for k, v in extras.get(tid, {}).items()}
            tasks[tid] = entry
            total_step += entry["cat"].get("step", 0.0)
            total_wall += max(0.0, entry["now"] - entry["t0"]) \
                + sum(entry["extra"].values())
        fraction = (total_step / total_wall) if total_wall > 0 else 0.0
        return tasks, fraction

    def _emit_goodput(self) -> None:
        """Fold the goodput tables into one GOODPUT jhist event (the
        metrics-snapshot cadence). Entries are cumulative, so the LAST
        event of a job is its complete breakdown — what the history
        server's /goodput endpoint replays bit-exact."""
        tasks, fraction = self._goodput_payload()
        if not tasks:
            return
        # The fraction gauge rides the coordinator's own registry into
        # the SAME _maybe_emit_metrics pass (am:0), hence /metrics.
        metrics_mod.get_default().gauge(
            "tony_goodput_fraction",
            help="job-level goodput fraction: step seconds over total "
                 "attributed wall seconds").set(round(fraction, 6))
        self.events.emit(ev.GOODPUT, tasks=tasks,
                         fraction=round(fraction, 6),
                         session_id=self.session.session_id)

    def _straggler_tick(self) -> None:
        """Detector window (monitor loop, tony.goodput.window-ms
        cadence): feed the latest per-task wires to the EWMA-vs-gang-
        median comparison; turn transitions into jhist events, the
        suspected counter, an active gauge, and flight-recorder evidence."""
        now = time.monotonic()
        if (self._goodput_window_s <= 0
                or now - self._goodput_last_tick < self._goodput_window_s):
            return
        self._goodput_last_tick = now
        with self._goodput_lock:
            wires = {t: dict(w) for t, w in self._goodput_wires.items()}
        if not wires:
            return
        suspected, cleared = self.straggler.observe(wires)
        reg = metrics_mod.get_default()
        for evidence in suspected:
            tid = evidence["task"]
            log.warning(
                "straggler suspected: %s step-wall EWMA %.4fs > %.1fx gang "
                "median %.4fs for %d windows", tid, evidence["ewma_s"],
                evidence["factor"], evidence["median_s"],
                evidence["windows"])
            reg.counter(
                "tony_straggler_suspected_total",
                help="straggler-detector suspicions raised",
                task=tid).inc()
            reg.gauge(
                "tony_straggler_active",
                help="1 while the task is suspected of straggling",
                task=tid).set(1)
            tracing.get_flight().record("straggler", **evidence)
            self.events.emit(ev.STRAGGLER_SUSPECTED,
                             session_id=self.session.session_id,
                             **evidence)
        for tid in cleared:
            log.info("straggler cleared: %s back under the gang threshold",
                     tid)
            reg.gauge("tony_straggler_active",
                      help="1 while the task is suspected of straggling",
                      task=tid).set(0)
            tracing.get_flight().record("straggler_cleared", task=tid)
            self.events.emit(ev.STRAGGLER_CLEARED, task=tid,
                             session_id=self.session.session_id)

    def _emit_trace_events(self) -> None:
        """Fold pending span batches into TRACE_SPAN jhist events, one
        per (task, batch), with the task's clock-offset estimate applied
        to every span timestamp — so the exported trace is on the
        coordinator's clock and cross-process spans line up. The
        coordinator's own spans ride as pseudo-task am:0 (offset 0)."""
        own = self.tracer.drain()
        with self._trace_lock:
            pending, self._trace_pending = self._trace_pending, []
            self._trace_pending_spans = 0
        if own:
            pending.append((f"{constants.COORDINATOR_JOB_NAME}:0", own))
        for task_id, spans in pending:
            offset = self.clock_offsets.get(task_id, 0.0)
            self.events.emit(
                ev.TRACE_SPAN, task=task_id,
                spans=tracing.apply_offset(spans, offset),
                offset_s=round(offset, 6),
                session_id=self.session.session_id)

    def _maybe_emit_metrics(self, force: bool = False) -> None:
        """Fold the per-task snapshot table (plus the coordinator's own
        registry as pseudo-task "am:0" — missed-heartbeat counters,
        process stats) into one METRICS_SNAPSHOT jhist event, on the
        tony.metrics.snapshot-interval-ms cadence (``force`` for the
        final at-stop emit). The event stream is flushed per record, so
        the history server's /metrics reads live values from the
        .inprogress file."""
        now = time.monotonic()
        if not force and (self._metrics_interval_s <= 0
                          or now - self._metrics_last_emit
                          < self._metrics_interval_s):
            return
        self._metrics_last_emit = now
        # trace spans share the snapshot cadence (batched, not per-beat)
        self._emit_trace_events()
        # goodput too — BEFORE the own-registry collection below, so the
        # fraction gauge it sets lands in this same snapshot
        self._emit_goodput()
        payload = self.metrics_table.as_payload()
        metrics_mod.sample_host_stats()
        own = metrics_mod.get_default().to_wire()
        if own["c"] or own["g"] or own["h"]:
            payload[f"{constants.COORDINATOR_JOB_NAME}:0"] = own
        if payload:
            self.events.emit(ev.METRICS_SNAPSHOT, tasks=payload,
                             session_id=self.session.session_id)

    def monitor(self, started_at: float) -> SessionStatus:
        """The hot control loop (reference: monitor:591-646)."""
        while True:
            time.sleep(self.MONITOR_PERIOD_S)
            self._apply_completions(self.backend.poll_completed())
            self._elastic_tick()
            self._drain_launch_timings()
            self._straggler_tick()
            self._maybe_emit_metrics()
            if self.timeout_s > 0 and time.monotonic() - started_at > self.timeout_s:
                self.failure_message = (
                    f"application timed out after {self.timeout_s:.0f}s")
                self.session.status = SessionStatus.FAILED
                return SessionStatus.FAILED
            if self.client_signalled_finish.is_set():
                status = self.session.update_session_status()
                if status is SessionStatus.RUNNING:
                    # finish while tasks still run = an explicit client
                    # kill (the `tony kill` path), not a success.
                    self.failure_message = "killed by client"
                    self.session.status = SessionStatus.KILLED
                    return SessionStatus.KILLED
                return status
            if self.task_missed_hb.is_set():
                return SessionStatus.FAILED
            if self.session.training_finished():
                return self.session.status

    # ------------------------------------------------------------------
    # Preprocess / single-node (reference: doPreprocessingJob:688-729)
    # ------------------------------------------------------------------
    def run_preprocess(self, user_command: str, single_node: bool) -> int:
        """Run the user command inside the coordinator process. Used for
        (a) preprocess jobs — shared computation hoisted out of the workers,
        run before any task is scheduled — and (b) single-node jobs (e.g.
        notebooks without a task fleet), whose exit code IS the job result."""
        import subprocess as sp
        from tony_tpu.cluster.executor import reserve_port
        env = dict(os.environ)
        env[constants.PREPROCESSING_JOB] = "true"
        if single_node:
            # Services like jupyter want a writable $HOME (reference
            # :718-722). Scoped to single-node: plain preprocess commands
            # keep the submitting user's real $HOME (gcloud/ssh creds,
            # pip caches).
            env["HOME"] = self.job_dir
            # Two DISTINCT ports, matching executor-mode semantics
            # (executor.py reserves tb_port and notebook_port separately) —
            # a command binding both $TB_PORT and $NOTEBOOK_PORT must not
            # collide. Single-node jobs never launch executors, so the
            # coordinator itself must export NOTEBOOK_PORT or the
            # documented `jupyter lab --port=$NOTEBOOK_PORT` gets nothing.
            tb_port = reserve_port()
            nb_port = reserve_port()
            env[constants.TB_PORT] = str(tb_port)
            env[constants.NOTEBOOK_PORT] = str(nb_port)
            # Notebook jobs proxy to the notebook endpoint (reference:
            # NotebookSubmitter.java:93-106); otherwise track TensorBoard.
            is_notebook = self.conf.get_int(
                K.instances_key(constants.NOTEBOOK_JOB_NAME), 0) > 0
            tracked_port = nb_port if is_notebook else tb_port
            self.tensorboard_url = (
                f"http://{socket.gethostname()}:{tracked_port}")
            log.info("single-node tracking URL: %s", self.tensorboard_url)
        log.info("running %s job in coordinator: %s",
                 "single-node" if single_node else "preprocess", user_command)
        # Same docker passthrough as scheduled tasks — with docker enabled
        # the preprocess step must see the image's deps, not the bare host.
        # HOME is forwarded into docker only for single-node jobs, where it
        # points at job_dir (bind-mounted). Forwarding the submitting
        # user's host HOME would name a path that does not exist in the
        # container.
        env_keys = [constants.PREPROCESSING_JOB, constants.TB_PORT,
                    constants.NOTEBOOK_PORT]
        if single_node:
            env_keys.append("HOME")
        command = docker_wrap(
            user_command, self.conf, self.job_dir,
            env_keys=tuple(env_keys),
            task_id="am-preprocess", app_id=self.app_id)
        logs = os.path.join(self.log_dir, "am-preprocess")
        timeout_s = self.conf.get_int(K.TASK_EXECUTION_TIMEOUT_KEY, 0) / 1000.0
        with open(logs + ".stdout", "ab") as out, \
                open(logs + ".stderr", "ab") as err:
            proc = sp.Popen(["bash", "-c", command], env=env,
                            cwd=self.job_dir, stdout=out, stderr=err,
                            start_new_session=True)
            # Tracked so coordinator kill paths (client timeout, Ctrl-C,
            # stop()) reap it — it is in no backend kill list.
            self._preprocess_proc = proc
            deadline = (time.monotonic() + timeout_s) if timeout_s > 0 \
                else None
            try:
                # Short-interval wait loop instead of one blocking wait:
                # an out-of-band `tony kill` (finishApplication) must be
                # able to interrupt single-node/notebook jobs, which never
                # reach the monitor loop.
                while True:
                    try:
                        exit_code = proc.wait(timeout=0.2)
                        break
                    except sp.TimeoutExpired:
                        if self.client_signalled_finish.is_set():
                            log.warning("client kill — stopping %s job",
                                        "single-node" if single_node
                                        else "preprocess")
                            self._kill_preprocess()
                            proc.wait()
                            exit_code = 143
                            break
                        if deadline and time.monotonic() > deadline:
                            log.error("preprocess exceeded %.0fs — killing",
                                      timeout_s)
                            self._kill_preprocess()
                            proc.wait()
                            exit_code = 1
                            break
            finally:
                self._preprocess_proc = None
        log.info("preprocess/single-node job exited with %d", exit_code)
        return exit_code

    def _kill_preprocess(self) -> None:
        """TERM first (lets a docker_wrap trap docker-kill its container),
        escalate to KILL after a short grace."""
        proc = self._preprocess_proc
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, 15)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.monotonic() + 5
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.1)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, 9)
            except (ProcessLookupError, PermissionError):
                pass

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def run(self, user_command: str) -> int:
        self.events.start()
        # One start record per coordinator process — the count IS the
        # incarnation id (a recovered journal folds to incarnation-1
        # starts, so appending ours keeps fold() == self.incarnation).
        self._journal_append("coordinator_start", app_id=self.app_id)
        # The job root span: every process's coarse spans (bring-up,
        # executor lifecycle, incidents) parent onto it via the
        # TONY_TRACE_CTX env exported into each launch.
        self.job_span = self.tracer.start_span(
            "job", coarse=True, app_id=self.app_id,
            num_tasks=self.session.total_tasks())
        # Frozen per-job config next to the jhist so the history server's
        # /config page can render it (reference: TonyApplicationMaster
        # setupJobDir + writeConfigFile :458-463).
        try:
            from tony_tpu.storage import sjoin, storage_for
            dest = sjoin(self.events.history_dir,
                         ev.config_file_name(self.app_id))
            tmp_xml = os.path.join(self.job_dir, ".history-config.xml")
            self.conf.write_xml(tmp_xml)
            storage_for(dest).put(tmp_xml, dest)
            os.remove(tmp_xml)
        except Exception:
            # Best-effort convenience file — never fail the job over it.
            log.warning("could not write history config copy", exc_info=True)
        self.rpc_server.start()
        self.hb_monitor.start()
        addr = f"{socket.gethostname()}:{self.rpc_server.port}"
        addr_path = os.path.join(self.job_dir, COORDINATOR_ADDR_FILE)
        tmp = addr_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(addr)
        os.replace(tmp, addr_path)  # atomic: client never reads a partial file
        self._journal_append("rpc_bound", port=self.rpc_server.port)
        log.info("coordinator %s serving on %s", self.app_id, addr)
        self.events.emit(ev.APPLICATION_INITED, app_id=self.app_id,
                         num_tasks=self.session.total_tasks(),
                         host=socket.gethostname())
        if self._recovered is not None:
            self._adopt_recovered()

        # Chaos: coordinator suicide before any task is scheduled (reference:
        # TEST_AM_CRASH, TonyApplicationMaster.java:352-357 returns false
        # before start()). The client observes a dead coordinator with no
        # final status and fails (or relaunches if retries remain).
        if os.environ.get(constants.TEST_AM_CRASH) == "true":
            log.error("chaos: TEST_AM_CRASH set — exiting hard")
            os._exit(3)

        # Preprocess / single-node arm (reference: start:520-546 — preprocess
        # runs first; single-node jobs short-circuit with its exit code).
        single_node = self.conf.get_bool(K.APPLICATION_SINGLE_NODE_KEY, False)
        if single_node or self.conf.get_bool(K.APPLICATION_PREPROCESS_KEY,
                                             False):
            exit_code = self.run_preprocess(user_command, single_node)
            if self.client_signalled_finish.is_set() and exit_code != 0:
                self.failure_message = "killed by client"
                return self.stop(SessionStatus.KILLED)
            if single_node:
                if exit_code != 0:
                    self.failure_message = (
                        f"single-node job failed with exit code {exit_code}")
                return self.stop(SessionStatus.SUCCEEDED if exit_code == 0
                                 else SessionStatus.FAILED)
            if exit_code != 0:
                self.failure_message = (
                    f"preprocess job failed with exit code {exit_code}")
                return self.stop(SessionStatus.FAILED)

        status = SessionStatus.FAILED
        while True:
            started = time.monotonic()
            try:
                self.schedule_tasks(user_command)
                status = self.monitor(started)
            except Exception as e:  # backend/provisioning failure must still
                # produce a final status for the client (not an AM "crash"
                # that gets blindly relaunched retry-count times)
                log.exception("session %d aborted by backend error",
                              self.session.session_id)
                self.failure_message = f"backend error: {e}"
                self.session.status = SessionStatus.FAILED
                status = SessionStatus.FAILED
                break
            if status is SessionStatus.SUCCEEDED \
                    or self.client_signalled_finish.is_set() \
                    or (self.timeout_s > 0
                        and time.monotonic() - started > self.timeout_s):
                break
            # Failure triage: pure infrastructure preemption (every failed
            # tracked task was preempted, no heartbeat expiry) retries from
            # the preemption budget; anything else consumes a user retry.
            infra_only = (self._session_preempted
                          and not self._session_real_failure
                          and not self.task_missed_hb.is_set())
            if infra_only and self.preemption_retries_left > 0:
                self.preemption_retries_left -= 1
                log.warning(
                    "session %d lost to slice preemption — re-running "
                    "(%d preemption retries left)",
                    self.session.session_id, self.preemption_retries_left)
            elif self.retries_left > 0:
                self.retries_left -= 1
                log.warning(
                    "session %d failed (%s) — retrying (%d retries left)",
                    self.session.session_id, self.session.failure_message,
                    self.retries_left)
            else:
                break
            # reset (reference: reset:570-585): stop everything, new session.
            # In-flight launches from the failed session must land (or be
            # skipped by their liveness check — the verdict is set by now)
            # BEFORE the kill sweep, or a late launch would inject a
            # zombie into the new session's gang.
            self._drain_launches()
            self.backend.kill_all()
            # drain completion events from the killed generation so they are
            # not misattributed to the new session
            deadline = time.monotonic() + 10
            while any(not t.completed for t in self.session.all_tasks()
                      if t.status is not TaskStatus.NEW) \
                    and time.monotonic() < deadline:
                self._apply_completions(self.backend.poll_completed())
                time.sleep(0.1)
            self.hb_monitor.reset()
            self.task_missed_hb.clear()
            self._session_preempted = False
            self._session_real_failure = False
            # elastic state belongs to the dead session: pending losses,
            # barrier watches and queued regrows must not leak into the
            # rebuilt gang (the elastic BUDGET is job-scoped and persists)
            with self._completion_lock:
                self._elastic_pending.clear()
                self._elastic_bypass.clear()
                self._elastic_awaiting_exit.clear()
            self._elastic_awaiting_resume = False
            self._elastic_regrow_queue.clear()
            self._elastic_regrow_attempts.clear()
            # stale twin-report markers must not swallow the new session's
            # completions (session-id filtering already drops cross-session
            # RPC reports, but process-exit reports carry no session id)
            self._restart_dup.clear()
            # the dead session's launch errors must not mislabel a LATER
            # failure at stop() (the new session re-records its own)
            with self._launch_lock:
                self._launch_errors.clear()
            # the table holds the dead generation's snapshots; the new
            # session's executors repopulate it within one heartbeat
            self.metrics_table.clear()
            # goodput follows the same scoping: the dead session's ledger
            # wires, coordinator attributions and straggler EWMAs all
            # belong to it (the journal fold clears goodput_extra on
            # session_reset too, keeping replay and live state aligned)
            with self._goodput_lock:
                self._goodput_wires.clear()
                self._goodput_extra.clear()
            self.straggler = goodput_mod.StragglerDetector(
                factor=self.straggler.factor,
                windows=self.straggler.windows)
            self.events.emit(ev.SESSION_RESET,
                             old_session_id=self.session.session_id)
            # Keep the failed attempt's uptime: the north-star fraction must
            # reflect work lost to preemption/failure, not just the attempt
            # that finally succeeded.
            self._session_metrics.append(self.session.uptime_metrics())
            self.session = next_session(self.session)
            # per-task journal state starts over with the new session
            self._journal_append("session_reset",
                                 session_id=self.session.session_id)

        return self.stop(status)

    def _combined_uptime_metrics(self) -> dict:
        """Merge uptime across ALL attempts: the tracked fraction is the
        window-weighted mean over sessions, so time lost to preempted or
        failed attempts stays visible in the final number."""
        final = self.session.uptime_metrics()
        all_sessions = self._session_metrics + [final]
        # Single-node jobs run in the coordinator and never launch
        # executors, so their task entries (e.g. notebook:0) can never
        # register — a 0.0 fraction is an artifact, not an uptime signal.
        # Stripped from EVERY attempt, or a retried single-node job would
        # resurrect the artifact from a prior session's metrics.
        if self.conf.get_bool(K.APPLICATION_SINGLE_NODE_KEY, False):
            for m in all_sessions:
                m.pop("tracked_uptime_fraction", None)
        # Sessions without the fraction (no tracked tasks scheduled, e.g.
        # single-node/notebook) carry no uptime signal — excluded rather
        # than counted as zero.
        sessions = [m for m in all_sessions
                    if "tracked_uptime_fraction" in m]
        # An attempt whose gang never registered has window 0 but still
        # burned wall time — floor its weight at the session wall so lost
        # attempts cannot vanish from the combined fraction.
        weights = [m["tracked_window_s"] or m["session_wall_s"]
                   for m in sessions]
        total_w = sum(weights)
        if total_w > 0:
            final["tracked_uptime_fraction"] = round(
                sum(m["tracked_uptime_fraction"] * w
                    for m, w in zip(sessions, weights)) / total_w, 4)
        final["attempts"] = len(all_sessions)
        return final

    def stop(self, status: SessionStatus) -> int:
        # Idempotent: the signal handler's stop(KILLED) can land while the
        # main thread is ALREADY inside stop() (double SIGTERM, or a
        # client kill racing normal teardown) — re-running the teardown
        # would double-emit terminal events and re-enter non-reentrant
        # backend kills. First caller wins; later callers only read the
        # already-decided verdict.
        if self._stopping.is_set():
            return 0 if self.final_status == SessionStatus.SUCCEEDED.value \
                else 1
        self._stopping.set()
        self.final_status = status.value
        self.failure_message = self.failure_message or self.session.failure_message
        with self._launch_lock:
            launch_error = self._launch_errors[0] if self._launch_errors \
                else None
        if status is not SessionStatus.SUCCEEDED and launch_error:
            # A funneled launch failure reduces to a generic exit-code
            # line; attach the backend's actionable provisioning error.
            self.failure_message = (
                f"{self.failure_message} ({launch_error})"
                if self.failure_message else launch_error)
        log.info("application finished: %s (%s)", self.final_status,
                 self.failure_message or "ok")
        # Final-status file FIRST — it is the client's authoritative signal,
        # so the client is not kept waiting on our teardown.
        final = {"status": self.final_status,
                 "message": self.failure_message or "",
                 "app_id": self.app_id,
                 "tensorboard_url": self.tensorboard_url or ""}
        tmp = os.path.join(self.job_dir, FINAL_STATUS_FILE + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(final, f)
        os.replace(tmp, os.path.join(self.job_dir, FINAL_STATUS_FILE))
        self._kill_preprocess()
        # In-flight launches finish (or skip on their final_status check)
        # before the kill sweep; bounded so a minutes-long gcloud create
        # can't hold a client kill hostage — a straggler past the bound is
        # logged and the sweep proceeds.
        self._drain_launches(
            timeout=5 if os.environ.get("TONY_TEST_MODE") else 120)
        self.backend.kill_all()
        self.backend.stop()
        self.hb_monitor.stop()
        # Final launch-timing + metrics flush BEFORE the terminal event:
        # short jobs (and single-node jobs, which never reach the monitor
        # loop) still get their LAUNCH events and at least one
        # METRICS_SNAPSHOT for the history replay.
        self._drain_launch_timings()
        # close the job root span (so the exported trace brackets the
        # whole job) and, on a non-success, dump the coordinator's
        # flight ring — the job-level postmortem artifact
        if self.job_span is not None:
            self.job_span.end(status=self.final_status)
        if status is not SessionStatus.SUCCEEDED:
            tracing.get_flight().record(
                "job_finished", status=self.final_status,
                message=(self.failure_message or "")[:500])
            tracing.get_flight().dump(
                f"job_{(self.final_status or 'failed').lower()}")
        self._maybe_emit_metrics(force=True)
        self.events.emit(
            ev.APPLICATION_FINISHED, app_id=self.app_id,
            status=self.final_status,
            # triage cause in the history UI (e.g. "lost contact with the
            # coordinator" vs a user-code exit)
            message=self.failure_message or "",
            failed_tasks=[t.task_id for t in self.session.all_tasks()
                          if t.status is TaskStatus.FAILED],
            metrics=self._combined_uptime_metrics())
        try:
            self.events.stop(self.final_status)
        except OSError:
            # History publish failure (e.g. transient gs:// error renaming
            # .inprogress) must not abort teardown: the final-status file
            # is already written, and the client must still get its RPC
            # finish handshake.
            log.warning("history event publish failed", exc_info=True)
        # Wait briefly for the client's finish signal (reference: stop:669-694
        # polls up to 30s for finishApplication), then stop serving RPC.
        self.client_signalled_finish.wait(
            timeout=5 if os.environ.get("TONY_TEST_MODE") else 30)
        self.rpc_server.stop()
        # The final-status file above is what marks the journal obsolete
        # (a future submit on this job dir starts fresh); close the handle
        # last so every record through teardown made it out.
        if self.journal is not None:
            self.journal.close()
        return 0 if status is SessionStatus.SUCCEEDED else 1


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    parser = argparse.ArgumentParser(prog="tony-coordinator")
    parser.add_argument("--conf_file", required=True)
    parser.add_argument("--app_id", required=True)
    parser.add_argument("--job_dir", required=True)
    parser.add_argument("--task_command", required=True)
    args = parser.parse_args(argv)
    conf = TonyConfig.from_file(args.conf_file)
    coordinator = Coordinator(conf, args.app_id, args.job_dir)

    def _terminate(signum, frame):
        # Client timeout kill / Ctrl-C: executors and user processes run in
        # their own process groups, so without this sweep they would outlive
        # the coordinator (the reference relies on YARN reclaiming
        # containers; here we are the reaper).
        log.warning("received signal %d — killing all tasks and exiting",
                    signum)
        try:
            coordinator.failure_message = f"killed by signal {signum}"
            coordinator.stop(SessionStatus.KILLED)
        finally:
            os._exit(1)

    import signal as _signal
    _signal.signal(_signal.SIGTERM, _terminate)
    _signal.signal(_signal.SIGINT, _terminate)
    return coordinator.run(args.task_command)


if __name__ == "__main__":
    sys.exit(main())
