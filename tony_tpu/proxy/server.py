"""Gateway→cluster TCP proxy.

Analog of the reference's tony-proxy module (reference: tony-proxy/src/main/
java/com/linkedin/tonyproxy/ProxyServer.java:23-93): a thread-per-connection
bidirectional byte pump, used by the notebook submitter to expose a notebook
running on a cluster/TPU host on a local gateway port. Unlike the reference
(which blocks forever in ``start()``), this one runs its accept loop on a
daemon thread and supports clean shutdown, so the client can run it alongside
its monitor loop and tests can start/stop it freely.

Usage::

    proxy = ProxyServer(remote_host, remote_port, local_port=0)
    port = proxy.start()          # returns the bound local port
    ...
    proxy.stop()

Also runnable standalone::

    python -m tony_tpu.proxy.server --remote host:8888 --port 9999
"""

from __future__ import annotations

import argparse
import logging
import socket
import threading

log = logging.getLogger(__name__)

_BUF = 1 << 16


def _pump(src: socket.socket, dst: socket.socket) -> None:
    """Copy bytes src→dst until EOF, then half-close dst's write side."""
    try:
        while True:
            data = src.recv(_BUF)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass


class ProxyServer:
    """Forward connections on a local port to ``remote_host:remote_port``."""

    def __init__(self, remote_host: str, remote_port: int,
                 local_port: int = 0, bind_host: str = "127.0.0.1") -> None:
        self.remote_host = remote_host
        self.remote_port = remote_port
        self.local_port = local_port
        # Loopback by default: the proxied service (e.g. a tokenless
        # notebook) must not be exposed to the whole network just because
        # the gateway has more interfaces; remote users tunnel via ssh -L.
        self.bind_host = bind_host
        self._server: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = threading.Event()

    def start(self) -> int:
        """Bind and start accepting on a daemon thread; return bound port."""
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((self.bind_host, self.local_port))
        server.listen(16)
        self.local_port = server.getsockname()[1]
        self._server = server
        log.info("proxy for %s:%s listening on local port %s",
                 self.remote_host, self.remote_port, self.local_port)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tony-proxy-accept", daemon=True)
        self._accept_thread.start()
        return self.local_port

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stopping.is_set():
            try:
                client, addr = self._server.accept()
            except OSError:
                break                      # socket closed by stop()
            threading.Thread(target=self._handle, args=(client,),
                             name=f"tony-proxy-{addr[1]}",
                             daemon=True).start()

    def _handle(self, client: socket.socket) -> None:
        try:
            upstream = socket.create_connection(
                (self.remote_host, self.remote_port), timeout=10)
        except OSError as e:
            log.warning("proxy: cannot reach %s:%s: %s",
                        self.remote_host, self.remote_port, e)
            client.close()
            return
        # TCP_NODELAY both sides: the proxied payloads are interactive
        # (notebook keystrokes, token-delta frames) — Nagle coalescing
        # behind an unacked segment adds up to ~40 ms per small write
        for s in (client, upstream):
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        upstream.settimeout(None)
        t = threading.Thread(target=_pump, args=(client, upstream),
                             name="tony-proxy-pump", daemon=True)
        t.start()
        _pump(upstream, client)
        t.join()
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            try:
                # shutdown() wakes the thread blocked in accept(); close()
                # alone leaves the fd referenced by the blocked syscall and
                # the port bound until the join times out.
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def serve_forever(self) -> None:
        """Blocking variant mirroring the reference's ``start()``."""
        if self._server is None:
            self.start()
        try:
            self._stopping.wait()
        except KeyboardInterrupt:
            self.stop()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tony-proxy",
        description="TCP proxy from a local gateway port to a cluster host")
    parser.add_argument("--remote", required=True, metavar="HOST:PORT")
    parser.add_argument("--port", type=int, default=0,
                        help="local port (0 = ephemeral)")
    parser.add_argument("--bind", default="127.0.0.1",
                        help="local interface to listen on (default loopback)")
    args = parser.parse_args(argv)
    host, _, port = args.remote.rpartition(":")
    logging.basicConfig(level=logging.INFO)
    proxy = ProxyServer(host, int(port), args.port, bind_host=args.bind)
    print(f"listening on {proxy.start()}", flush=True)
    proxy.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
