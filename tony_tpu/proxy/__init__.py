from tony_tpu.proxy.server import ProxyServer

__all__ = ["ProxyServer"]
