"""Job-history events: schema, async writer, filename codec, parser.

Rebuild of the reference's events layer (reference: tony-core/src/main/avro/
*.avsc schemas, events/EventHandler.java:22-134, util/HistoryFileUtils.java:
11-32, util/ParserUtils.java). The reference appends Avro records to an
``.jhist.inprogress`` file on HDFS from a background thread and renames it to
``appId-started[-completed]-user-STATUS.jhist`` on completion; the history
server replays them. We keep the exact lifecycle and filename codec but encode
events as JSON-lines (self-describing, no Avro runtime in this image; the
schema below mirrors Event.avsc's
``{type, event, timestamp}`` union shape).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import threading
import time
from dataclasses import asdict, dataclass, field

log = logging.getLogger(__name__)

# Event types (reference: EventType.avsc — APPLICATION_INITED/FINISHED; we add
# the finer-grained task lifecycle the reference's TODOs point at).
APPLICATION_INITED = "APPLICATION_INITED"
APPLICATION_FINISHED = "APPLICATION_FINISHED"
TASK_SCHEDULED = "TASK_SCHEDULED"
TASK_REGISTERED = "TASK_REGISTERED"
TASK_FINISHED = "TASK_FINISHED"
SESSION_RESET = "SESSION_RESET"


@dataclass
class Event:
    """Mirror of Event.avsc: {event_type, payload union, timestamp(ms)}."""
    event_type: str
    payload: dict = field(default_factory=dict)
    timestamp: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Event":
        d = json.loads(line)
        return cls(d["event_type"], d.get("payload", {}), d.get("timestamp", 0))


# ---------------------------------------------------------------------------
# Filename codec (reference: HistoryFileUtils.generateFileName:11-32):
#   appId-started[-completed]-user[-STATUS].jhist[.inprogress]
# ---------------------------------------------------------------------------
_HIST_RE = re.compile(
    r"^(?P<app>[\w\-]+?)-(?P<started>\d+)(?:-(?P<completed>\d+))?"
    r"-(?P<user>[a-zA-Z][\w]*?)(?:-(?P<status>SUCCEEDED|FAILED|KILLED|RUNNING))?"
    r"\.jhist(?P<inprogress>\.inprogress)?$")


def history_file_name(app_id: str, started_ms: int, user: str,
                      completed_ms: int | None = None,
                      status: str | None = None,
                      in_progress: bool = False) -> str:
    parts = [app_id, str(started_ms)]
    if completed_ms is not None:
        parts.append(str(completed_ms))
    parts.append(user)
    if status:
        parts.append(status)
    name = "-".join(parts) + ".jhist"
    return name + ".inprogress" if in_progress else name


@dataclass
class JobMetadata:
    """Parsed jhist filename (reference: models/JobMetadata.java:31-44)."""
    app_id: str
    started_ms: int
    user: str
    completed_ms: int | None = None
    status: str | None = None
    in_progress: bool = False

    @classmethod
    def from_file_name(cls, name: str) -> "JobMetadata | None":
        m = _HIST_RE.match(os.path.basename(name))
        if not m:
            return None
        return cls(app_id=m.group("app"), started_ms=int(m.group("started")),
                   user=m.group("user"),
                   completed_ms=(int(m.group("completed"))
                                 if m.group("completed") else None),
                   status=m.group("status"),
                   in_progress=bool(m.group("inprogress")))


def is_valid_history_file_name(name: str) -> bool:
    """Reference: ParserUtils.isValidHistFileName:60."""
    return JobMetadata.from_file_name(name) is not None


# ---------------------------------------------------------------------------
# Async writer (reference: EventHandler.java — blocking queue drained by a
# daemon thread into the .inprogress file; stop() drains and renames).
# ---------------------------------------------------------------------------
class EventHandler:
    def __init__(self, history_dir: str, app_id: str, user: str) -> None:
        self.history_dir = history_dir
        self.app_id = app_id
        self.user = user
        self.started_ms = int(time.time() * 1000)
        self._queue: queue.Queue[Event | None] = queue.Queue()
        self._thread: threading.Thread | None = None
        os.makedirs(history_dir, exist_ok=True)
        self._inprogress_path = os.path.join(
            history_dir,
            history_file_name(app_id, self.started_ms, user, in_progress=True))
        self.final_path: str | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="event-handler",
                                        daemon=True)
        self._thread.start()

    def emit(self, event_type: str, **payload) -> None:
        self._queue.put(Event(event_type, payload, int(time.time() * 1000)))

    def _run(self) -> None:
        with open(self._inprogress_path, "a", encoding="utf-8") as f:
            while True:
                ev = self._queue.get()
                if ev is None:
                    break
                f.write(ev.to_json() + "\n")
                f.flush()

    def stop(self, status: str) -> str:
        """Drain queue, close, rename to final name (EventHandler.stop:125)."""
        self._queue.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        completed = int(time.time() * 1000)
        self.final_path = os.path.join(
            self.history_dir,
            history_file_name(self.app_id, self.started_ms, self.user,
                              completed_ms=completed, status=status))
        os.replace(self._inprogress_path, self.final_path)
        return self.final_path


def parse_events(path: str) -> list[Event]:
    """Replay an event file (reference: ParserUtils.parseEvents:176)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(Event.from_json(line))
            except (json.JSONDecodeError, KeyError):
                log.warning("skipping malformed event line in %s", path)
    return events


def find_job_files(history_dir: str) -> list[str]:
    """Recursive jhist discovery (reference: HdfsUtils.getJobFolders:123)."""
    out = []
    for root, _, files in os.walk(history_dir):
        for name in files:
            if is_valid_history_file_name(name):
                out.append(os.path.join(root, name))
    return sorted(out)
