"""Job-history events: schema, async writer, filename codec, parser.

Rebuild of the reference's events layer (reference: tony-core/src/main/avro/
*.avsc schemas, events/EventHandler.java:22-134, util/HistoryFileUtils.java:
11-32, util/ParserUtils.java). The reference appends Avro records to an
``.jhist.inprogress`` file on HDFS from a background thread and renames it to
``appId-started[-completed]-user-STATUS.jhist`` on completion; the history
server replays them. We keep the exact lifecycle and filename codec but encode
events as JSON-lines (self-describing, no Avro runtime in this image; the
schema below mirrors Event.avsc's
``{type, event, timestamp}`` union shape).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import threading
import time
from dataclasses import asdict, dataclass, field

from tony_tpu.storage import is_remote, sjoin, storage_for

log = logging.getLogger(__name__)

# Event types (reference: EventType.avsc — APPLICATION_INITED/FINISHED; we add
# the finer-grained task lifecycle the reference's TODOs point at).
APPLICATION_INITED = "APPLICATION_INITED"
APPLICATION_FINISHED = "APPLICATION_FINISHED"
TASK_SCHEDULED = "TASK_SCHEDULED"
TASK_REGISTERED = "TASK_REGISTERED"
TASK_FINISHED = "TASK_FINISHED"
TASK_RESTARTED = "TASK_RESTARTED"      # in-session single-task relaunch
SESSION_RESET = "SESSION_RESET"
# Elastic transitions (tony.elastic.enabled): the session survives a gang
# loss without a reset. SHRINK cuts a new cluster-spec epoch over the
# survivors (payload {"epoch", "lost": [task ids], "active", "session_id"}),
# RESUMED marks the survivors' barrier re-releasing (payload {"epoch",
# "active", "recovery_wall_s", "session_id"} — recovery_wall_s is the
# shrink→barrier wall, the headline recovery number), REGROW marks
# replacement capacity folding back in (payload {"epoch", "regrown":
# [task ids], "active", "session_id"}).
ELASTIC_SHRINK = "ELASTIC_SHRINK"
ELASTIC_RESUMED = "ELASTIC_RESUMED"
ELASTIC_REGROW = "ELASTIC_REGROW"
# A relaunched coordinator recovered the session from its journal
# instead of re-provisioning: payload {"incarnation", "adopted":
# [task ids re-adopted live], "completed": n, "session_id"}. Written to
# the NEW coordinator's own jhist (the predecessor's .inprogress file is
# orphaned by the crash); zero TASK_SCHEDULED events after it is the
# history-visible proof that recovery launched nothing.
COORDINATOR_RESTART = "COORDINATOR_RESTART"
# Per-gang bring-up wall timing, one event per backend launch phase:
# payload {"gang", "phase": "provision"|"stage"|"dispatch", "seconds",
# "task"?, "cached"? (stage skipped via content-stamp match),
# "reprovision"?, "session_id"}. Drained from the backend by the
# coordinator's monitor loop, so the history server can show where
# startup time went (and whether the staging cache hit).
LAUNCH = "LAUNCH"
# Periodic coordinator-aggregated metrics: payload {"tasks": {task_id:
# wire snapshot (runtime/metrics.py to_wire)}, "session_id": n}. Emitted
# on tony.metrics.snapshot-interval-ms cadence while tasks run, plus one
# final emit at stop, so the history server can render live gauges from
# the .inprogress stream and reconstruct a finished job's series purely
# from the jhist replay.
METRICS_SNAPSHOT = "METRICS_SNAPSHOT"
# Coordinator-folded trace spans (runtime/tracing.py): payload {"task":
# task_id, "spans": [compact span dicts, timestamps already shifted by
# the task's clock-offset estimate], "offset_s": applied offset,
# "session_id"}. One event per heartbeat-shipped batch, emitted on the
# metrics-snapshot cadence; the history server renders every batch of a
# job as one Chrome-trace JSON (GET /api/jobs/<id>/trace).
TRACE_SPAN = "TRACE_SPAN"
# Periodic coordinator-aggregated goodput ledger (runtime/goodput.py):
# payload {"tasks": {task_id: {"t0", "now" (both clock-offset-corrected
# to coordinator time), "cat": {category: cumulative seconds}, "cur",
# "n", "sw", "extra": {category: coordinator-attributed seconds}}},
# "fraction": job-level goodput fraction, "session_id"}. Cumulative like
# METRICS_SNAPSHOT — the LAST event of a job is its complete breakdown,
# so GET /api/jobs/<id>/goodput replays it bit-exact.
GOODPUT = "GOODPUT"
# The straggler detector flagged a task: its step-wall EWMA exceeded the
# gang median by tony.straggler.factor for tony.straggler.windows
# consecutive windows. Payload {"task", "gang", "ewma_s", "median_s",
# "factor", "windows", "session_id"} — the evidence, not just the verdict.
STRAGGLER_SUSPECTED = "STRAGGLER_SUSPECTED"
# A previously-suspected task dropped back under the threshold (one
# window is enough to clear; flapping shows up as SUSPECTED/CLEARED
# pairs). Payload {"task", "session_id"}.
STRAGGLER_CLEARED = "STRAGGLER_CLEARED"
# Cluster-daemon lifecycle (one jhist per daemon incarnation; the
# history server's /cluster dashboard is replayed from these alone).
# A job entered the daemon's queue. Payload {"job_id", "user",
# "priority", "slices", "digest"}.
JOB_QUEUED = "JOB_QUEUED"
# A gang grant: all slices at once. Payload {"job_id", "slice_ids",
# "warm_hits", "queue_wait_s"} — warm_hits counts digest-matching
# slices (warm adoption), queue_wait_s this queued episode's wait.
JOB_GRANTED = "JOB_GRANTED"
# A victim's checkpoint fence committed and its slices drained back to
# the pool. Payload {"job_id", "fence_step", "released", "requeued"} —
# requeued=True means a shrink to zero (the job re-enters the queue
# resuming from fence_step).
JOB_PREEMPTED = "JOB_PREEMPTED"
# Terminal transition of a daemon-scheduled job. Payload {"job_id",
# "status", "queue_wait_s", "warm_hits", "preemptions"}.
JOB_COMPLETED = "JOB_COMPLETED"


@dataclass
class Event:
    """Mirror of Event.avsc: {event_type, payload union, timestamp(ms)}."""
    event_type: str
    payload: dict = field(default_factory=dict)
    timestamp: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Event":
        d = json.loads(line)
        return cls(d["event_type"], d.get("payload", {}), d.get("timestamp", 0))


# ---------------------------------------------------------------------------
# Filename codec (reference: HistoryFileUtils.generateFileName:11-32):
#   appId-started[-completed]-user[-STATUS].jhist[.inprogress]
# ---------------------------------------------------------------------------
# User names may contain hyphens and start with digits (USER=john-doe,
# USER=4dmin); the user field is therefore matched lazily with the status
# token and extension anchoring the right edge. App ids generated by
# new_app_id() use underscores (application_<ts>_<rand>), keeping the parse
# unambiguous; an app id containing "-<digits>-" would be inherently
# ambiguous in this (reference-inherited) codec.
_HIST_RE = re.compile(
    r"^(?P<app>[\w\-]+?)-(?P<started>\d+)(?:-(?P<completed>\d+))?"
    r"-(?P<user>[\w][\w\-]*?)(?:-(?P<status>SUCCEEDED|FAILED|KILLED|RUNNING))?"
    r"\.jhist(?P<inprogress>\.inprogress)?$")


def history_file_name(app_id: str, started_ms: int, user: str,
                      completed_ms: int | None = None,
                      status: str | None = None,
                      in_progress: bool = False) -> str:
    parts = [app_id, str(started_ms)]
    if completed_ms is not None:
        parts.append(str(completed_ms))
    parts.append(user)
    if status:
        parts.append(status)
    name = "-".join(parts) + ".jhist"
    return name + ".inprogress" if in_progress else name


@dataclass
class JobMetadata:
    """Parsed jhist filename (reference: models/JobMetadata.java:31-44)."""
    app_id: str
    started_ms: int
    user: str
    completed_ms: int | None = None
    status: str | None = None
    in_progress: bool = False

    @classmethod
    def from_file_name(cls, name: str) -> "JobMetadata | None":
        m = _HIST_RE.match(os.path.basename(name))
        if not m:
            return None
        user = m.group("user")
        completed = int(m.group("completed")) if m.group("completed") else None
        # Disambiguation: a user like "007-james" makes the regex steal the
        # leading digits as completed_ms. Completion can never precede start
        # (both are epoch-ms from the same clock), so such a parse is really
        # part of the user name.
        if completed is not None and completed < int(m.group("started")):
            user = f"{m.group('completed')}-{user}"
            completed = None
        return cls(app_id=m.group("app"), started_ms=int(m.group("started")),
                   user=user, completed_ms=completed,
                   status=m.group("status"),
                   in_progress=bool(m.group("inprogress")))


def is_valid_history_file_name(name: str) -> bool:
    """Reference: ParserUtils.isValidHistFileName:60."""
    return JobMetadata.from_file_name(name) is not None


# Shared between the coordinator (writer) and the history server (reader) so
# their defaults cannot drift apart.
DEFAULT_HISTORY_LOCATION = "tony-history"


@dataclass
class HistoryDirs:
    """History directory layout, the ONE place it is derived from config
    (reference: hadoop/Requirements.java creates these on startup). Used by
    the client (freezing absolute paths), the coordinator (writer), and the
    history server (reader)."""
    location: str
    intermediate: str
    finished: str

    @classmethod
    def from_conf(cls, conf) -> "HistoryDirs":
        from tony_tpu.conf import keys as K
        location = conf.get(K.HISTORY_LOCATION_KEY) or DEFAULT_HISTORY_LOCATION
        intermediate = (conf.get(K.HISTORY_INTERMEDIATE_KEY)
                        or sjoin(location, "intermediate"))
        finished = (conf.get(K.HISTORY_FINISHED_KEY)
                    or sjoin(location, "finished"))
        return cls(location, intermediate, finished)

    def absolutized(self) -> "HistoryDirs":
        # gs:// locations are already absolute; abspath would mangle them.
        return HistoryDirs(*(d if is_remote(d) else os.path.abspath(d)
                             for d in (self.location, self.intermediate,
                                       self.finished)))

    def ensure(self) -> None:
        for d in (self.location, self.intermediate, self.finished):
            storage_for(d).makedirs(d)


def config_file_name(app_id: str) -> str:
    """Per-job frozen-config file colocated with the jhist (reference keeps a
    config.xml in each job's history folder, TonyApplicationMaster.java
    writeConfigFile :458-463)."""
    return f"{app_id}.config.xml"


# ---------------------------------------------------------------------------
# Async writer (reference: EventHandler.java — blocking queue drained by a
# daemon thread into the .inprogress file; stop() drains and renames).
# ---------------------------------------------------------------------------
class EventHandler:
    def __init__(self, history_dir: str, app_id: str, user: str) -> None:
        self.history_dir = history_dir
        self.app_id = app_id
        self.user = user
        self.started_ms = int(time.time() * 1000)
        self._queue: queue.Queue[Event | None] = queue.Queue()
        self._thread: threading.Thread | None = None
        #: serializes emit()'s closed-check-then-put against stop()'s
        #: close-then-sentinel, so no event can land BEHIND the sentinel
        #: in the dead queue (event traffic is a handful of records per
        #: job — the lock is nowhere near a hot path)
        self._emit_lock = threading.Lock()
        #: stop() initiated: emits are dropped from here on
        self._closed = False
        #: stop() COMPLETED (the rename landed): further stop()s no-op.
        #: Two flags so a failed rename stays retryable while emits are
        #: already refused.
        self._stopped = False
        self._storage = storage_for(history_dir)
        self._storage.makedirs(history_dir)
        self._inprogress_path = sjoin(
            history_dir,
            history_file_name(app_id, self.started_ms, user, in_progress=True))
        self.final_path: str | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="tony-event-handler",
                                        daemon=True)
        self._thread.start()

    def emit(self, event_type: str, **payload) -> None:
        with self._emit_lock:
            if self._closed:
                # The writer thread is gone (or going) — an enqueue here
                # would vanish silently into a dead queue. Dropped loudly
                # rather than raised: late emitters are teardown races (a
                # straggler RPC thread), not callers with a recovery path.
                log.warning("emit(%s) after stop() — event dropped",
                            event_type)
                return
            self._queue.put(Event(event_type, payload,
                                  int(time.time() * 1000)))

    def _run(self) -> None:
        # flush per event keeps the .inprogress file live-readable by the
        # history server (the reference's HDFS append visibility; on GCS
        # the storage layer re-uploads the object per flush — event traffic
        # is a handful of lifecycle records per job).
        f = self._storage.open_append(self._inprogress_path)
        try:
            while True:
                ev = self._queue.get()
                if ev is None:
                    break
                f.write(ev.to_json() + "\n")
                try:
                    f.flush()
                except OSError:
                    # A transient backend error must not kill the writer
                    # thread (later flushes re-upload the whole buffer on
                    # remote storage, so nothing is lost on recovery).
                    log.warning("event flush failed; will retry on next "
                                "event", exc_info=True)
        finally:
            try:
                f.close()
            except OSError:
                log.warning("event stream close failed", exc_info=True)

    def stop(self, status: str) -> str:
        """Drain queue, close, rename to final name (EventHandler.stop:125).
        Idempotent once the rename LANDED: a second stop() returns the
        final path without re-renaming (the first call's status wins). A
        stop() whose rename failed stays retryable — the completed flag
        latches only after the move succeeds, so a transient storage
        error doesn't strand the file as .inprogress forever while
        reporting a final path that was never created."""
        if self._stopped:
            assert self.final_path is not None
            return self.final_path
        if not self._closed:
            with self._emit_lock:
                # under the emit lock: every emit either enqueued BEFORE
                # this sentinel (the writer drains it) or observes
                # _closed and drops with a warning — no silent loss
                self._closed = True
                self._queue.put(None)
            if self._thread:
                self._thread.join(timeout=10)
        completed = int(time.time() * 1000)
        final_path = sjoin(
            self.history_dir,
            history_file_name(self.app_id, self.started_ms, self.user,
                              completed_ms=completed, status=status))
        self._storage.move(self._inprogress_path, final_path)
        self.final_path = final_path
        self._stopped = True
        return final_path


def parse_events(path: str) -> list[Event]:
    """Replay an event file (reference: ParserUtils.parseEvents:176)."""
    events = []
    data = storage_for(path).read_bytes(path).decode("utf-8")
    for line in data.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            events.append(Event.from_json(line))
        except (json.JSONDecodeError, KeyError):
            log.warning("skipping malformed event line in %s", path)
    return events


def find_job_files(history_dir: str) -> list[str]:
    """Recursive jhist discovery (reference: HdfsUtils.getJobFolders:123)."""
    out = []
    for root, files in storage_for(history_dir).walk_files(history_dir):
        for name in files:
            if is_valid_history_file_name(name):
                out.append(sjoin(root, name))
    return sorted(out)
