"""Cloud TPU pod-slice backend: gang allocation of slice hosts.

The substrate the whole framework exists for. Where the reference negotiates
per-container allocations from the YARN RM (reference: TonyApplicationMaster
.java:927-941 setupContainerRequestForRM, RMCallbackHandler.
onContainersAllocated:1031), this backend provisions **whole pod slices** —
the key impedance mismatch called out in SURVEY.md §7: a slice arrives as a
gang (all hosts at once, one allocation = N worker processes), is preempted
as a gang, and is released as a gang.

Mechanics: one TPU VM (slice) per *gang* — a job type that requests TPUs gets
``tony.{job}.slices`` gangs (default 1), each a whole pod slice — created via
the ``gcloud compute tpus tpu-vm`` CLI (the only dependency-free path — the
Cloud TPU REST API would need google-api-python-client, which is not baked
in). Task index i of an S-slice job type maps to slice i // hosts_per_slice,
host i % hosts_per_slice; preemption is detected and reprovisioned per gang. After provisioning, the job dir (tony-final.xml, staged sources, venv
zip, and a ``.tony-framework/`` copy of this package) is localized onto every
slice host at ``~/tony-job`` — the container-localization analog (reference:
TonyClient.java:163-192 uploads src/venv/conf to HDFS staging and
TonyApplicationMaster.java:1090-1104 localizes them into each container).
Two transports: a tarball over ``gcloud ... scp`` (default), or a
``gsutil rsync`` pull when the client staged to gs://
(tony.staging.remote-job-dir). Each host then runs one task executor over
``gcloud ... ssh --worker=<i>`` with cwd ``~/tony-job``. Completion is
observed by polling the ssh-launched processes, and slice preemption
(state=PREEMPTED) is reported with ``preempted=True`` so the coordinator can
retry the session rather than fail it.

This backend requires GCP credentials and egress; in the development image it
is exercised end-to-end against a fake ``gcloud`` on PATH
(tests/test_tpu_backend_e2e.py) that runs ssh commands as local processes —
the MiniYARN trick — plus command-plan unit tests in the reference's style
(TestTonyClient.java:23-31).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import logging
import shlex
import shutil
import subprocess
import tarfile
import threading
import time

from tony_tpu import constants
from tony_tpu.backend.base import CompletionEvent, LaunchSpec, SchedulerBackend
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig

log = logging.getLogger(__name__)

#: job-dir location on every slice host
REMOTE_JOB_DIR = "~/tony-job"
#: subdir (inside the job dir) carrying the tony_tpu package itself, so
#: slice hosts need no pip install — the fat-jar-on-HDFS analog
#: (reference: cli/ClusterSubmitter.java:37-61 ships tony's own jar)
FRAMEWORK_DIR = ".tony-framework"
#: content stamp written on every host as the LAST staging command: holds
#: the sha256 of the staged tree, so a later stage of the same content
#: (session retry, warm coordinator restart onto a surviving slice) is a
#: one-ssh probe instead of a full tarball ship + untar
STAGE_DIGEST_FILE = ".tony-stage.digest"

#: job-dir entries excluded from the stage tarball AND the content digest.
#: Two reasons to be here: per-run volatile files (logs, the coordinator's
#: published address/status, the digest artifacts themselves) that would
#: make a retried coordinator hash a different tree for identical content,
#: and secrets that must never ride a user-readable tarball — the auth
#: secret travels only as a chmod-600 scp'd file, the TLS PRIVATE key and
#: the GCS token never leave the coordinator host at all (executors get
#: the public cert scp'd separately).
STAGE_EXCLUDE = frozenset({
    constants.TONY_LOG_DIR, ".tony-stage.tgz", STAGE_DIGEST_FILE,
    constants.TONY_SECRET_FILE, constants.TONY_TLS_KEY_FILE,
    ".gcs-token", ".history-config.xml",
    constants.COORDINATOR_ADDR_FILE, constants.FINAL_STATUS_FILE,
    constants.FINAL_STATUS_FILE + ".tmp",
})


def compute_stage_digest(job_dir: str) -> str:
    """sha256 over everything the stage tarball would ship from
    ``job_dir`` (top-level STAGE_EXCLUDE entries pruned — the same set
    the tarball skips), in sorted-walk order: file contents AND
    permission bits, symlink targets (file and directory links alike —
    ``os.walk`` lists unfollowed dir-symlinks under ``dirs``), and
    directory entries themselves (an added empty dir changes the tree).
    Deliberately mtime-free: the gzip header of a rebuilt tarball
    carries a fresh mtime, so hashing tarball BYTES would never match
    across coordinator attempts even when nothing changed."""
    h = hashlib.sha256()
    base = os.path.abspath(job_dir)

    def mode_of(path: str) -> bytes:
        try:
            return oct(os.lstat(path).st_mode & 0o7777).encode()
        except OSError:
            return b"?"

    def entry(kind: bytes, relp: str, tail: bytes) -> None:
        h.update(kind + relp.encode() + b"\0" + tail + b"\0")

    for root, dirs, files in os.walk(base):
        if root == base:
            dirs[:] = sorted(d for d in dirs if d not in STAGE_EXCLUDE)
            files = [f for f in files if f not in STAGE_EXCLUDE]
        else:
            dirs.sort()
        rel = os.path.relpath(root, base)
        for name in dirs:
            path = os.path.join(root, name)
            relp = os.path.normpath(os.path.join(rel, name))
            if os.path.islink(path):
                entry(b"l", relp, os.readlink(path).encode())
            else:
                entry(b"d", relp, mode_of(path))
        for name in sorted(files):
            path = os.path.join(root, name)
            relp = os.path.normpath(os.path.join(rel, name))
            if os.path.islink(path):
                entry(b"l", relp, os.readlink(path).encode())
                continue
            entry(b"f", relp, mode_of(path))
            try:
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
            except OSError:
                # vanished mid-walk (a racing writer): salt the digest so
                # the stage ships rather than stamping unverified content
                h.update(os.urandom(16))
            h.update(b"\0")
    return h.hexdigest()


class TpuProvisioningError(RuntimeError):
    pass


#: stderr markers that make a failed `gcloud create` worth retrying with
#: backoff — capacity and transient API conditions. Anything else (bad
#: accelerator type/topology, auth/permission) is a configuration error
#: whose actionable message must surface immediately.
_RETRYABLE_CREATE = ("RESOURCE_EXHAUSTED", "QUOTA", "quota",
                     "UNAVAILABLE", "RATE_LIMIT", "rate limit",
                     "INTERNAL", "try again", "DEADLINE_EXCEEDED",
                     "ABORTED", "stockout", "no more capacity")


def _retryable_create_error(stderr: str) -> bool:
    return any(m in stderr for m in _RETRYABLE_CREATE)


def slice_name(app_id: str, job_type: str, slice_idx: int = 0,
               num_slices: int = 1) -> str:
    """One TPU VM name per gang. Multi-slice job types (tony.{job}.slices=N)
    get an -s<i> suffix on every gang; single-slice names stay unsuffixed so
    they match what operators see for the common case."""
    base = f"tony-{app_id.replace('_', '-')}-{job_type}"
    if num_slices > 1:
        return f"{base[:56]}-s{slice_idx}"[:61]
    return base[:61]


class TpuSliceBackend(SchedulerBackend):
    """Gang-scheduled TPU slices via the gcloud CLI."""

    def __init__(self, conf: TonyConfig, app_id: str = "app",
                 dry_run: bool = False) -> None:
        self.conf = conf
        self.app_id = app_id
        self.dry_run = dry_run
        self.project = conf.get(K.TPU_PROJECT_KEY) or ""
        self.zone = conf.get(K.TPU_ZONE_KEY) or ""
        self.accelerator_type = conf.get(K.TPU_ACCELERATOR_TYPE_KEY) or ""
        self.runtime_version = conf.get(K.TPU_RUNTIME_VERSION_KEY) or ""
        self.preemptible = conf.get_bool(K.TPU_PREEMPTIBLE_KEY, False)
        # Placement label passthrough (the YARN node-label analog,
        # reference: tony.application.node-label): attached as a GCE label
        # so reservations/affinity tooling can match slices.
        self.node_label = conf.get(K.APPLICATION_NODE_LABEL_KEY) or ""
        # gang key (job_type, slice_idx) -> {"name": VM name, "ready":
        # Event set once the gang is provisioned AND staged}. One entry per
        # provisioning GENERATION: a failed/reprovisioned gang gets a fresh
        # entry with a fresh event, so waiters can detect staleness by
        # re-fetching the entry after their event fires.
        self._gangs: dict[tuple[str, int], dict] = {}
        self._artifacts_lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        self._reported: set[str] = set()
        self._lock = threading.Lock()
        # Slice state is refreshed from the cloud API at most once per
        # tony.tpu.state-refresh-ms and NEVER under the lock — the monitor
        # polls 5x/s and a describe call can take seconds; hammering the API
        # from the hot loop while blocking kill/launch would both exhaust
        # quota and stall client kills behind network calls.
        self._state_refresh_s = conf.get_int(K.TPU_STATE_REFRESH_KEY,
                                             10000) / 1000.0
        self._state_cache: dict[str, str] = {}
        self._state_ts: dict[str, float] = {}
        self._artifacts_ready = False
        #: content digest of the stage artifacts, set when they are built
        self._stage_digest: str | None = None
        #: drained by the coordinator via take_launch_timings()
        self._timings: list[dict] = []
        self._timings_lock = threading.Lock()
        if not dry_run:
            if shutil.which("gcloud") is None:
                raise TpuProvisioningError(
                    "tony.scheduler.backend=tpu requires the gcloud CLI on "
                    "the coordinator host; it was not found on PATH. Use the "
                    "'local' backend for development.")
            if not (self.project and self.zone and self.accelerator_type):
                raise TpuProvisioningError(
                    "tony.scheduler.backend=tpu requires tony.tpu.project, "
                    "tony.tpu.zone and tony.tpu.accelerator-type to be set.")

    # ------------------------------------------------------------------
    # Multi-slice gang arithmetic (tony.{job}.slices = N gangs per job type;
    # task index i lives in gang i // hosts_per_slice at host i % hosts)
    # ------------------------------------------------------------------
    def _num_slices(self, job_type: str) -> int:
        return max(1, self.conf.get_int(K.slices_key(job_type), 1))

    def _hosts_per_slice(self, job_type: str) -> int:
        instances = self.conf.get_int(K.instances_key(job_type), 1)
        return max(1, instances // self._num_slices(job_type))

    def _gang_of(self, task_id: str) -> tuple[str, int, int]:
        """task id → (job_type, slice index, host index within the slice)."""
        job_type, _, idx = task_id.partition(":")
        n = self._num_slices(job_type)
        if n == 1:
            return job_type, 0, int(idx)
        hosts = self._hosts_per_slice(job_type)
        return job_type, int(idx) // hosts, int(idx) % hosts

    @staticmethod
    def _gang_label(gang: tuple[str, int]) -> str:
        """Human-readable form of a (job_type, slice_idx) gang key, for
        logs/errors only — state dicts use the tuple."""
        return f"{gang[0]}/s{gang[1]}"

    def _slice_name(self, job_type: str, slice_idx: int = 0) -> str:
        return slice_name(self.app_id, job_type, slice_idx,
                          self._num_slices(job_type))

    # ------------------------------------------------------------------
    # Command plans (unit-tested; executed via subprocess when not dry_run)
    # ------------------------------------------------------------------
    def create_slice_command(self, job_type: str, topology: str,
                             slice_idx: int = 0) -> list[str]:
        """``gcloud compute tpus tpu-vm create`` for one gang allocation.
        ``topology`` (tony.{job}.tpu.topology) picks the accelerator shape:
        the slice IS the resource ask — there is no per-container request
        (contrast Utils.setCapabilityGPU:167 requesting yarn.io/gpu units)."""
        name = self._slice_name(job_type, slice_idx)
        if topology and "-" not in self.accelerator_type:
            # "v5litepod" + topology "4x4" → "v5litepod-16" (chip count is
            # the product of the topology dims)
            chips = 1
            for d in topology.split("x"):
                chips *= int(d)
            accel = f"{self.accelerator_type}-{chips}"
        else:
            accel = self.accelerator_type
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
               f"--project={self.project}", f"--zone={self.zone}",
               f"--accelerator-type={accel}",
               f"--version={self.runtime_version}", "--quiet"]
        if self.preemptible:
            cmd.append("--preemptible")
        if self.node_label:
            # GCE label values: lowercase [a-z0-9_-], <=63 chars. YARN-style
            # labels ("GPU", "batch.pool") are sanitized rather than failing
            # the whole job at provision time with a gcloud error.
            label = re.sub(r"[^a-z0-9_-]", "-", self.node_label.lower())[:63]
            cmd.append(f"--labels=tony-node-label={label}")
        return cmd

    def ssh_command(self, job_type: str, host_index: int | str,
                    remote_command: str, slice_idx: int = 0) -> list[str]:
        """``host_index`` is a host number WITHIN the slice or ``"all"``
        (staging runs the same command on every host of the gang)."""
        name = self._slice_name(job_type, slice_idx)
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--worker={host_index}", "--quiet",
                f"--command={remote_command}"]

    def scp_command(self, job_type: str, local_path: str,
                    remote_path: str, slice_idx: int = 0) -> list[str]:
        name = self._slice_name(job_type, slice_idx)
        return ["gcloud", "compute", "tpus", "tpu-vm", "scp", local_path,
                f"{name}:{remote_path}",
                f"--project={self.project}", f"--zone={self.zone}",
                "--worker=all", "--quiet"]

    def stage_probe_command(self, job_type: str, digest: str,
                            slice_idx: int = 0) -> list[str]:
        """One ssh across the gang checking every host's content stamp
        against ``digest``. Exit 0 (all hosts match) means the staged tree
        is byte-identical to what we would ship — the scp+untar (or gsutil
        rsync) is skipped entirely; any mismatch/missing stamp falls back
        to the idempotent full re-stage."""
        probe = (f'[ "$(cat {REMOTE_JOB_DIR}/{STAGE_DIGEST_FILE} '
                 f'2>/dev/null)" = "{digest}" ]')
        return self.ssh_command(job_type, "all", probe, slice_idx)

    def stage_commands(self, job_type: str, job_dir: str,
                       slice_idx: int = 0,
                       digest: str | None = None) -> list[list[str]]:
        """Command plan localizing the job dir onto every slice host
        (reference: TonyApplicationMaster.java:1090-1104). gs:// pull when
        the client staged remotely, tarball-over-scp otherwise. The per-job
        auth secret travels ONLY as a chmod-600 scp'd file — never in the
        tarball (user-readable paths), the bucket, or any command argv.
        With ``digest``, the content stamp is written as the LAST command
        — only after every staging step (including the secret/cert ships)
        succeeded, so a partial stage can never probe as complete."""
        remote_staging = self.conf.get(K.REMOTE_JOB_DIR_KEY) or ""
        if remote_staging:
            pull = (f"rm -rf {REMOTE_JOB_DIR} && mkdir -p {REMOTE_JOB_DIR} "
                    f"&& gsutil -m rsync -r {shlex.quote(remote_staging)} "
                    f"{REMOTE_JOB_DIR}")
            cmds = [self.ssh_command(job_type, "all", pull, slice_idx)]
        else:
            tarball = os.path.join(job_dir, ".tony-stage.tgz")
            unpack = (f"rm -rf {REMOTE_JOB_DIR} && mkdir -p {REMOTE_JOB_DIR} "
                      f"&& tar -xzf /tmp/tony-stage.tgz -C {REMOTE_JOB_DIR} "
                      f"&& rm -f /tmp/tony-stage.tgz")
            cmds = [
                self.scp_command(job_type, tarball, "/tmp/tony-stage.tgz",
                                 slice_idx),
                self.ssh_command(job_type, "all", unpack, slice_idx),
            ]
        secret_path = os.path.join(job_dir, ".tony-secret")
        if os.path.exists(secret_path):
            cmds.append(self.scp_command(
                job_type, secret_path, f"{REMOTE_JOB_DIR}/.tony-secret",
                slice_idx))
            cmds.append(self.ssh_command(
                job_type, "all",
                f"chmod 600 {REMOTE_JOB_DIR}/.tony-secret", slice_idx))
        # Per-job TLS cert (rpc/tls.py): executors need only the PUBLIC
        # cert to pin their channels — the private key never leaves the
        # coordinator host.
        cert_path = os.path.join(job_dir, ".tony-tls.crt")
        if os.path.exists(cert_path):
            cmds.append(self.scp_command(
                job_type, cert_path, f"{REMOTE_JOB_DIR}/.tony-tls.crt",
                slice_idx))
        if digest:
            cmds.append(self.ssh_command(
                job_type, "all",
                f"echo {digest} > {REMOTE_JOB_DIR}/{STAGE_DIGEST_FILE}",
                slice_idx))
        return cmds

    def describe_command(self, job_type: str,
                         slice_idx: int = 0) -> list[str]:
        name = self._slice_name(job_type, slice_idx)
        return ["gcloud", "compute", "tpus", "tpu-vm", "describe", name,
                f"--project={self.project}", f"--zone={self.zone}",
                "--format=json"]

    def delete_slice_command(self, job_type: str, wait: bool = False,
                             slice_idx: int = 0) -> list[str]:
        """``wait=True`` (synchronous delete) is used on the reprovision
        path, where a create with the same name must not race the delete."""
        name = self._slice_name(job_type, slice_idx)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "delete", name,
               f"--project={self.project}", f"--zone={self.zone}", "--quiet"]
        if not wait:
            cmd.append("--async")
        return cmd

    # ------------------------------------------------------------------
    # SchedulerBackend surface
    # ------------------------------------------------------------------
    def launch_task(self, spec: LaunchSpec) -> None:
        job_type, slice_idx, host_idx = self._gang_of(spec.task_id)
        gang = (job_type, slice_idx)
        timeout_s = self.conf.get_int(K.TPU_PROVISION_TIMEOUT_KEY,
                                      600000) / 1000
        # Relaunch of a task id whose predecessor wrapper is STILL ALIVE
        # (possible on the in-session restart path): reap it locally AND
        # remotely, and WAIT for the remote reap before launching — its
        # pkill pattern would race the new executor into the grave. A
        # dead wrapper needs nothing: ssh returns when the remote command
        # exits, so the remote executor is already gone (and kill_all
        # handles whole-session teardown before session retries).
        with self._lock:
            old = self._procs.pop(spec.task_id, None)
        if old is not None and not self.dry_run and old.poll() is None:
            old.terminate()
            reaper = self._kill_remote(spec.task_id)
            try:
                old.wait(timeout=5)
            except subprocess.TimeoutExpired:
                old.kill()
            if reaper is not None:
                try:
                    reaper.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    reaper.kill()
        # Claim-or-wait under the lock; the slow work (gcloud delete/create,
        # staging — minutes) runs OUTSIDE it so poll_completed/kill paths
        # never stall behind provisioning, and independent gangs can
        # provision concurrently.
        with self._lock:
            # Relaunch of the same task id (session retry): forget the old
            # generation's completion so the new one is observed.
            self._reported.discard(spec.task_id)
            dead = gang in self._gangs and self._state_cache.get(gang) \
                in ("PREEMPTED", "TERMINATED")
            if dead:
                # The gang's slice is gone — a retried session must get a
                # fresh one, not instantly re-fail on the cached dead state.
                log.info("slice for %s was %s — reprovisioning",
                         self._gang_label(gang), self._state_cache[gang])
                del self._gangs[gang]
                self._state_cache.pop(gang, None)
                self._state_ts.pop(gang, None)
            if gang not in self._gangs:
                entry = {"name": self._slice_name(job_type, slice_idx),
                         "ready": threading.Event()}
                self._gangs[gang] = entry
                is_provisioner = True
            else:
                entry = self._gangs[gang]
                is_provisioner = False
        if is_provisioner:
            try:
                self._provision(job_type, slice_idx, spec, reprovision=dead)
            except BaseException:
                with self._lock:
                    # Only retract OUR generation — a concurrent retry may
                    # already have re-claimed the gang with a fresh entry.
                    if self._gangs.get(gang) is entry:
                        del self._gangs[gang]
                entry["ready"].set()  # wake waiters; they re-check below
                raise
            entry["ready"].set()
        else:
            self._await_gang(gang, timeout_s)
        with self._lock:
            # The auth secret must NOT ride the ssh argv (visible in ps /
            # /proc); the host reads it from the chmod-600 staged file.
            # TONY_TLS_CERT is a coordinator-LOCAL path in spec.env — the
            # remote export above points at the staged copy, and a K=V
            # prefix here would override it with a path that does not
            # exist on the slice host.
            env_prefix = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in spec.env.items()
                if k not in ("TONY_SECRET", "TONY_TLS_CERT"))
            secret_src = (
                f"[ -f {REMOTE_JOB_DIR}/.tony-secret ] && "
                f"export TONY_SECRET=$(cat {REMOTE_JOB_DIR}/.tony-secret); "
                f"[ -f {REMOTE_JOB_DIR}/.tony-tls.crt ] && "
                f"export TONY_TLS_CERT={REMOTE_JOB_DIR}/.tony-tls.crt; ")
            # Strict cd: staging guarantees the job dir; a missing one is a
            # loud failure, not a task running in $HOME. The staged
            # framework copy leads PYTHONPATH so `python3 -m
            # tony_tpu.cluster.executor` resolves without any install.
            remote = (f"cd {REMOTE_JOB_DIR} && "
                      f"export PYTHONPATH={REMOTE_JOB_DIR}/{FRAMEWORK_DIR}"
                      f"${{PYTHONPATH:+:$PYTHONPATH}} && "
                      f"{secret_src}"
                      f"{env_prefix} {spec.command}")
            cmd = self.ssh_command(job_type, host_idx, remote, slice_idx)
            if self.dry_run:
                log.info("[dry-run] %s", " ".join(cmd))
                return
            t0 = time.monotonic()
            # Popen dups the log fd into the child, so the coordinator's
            # own handle closes right here — long sessions with many
            # restarts no longer accumulate open fds per launch.
            with open(os.path.join(
                    spec.log_dir,
                    f"{constants.task_log_stem(spec.task_id)}.stdout"),
                    "ab") as out:
                self._procs[spec.task_id] = subprocess.Popen(
                    cmd, stdout=out, stderr=subprocess.STDOUT)
        self._record_timing(self._gang_label(gang), "dispatch",
                            time.monotonic() - t0, task=spec.task_id)

    def _await_gang(self, gang: tuple[str, int], timeout_s: float) -> None:
        """Wait until the gang is provisioned+staged. The deadline covers
        the provisioner's WHOLE pipeline — delete (reprovision path) +
        create + staging commands, each individually bounded by timeout_s —
        not a single interval, so a slow-but-succeeding provision does not
        fail its co-gang tasks. Re-fetches the entry after every wake: a
        failed generation's event is set as it is retracted, and a retry
        may have re-claimed the gang with a fresh entry (and fresh event)
        that must be waited on instead."""
        # Worst case: delete (reprovision path) + (1 + create-retries)
        # creates + their backoff sleeps + (1 + stage-retries) passes over
        # the 7 staging commands (digest probe, scp tarball, unpack, scp
        # secret, chmod, scp TLS cert, digest stamp), each command bounded
        # by timeout_s; +1 command of scheduling slack so a co-gang waiter
        # never times out while the provisioner is still succeeding.
        create_r = self.conf.get_int(K.TPU_CREATE_RETRIES_KEY, 3)
        stage_r = self.conf.get_int(K.TPU_STAGE_RETRIES_KEY, 2)
        backoff = self.conf.get_int(K.TPU_RETRY_BACKOFF_KEY, 5000) / 1000
        backoff_total = sum(min(backoff * 2 ** i, 60.0)
                            for i in range(create_r))
        worst_cmds = 1 + (1 + create_r) + 7 * (1 + stage_r) + 1
        deadline = time.monotonic() + worst_cmds * timeout_s + backoff_total
        while True:
            with self._lock:
                current = self._gangs.get(gang)
                if current is None:
                    raise TpuProvisioningError(
                        f"gang {self._gang_label(gang)} failed to provision")
                if current["ready"].is_set():
                    return
                ready = current["ready"]
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ready.wait(timeout=remaining):
                raise TpuProvisioningError(
                    f"timed out waiting for gang {self._gang_label(gang)} "
                    f"to provision")

    def _provision(self, job_type: str, slice_idx: int, spec: LaunchSpec,
                   reprovision: bool = False) -> None:
        """Create + stage one gang (``reprovision``: synchronously delete
        the dead slice first — a create with the same name must not race
        the delete). Runs WITHOUT self._lock (launch_task claimed the gang
        first); touches no shared state beyond the timing log."""
        gang = self._gang_label((job_type, slice_idx))
        timeout_s = self.conf.get_int(K.TPU_PROVISION_TIMEOUT_KEY, 600000) / 1000
        backoff_s = self.conf.get_int(K.TPU_RETRY_BACKOFF_KEY, 5000) / 1000
        t0 = time.monotonic()
        if reprovision:
            # bounded by the SAME per-command timeout the _await_gang
            # deadline is derived from — a hardcoded bound here would let
            # the pipeline outrun the co-gang waiters' deadline
            cmd = self.delete_slice_command(job_type, wait=True,
                                            slice_idx=slice_idx)
            if self.dry_run:
                log.info("[dry-run] %s", " ".join(cmd))
            else:
                subprocess.run(cmd, capture_output=True, timeout=timeout_s)
        cmd = self.create_slice_command(job_type, spec.tpu_topology,
                                        slice_idx)
        if self.dry_run:
            log.info("[dry-run] %s", " ".join(cmd))
        else:
            # Quota-exhausted/transient create failures retry with
            # exponential backoff (capacity frees up as other jobs finish
            # — the fleet-level reality the reference delegated to YARN's
            # allocation loop). The budget bounds ONE provisioning
            # attempt; a lost slice afterwards is the preemption budget's
            # business.
            creates_left = self.conf.get_int(K.TPU_CREATE_RETRIES_KEY, 3)
            while True:
                log.info("provisioning slice for %s: %s", gang,
                         " ".join(cmd))
                try:
                    res = subprocess.run(cmd, capture_output=True,
                                         text=True, timeout=timeout_s)
                    stderr = res.stderr or ""
                    ok = res.returncode == 0
                    # Permanent errors (bad topology/type, auth) fail
                    # fast with the actionable message — only capacity/
                    # transient API failures are worth the backoff.
                    retryable = _retryable_create_error(stderr)
                except subprocess.TimeoutExpired:
                    ok, stderr, retryable = False, "create timed out", True
                if ok:
                    break
                if ("ALREADY_EXISTS" in stderr
                        or "already exists" in stderr) and not reprovision:
                    # Warm restart: the slice survives from a previous
                    # coordinator attempt. Adopt it — the staging step
                    # below probes the content stamp and re-ships only on
                    # mismatch, so the surviving gang comes up in ~0.
                    # NOT on the reprovision path: there ALREADY_EXISTS
                    # means the delete of the DEAD slice failed, and
                    # adopting it would stage onto a preempted VM — fail
                    # loudly instead (a later retry re-detects the dead
                    # state via the refreshed poller and re-deletes).
                    log.info("slice for %s already exists — adopting the "
                             "surviving slice", gang)
                    break
                if creates_left <= 0 or not retryable:
                    raise TpuProvisioningError(
                        f"slice provisioning failed for {gang}: {stderr}")
                creates_left -= 1
                log.warning(
                    "create failed for %s (%s) — retrying in %.1fs "
                    "(%d create retries left)", gang,
                    stderr.strip().splitlines()[-1:],
                    backoff_s, creates_left)
                time.sleep(backoff_s)
                backoff_s = min(backoff_s * 2, 60.0)
        self._record_timing(gang, "provision", time.monotonic() - t0,
                            reprovision=reprovision)
        # Staging re-runs from the top on a dropped connection: the
        # command sequence is idempotent (rm -rf + mkdir + untar; scp
        # overwrites), so a mid-sequence ssh/scp failure — or a HUNG one
        # (TimeoutExpired) — re-stages clean.
        stages_left = self.conf.get_int(K.TPU_STAGE_RETRIES_KEY, 2)
        while True:
            try:
                self._stage(job_type, slice_idx, spec, timeout_s)
                return
            except (TpuProvisioningError, subprocess.TimeoutExpired) as e:
                if stages_left <= 0:
                    if isinstance(e, subprocess.TimeoutExpired):
                        raise TpuProvisioningError(
                            f"staging timed out for {gang}: {e}") from e
                    raise
                stages_left -= 1
                log.warning("staging failed for %s (%s) — re-staging "
                            "(%d stage retries left)", gang, e, stages_left)

    # ------------------------------------------------------------------
    # Staging / localization
    # ------------------------------------------------------------------
    def _prepare_stage_artifacts(self, job_dir: str) -> None:
        """Make the job dir self-sufficient for a bare slice host: add a
        copy of the tony_tpu package under .tony-framework/ (executors run
        with PYTHONPATH pointing there — no pip install on hosts, like the
        reference shipping its own fat jar, ClusterSubmitter.java:37-61),
        and build the transport tarball. Logs and the per-job auth secret
        (env-delivered) are excluded."""
        with self._artifacts_lock:
            self._prepare_stage_artifacts_locked(job_dir)

    def _prepare_stage_artifacts_locked(self, job_dir: str) -> None:
        if self._artifacts_ready:
            return    # job-scoped, not gang-scoped: build/upload once
        import tony_tpu
        pkg_src = os.path.dirname(os.path.abspath(tony_tpu.__file__))
        fw_dst = os.path.join(job_dir, FRAMEWORK_DIR, "tony_tpu")
        # A half-written tree from an aborted earlier attempt must not be
        # shipped as-is: rebuild from scratch.
        if os.path.isdir(fw_dst):
            shutil.rmtree(fw_dst)
        shutil.copytree(
            pkg_src, fw_dst,
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc"))
        remote_staging = self.conf.get(K.REMOTE_JOB_DIR_KEY) or ""
        if remote_staging:
            # gs:// mode: the client already pushed the job dir; add the
            # framework so hosts pull ONE complete tree. The digest is
            # computed over the LOCAL spool (framework included) — the
            # same content the hosts rsync down.
            from tony_tpu.storage import sjoin, storage_for
            storage_for(remote_staging).put_tree(
                os.path.join(job_dir, FRAMEWORK_DIR),
                sjoin(remote_staging, FRAMEWORK_DIR))
            self._stage_digest = compute_stage_digest(job_dir)
            self._artifacts_ready = True    # only after the work succeeded
            return
        tarball = os.path.join(job_dir, ".tony-stage.tgz")
        with tarfile.open(tarball, "w:gz") as tf:
            for name in sorted(os.listdir(job_dir)):
                if name in STAGE_EXCLUDE:
                    continue
                tf.add(os.path.join(job_dir, name), arcname=name)
        self._stage_digest = compute_stage_digest(job_dir)
        self._artifacts_ready = True        # only after the work succeeded

    def _stage(self, job_type: str, slice_idx: int, spec: LaunchSpec,
               timeout_s: float) -> None:
        job_dir = spec.cwd
        if not job_dir:
            if not self.dry_run:
                raise TpuProvisioningError(
                    f"cannot stage {job_type}: launch spec has no job dir")
            job_dir = "<job-dir>"    # command-plan inspection only
        digest = None
        if not self.dry_run:
            self._prepare_stage_artifacts(job_dir)
            digest = self._stage_digest
        gang = self._gang_label((job_type, slice_idx))
        t0 = time.monotonic()
        if digest:
            # Check-then-ship: one ssh probe of the per-host content stamp.
            # A match means the staged tree is byte-identical (session
            # retry / warm restart onto a surviving slice) — skip the
            # whole scp+untar. Any probe failure (missing stamp, fresh
            # slice, hung ssh) falls through to the idempotent full stage.
            try:
                res = subprocess.run(
                    self.stage_probe_command(job_type, digest, slice_idx),
                    capture_output=True, timeout=timeout_s)
                if res.returncode == 0:
                    log.info("stage digest match for %s — skipping ship",
                             gang)
                    self._record_timing(gang, "stage",
                                        time.monotonic() - t0, cached=True)
                    return
            except subprocess.TimeoutExpired:
                pass
        for cmd in self.stage_commands(job_type, job_dir, slice_idx,
                                       digest=digest):
            if self.dry_run:
                log.info("[dry-run] %s", " ".join(cmd))
                continue
            log.info("staging %s: %s", job_type, " ".join(cmd))
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=timeout_s)
            if res.returncode != 0:
                raise TpuProvisioningError(
                    f"staging failed for {job_type}: {res.stderr}")
        self._record_timing(gang, "stage", time.monotonic() - t0,
                            cached=False)

    def _record_timing(self, gang: str, phase: str, seconds: float,
                       **extra) -> None:
        rec = {"gang": gang, "phase": phase,
               "seconds": round(seconds, 6), **extra}
        with self._timings_lock:
            self._timings.append(rec)

    def take_launch_timings(self) -> list[dict]:
        with self._timings_lock:
            recs, self._timings = self._timings, []
        return recs

    def _slice_state(self, gang: tuple[str, int]) -> str:
        if self.dry_run:
            return "READY"
        try:
            res = subprocess.run(
                self.describe_command(gang[0], gang[1]),
                capture_output=True, text=True, timeout=60)
        except subprocess.TimeoutExpired:
            return "UNKNOWN"
        if res.returncode != 0:
            return "UNKNOWN"
        return json.loads(res.stdout).get("state", "UNKNOWN")

    def _refresh_slice_states(self) -> None:
        now = time.monotonic()
        with self._lock:
            stale = [g for g in self._gangs
                     if now - self._state_ts.get(g, 0.0)
                     > self._state_refresh_s]
        if not stale:
            return
        # Describes run OUTSIDE the lock and concurrently: gangs are
        # independent VMs, and serial 60s-timeout calls would stall
        # completion/preemption reporting by minutes on a wide job.
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(8, len(stale))) as pool:
            states = list(pool.map(self._slice_state, stale))
        with self._lock:
            for g, state in zip(stale, states):
                self._state_cache[g] = state
                self._state_ts[g] = time.monotonic()

    def poll_completed(self) -> list[CompletionEvent]:
        self._refresh_slice_states()
        events = []
        with self._lock:
            preempted_gangs = {g for g in self._gangs
                               if self._state_cache.get(g, "READY")
                               in ("PREEMPTED", "TERMINATED")}
            for task_id, proc in self._procs.items():
                if task_id in self._reported:
                    continue
                jt, slice_idx, _ = self._gang_of(task_id)
                if (jt, slice_idx) in preempted_gangs:
                    # preemption kills one gang; the whole session retries
                    # (gang semantics), but only this gang reprovisions
                    self._reported.add(task_id)
                    events.append(CompletionEvent(task_id, -1, preempted=True))
                    continue
                code = proc.poll()
                if code is not None:
                    self._reported.add(task_id)
                    events.append(CompletionEvent(task_id, code))
        return events

    def remote_kill_command(self, job_type: str, host_index: int,
                            slice_idx: int = 0) -> list[str]:
        """Best-effort remote reap: terminating the local ``gcloud ssh``
        wrapper does NOT stop the executor on the TPU VM — it keeps
        heartbeating with a stale session id and holds the data ports, so a
        session retry onto the same slice would hit port conflicts."""
        return self.ssh_command(
            job_type, host_index,
            "pkill -9 -f tony_tpu.cluster.executor || true", slice_idx)

    def _kill_remote(self, task_id: str) -> subprocess.Popen | None:
        jt, slice_idx, host_idx = self._gang_of(task_id)
        cmd = self.remote_kill_command(jt, host_idx, slice_idx)
        if self.dry_run:
            log.info("[dry-run] %s", " ".join(cmd))
            return None
        return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def kill_task(self, task_id: str) -> None:
        with self._lock:
            proc = self._procs.get(task_id)
            if proc is not None and proc.poll() is None:
                proc.terminate()
        if proc is not None:
            # A dead local ssh wrapper does NOT imply a dead remote
            # executor, so the remote reap is unconditional (and
            # fire-and-forget: a single-task kill is not followed by a
            # relaunch of the same id, so there is no race to close).
            self._kill_remote(task_id)

    def kill_all(self) -> None:
        with self._lock:
            task_ids = list(self._procs)
            for proc in self._procs.values():
                if proc.poll() is None:
                    proc.terminate()
        # kill_all IS followed by a relaunch (session reset): the remote
        # pkills run in parallel but are awaited, otherwise a slow ssh could
        # land its SIGKILL on the NEXT session's executor.
        reapers = [p for p in (self._kill_remote(t) for t in task_ids)
                   if p is not None]
        deadline = time.monotonic() + 120
        for p in reapers:
            try:
                p.wait(timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()

    def release_gang(self, job_type: str,
                     slice_idx: int = 0) -> tuple[str, str]:
        """Release one gang's slice to the caller WITHOUT teardown.

        The slice stays alive — provisioned, staged, digest-stamped —
        and this backend forgets it, so ``stop()`` will not delete it.
        Returns ``(slice_name, staging_digest)``: the cluster daemon
        pools the name under the digest, and the next digest-matching
        job re-adopts it through the create path's ALREADY_EXISTS
        branch (plus the remote digest probe) at warm-adopt cost.
        """
        gang = (job_type, slice_idx)
        with self._lock:
            entry = self._gangs.pop(gang, None)
            self._state_cache.pop(gang, None)
            self._state_ts.pop(gang, None)
            name = entry["name"] if entry is not None \
                else self._slice_name(job_type, slice_idx)
            digest = self._stage_digest or ""
        return name, digest

    def release_all(self) -> list[tuple[str, str]]:
        with self._lock:
            gangs = list(self._gangs)
        return [self.release_gang(jt, slice_idx)
                for jt, slice_idx in gangs]

    def stop(self) -> None:
        self.kill_all()
        with self._lock:
            for jt, slice_idx in list(self._gangs):
                cmd = self.delete_slice_command(jt, slice_idx=slice_idx)
                if self.dry_run:
                    log.info("[dry-run] %s", " ".join(cmd))
                    continue
                subprocess.run(cmd, capture_output=True, timeout=120)
            self._gangs.clear()
