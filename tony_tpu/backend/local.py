"""Local subprocess backend — the in-process fake cluster.

Analog of the reference's tony-mini MiniCluster (reference: tony-mini/src/
main/java/com/linkedin/minitony/cluster/MiniCluster.java:44-60, a
MiniYARNCluster + MiniDFSCluster used by the whole E2E suite). Here the
"containers" are plain subprocesses on this host with stdout/stderr redirected
to per-task log files (the YARN container-log-dir analog, reference:
TonyApplicationMaster.java:1119-1127). This backend is how the entire
distributed control plane — gang barrier, heartbeats, chief short-circuit,
session retries, chaos hooks — is exercised on a dev box or CI without TPUs.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
import time

from tony_tpu import constants
from tony_tpu.backend.base import CompletionEvent, LaunchSpec, SchedulerBackend
from tony_tpu.utils.env import with_framework_path

log = logging.getLogger(__name__)


class LocalBackend(SchedulerBackend):
    KILL_GRACE_S = 2.0
    #: how long an adopted pid must stay observably dead before its
    #: completion event is emitted — gives the executor's own
    #: register_execution_result RPC (which always lands before the
    #: process is reaped) time to report the REAL exit code, so this
    #: backend-side observation is the deduped fallback, not the source
    #: of truth
    ADOPTED_REAP_HOLD_S = 1.2

    def __init__(self) -> None:
        self._procs: dict[str, subprocess.Popen] = {}
        self._files: dict[str, list] = {}
        self._reported: set[str] = set()
        self._killed: set[str] = set()
        self._preempted: set[str] = set()
        self._preemption_simulated = False
        #: TEST_PREEMPT_TASKS clauses already fired (one-shot each)
        self._preempt_clauses_fired: set[str] = set()
        #: tasks re-adopted by a restarted coordinator: task_id -> pid of a
        #: process LAUNCHED BY THE PREDECESSOR (journal-recovered; not our
        #: child, so no Popen handle — liveness is os.kill(pid, 0))
        self._adopted: dict[str, int] = {}
        #: adopted pids first observed dead: task_id -> monotonic time
        self._adopted_dead_at: dict[str, float] = {}
        self._lock = threading.Lock()
        #: drained by the coordinator via take_launch_timings(); local
        #: launches have no provision/stage phase, only process dispatch
        self._timings: list[dict] = []

    def take_launch_timings(self) -> list[dict]:
        with self._lock:
            recs, self._timings = self._timings, []
        return recs

    def launch_task(self, spec: LaunchSpec) -> None:
        t_start = time.monotonic()
        os.makedirs(spec.log_dir, exist_ok=True)
        # Relaunch of the same task id (session retry racing a slow death):
        # reap the previous generation first so its exit event and fds are
        # not leaked by the dict overwrite below.
        with self._lock:
            old = self._procs.get(spec.task_id)
            if old is not None and old.poll() is None:
                self._kill_proc(spec.task_id, old)
                try:
                    old.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    log.warning("previous %s did not die in 5s", spec.task_id)
            for f in self._files.pop(spec.task_id, ()):
                f.close()
        safe = constants.task_log_stem(spec.task_id)
        out = open(os.path.join(spec.log_dir, f"{safe}.stdout"), "ab")
        err = open(os.path.join(spec.log_dir, f"{safe}.stderr"), "ab")
        env = with_framework_path(dict(os.environ))
        env.update(spec.env)
        proc = subprocess.Popen(
            ["bash", "-c", spec.command], env=env, stdout=out, stderr=err,
            cwd=spec.cwd or None,
            start_new_session=True)  # own process group → clean group kill
        with self._lock:
            self._procs[spec.task_id] = proc
            self._files[spec.task_id] = [out, err]
            self._reported.discard(spec.task_id)
            self._killed.discard(spec.task_id)
            self._preempted.discard(spec.task_id)
            self._timings.append({
                "gang": spec.task_id.partition(":")[0], "phase": "dispatch",
                "seconds": round(time.monotonic() - t_start, 6),
                "task": spec.task_id})
        log.info("launched %s as pid %d", spec.task_id, proc.pid)

    # -- crash-recovery adoption --------------------------------------------
    def adopt(self, task_id: str, pid: int) -> None:
        """Re-adopt a live task process launched by a PREDECESSOR coordinator
        (pid recovered from the session journal). The process is not our
        child, so there is no Popen handle: liveness is probed with
        ``os.kill(pid, 0)`` and kills go through ``os.killpg`` (launch_task
        uses start_new_session, so pid == pgid)."""
        with self._lock:
            try:
                os.kill(pid, 0)
            except (ProcessLookupError, PermissionError):
                # Died during the coordinator outage — surface immediately
                # as an ordinary failure (no reap hold: there is no live
                # executor left to race a result RPC against).
                log.warning("adopt: %s pid %d already dead", task_id, pid)
                self._adopted[task_id] = pid
                self._adopted_dead_at[task_id] = -1.0
                return
            self._adopted[task_id] = pid
            self._reported.discard(task_id)
        log.info("adopted %s as pre-existing pid %d", task_id, pid)

    def pid_of(self, task_id: str) -> int | None:
        """Pid of the task's process, for journaling (None = unknown)."""
        with self._lock:
            proc = self._procs.get(task_id)
            if proc is not None:
                return proc.pid
            return self._adopted.get(task_id)

    def _maybe_simulate_preemption(self) -> None:
        """TEST_PREEMPT_SLICE=<job_type> chaos: SIGKILL every running task of
        that job type ONCE and report it preempted — simulates losing a TPU
        slice wholesale, driving the coordinator's preemption-retry path
        (the infra-failure analog of the reference's TEST_* hooks)."""
        job_type = os.environ.get(constants.TEST_PREEMPT_SLICE)
        if not job_type or self._preemption_simulated:
            return
        victims = [(tid, p) for tid, p in self._procs.items()
                   if tid.partition(":")[0] == job_type
                   and tid not in self._reported and p.poll() is None]
        if not victims:
            return
        self._preemption_simulated = True
        for task_id, proc in victims:
            log.info("chaos: simulating slice preemption of %s", task_id)
            self._preempted.add(task_id)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def _maybe_kill_gang_at_marker(self) -> None:
        """TEST_PREEMPT_TASKS chaos: ';'-separated ONE-SHOT clauses of
        "task_id[,task_id...][@marker_path]" — SIGKILL the listed tasks
        and report them preempted, immediately or once the marker file
        exists. Trainers touch the marker from a step hook, so "lose gang
        G at step K" is exactly reproducible without real TPUs (the
        elastic suite's kill-gang-at-step hook; fake_gcloud's
        FAKE_PREEMPT_<GANG> is the TPU-backend twin)."""
        spec = os.environ.get(constants.TEST_PREEMPT_TASKS)
        if not spec:
            return
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause or clause in self._preempt_clauses_fired:
                continue
            tasks, _, marker = clause.partition("@")
            if marker and not os.path.exists(marker):
                continue
            task_ids = [t.strip() for t in tasks.split(",") if t.strip()]
            # A clause must not burn before its tasks have even launched
            # (launches fan out concurrently with this poll): stay armed
            # until EVERY listed task is known to the backend — a partial
            # kill would turn an intended whole-gang preemption into a
            # different scenario. A clause naming a never-launched task
            # simply stays armed (and inert) for the backend's life.
            # Adopted tasks count as launched: a restarted coordinator's
            # re-adopted gang must stay preemptable, or chaos schedules
            # spanning a coordinator kill could never fire their later
            # clauses.
            if not all(tid in self._procs or tid in self._adopted
                       for tid in task_ids):
                continue
            self._preempt_clauses_fired.add(clause)
            for task_id in task_ids:
                if task_id in self._reported:
                    continue
                proc = self._procs.get(task_id)
                pid = proc.pid if proc is not None \
                    else self._adopted.get(task_id)
                if pid is None or (proc is not None
                                   and proc.poll() is not None):
                    continue
                log.info("chaos: TEST_PREEMPT_TASKS killing %s (marker %s)",
                         task_id, marker or "<immediate>")
                self._preempted.add(task_id)
                try:
                    os.killpg(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    def _maybe_kill_coordinator(self) -> None:
        """TEST_KILL_COORDINATOR chaos: the value is a marker-file path;
        once the marker exists, SIGKILL the COORDINATOR process — this
        backend runs inside it — exactly once. The one-shot latch is a
        sentinel FILE ("<marker>.fired", written before the kill): any
        in-memory fired flag would die with the process and re-fire on
        every restart. Trainers touch the marker from a step hook, so
        "kill the coordinator at step K" is exactly reproducible; tasks
        survive the kill (they run in their own sessions) for the
        restarted coordinator to re-adopt."""
        marker = os.environ.get(constants.TEST_KILL_COORDINATOR)
        if not marker or not os.path.exists(marker):
            return
        sentinel = marker + ".fired"
        if os.path.exists(sentinel):
            return
        log.warning("chaos: TEST_KILL_COORDINATOR marker %s present — "
                    "SIGKILLing coordinator pid %d", marker, os.getpid())
        with open(sentinel, "w") as f:
            f.write(str(os.getpid()))
            f.flush()
            os.fsync(f.fileno())
        os.kill(os.getpid(), signal.SIGKILL)

    def poll_completed(self) -> list[CompletionEvent]:
        events = []
        with self._lock:
            self._maybe_kill_coordinator()
            self._maybe_simulate_preemption()
            self._maybe_kill_gang_at_marker()
            now = time.monotonic()
            for task_id, pid in self._adopted.items():
                if task_id in self._reported or task_id in self._procs:
                    continue
                try:
                    os.kill(pid, 0)
                    self._adopted_dead_at.pop(task_id, None)
                    continue
                except (ProcessLookupError, PermissionError):
                    pass
                first_dead = self._adopted_dead_at.setdefault(task_id, now)
                # Hold the dead observation briefly (unless it was dead at
                # adoption, first_dead < 0): the executor's
                # register_execution_result RPC carries the real exit code
                # and beats this fallback, which record_completion dedupes.
                if first_dead >= 0 and now - first_dead < self.ADOPTED_REAP_HOLD_S:
                    continue
                self._reported.add(task_id)
                events.append(CompletionEvent(
                    task_id, 1, preempted=task_id in self._preempted))
            for task_id, proc in self._procs.items():
                if task_id in self._reported:
                    continue
                code = proc.poll()
                if code is None:
                    continue
                self._reported.add(task_id)
                for f in self._files.pop(task_id, ()):
                    f.close()
                # Only simulated slice loss is "preempted" (infra failure,
                # retryable from the preemption budget). Deliberate
                # coordinator kills (session reset, chaos worker
                # termination) must look like ordinary task death, as in
                # the reference where a killed container is just a failed
                # container.
                events.append(CompletionEvent(
                    task_id, code, preempted=task_id in self._preempted))
        return events

    def _kill_proc(self, task_id: str, proc: subprocess.Popen) -> None:
        """TERM first — the executor forwards it to the user process group
        (which lives in its own session, out of killpg's reach) — then
        escalate to group SIGKILL after a grace period; PDEATHSIG on the
        user process backstops the SIGKILL path."""
        if proc.poll() is not None:
            return
        self._killed.add(task_id)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return

        def _escalate():
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        t = threading.Timer(self.KILL_GRACE_S, _escalate)
        t.daemon = True
        t.start()

    def _kill_adopted(self, task_id: str) -> None:
        """Kill an adopted (non-child) task: TERM its process group, then
        escalate after the usual grace (launch_task's start_new_session
        guarantees pid == pgid for adopted pids too)."""
        pid = self._adopted.get(task_id)
        if pid is None or task_id in self._reported:
            return
        self._killed.add(task_id)
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return

        def _escalate():
            try:
                os.kill(pid, 0)
                os.killpg(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

        t = threading.Timer(self.KILL_GRACE_S, _escalate)
        t.daemon = True
        t.start()

    def kill_task(self, task_id: str) -> None:
        with self._lock:
            proc = self._procs.get(task_id)
            if proc:
                self._kill_proc(task_id, proc)
            elif task_id in self._adopted:
                self._kill_adopted(task_id)

    def kill_all(self) -> None:
        with self._lock:
            for task_id, proc in self._procs.items():
                self._kill_proc(task_id, proc)
            for task_id in self._adopted:
                if task_id not in self._procs:
                    self._kill_adopted(task_id)

    def stop(self) -> None:
        self.kill_all()
        with self._lock:
            for proc in self._procs.values():
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            for files in self._files.values():
                for f in files:
                    f.close()
            self._files.clear()
