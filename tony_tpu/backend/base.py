"""Scheduler-backend abstraction: where task processes actually run.

The reference hardwires YARN (AMRMClientAsync/NMClientAsync inside
TonyApplicationMaster.java:990-1151); the TPU build makes the substrate
pluggable, because TPU pod slices are gang-allocated (one allocation = every
host of a slice) while the local test backend allocates per-process. Backends
implement launch/poll/kill; the coordinator owns all policy (matching, retry,
liveness)."""

from __future__ import annotations

import abc
from dataclasses import dataclass


@dataclass
class LaunchSpec:
    """Everything needed to start one task process."""
    task_id: str            # "jobtype:index"
    command: str            # executor launch command (shell)
    env: dict[str, str]     # additional environment
    log_dir: str            # where stdout/stderr land
    cwd: str = ""           # working dir for the task process (job dir)
    memory_mb: int = 2048
    vcores: int = 1
    gpus: int = 0
    tpus: int = 0
    tpu_topology: str = ""


@dataclass
class CompletionEvent:
    task_id: str
    exit_code: int
    preempted: bool = False  # TPU slices can be preempted wholesale; the
                             # monitor treats preemption as retryable


class SchedulerBackend(abc.ABC):
    """Minimal container-management surface the coordinator needs."""

    @abc.abstractmethod
    def launch_task(self, spec: LaunchSpec) -> None: ...

    @abc.abstractmethod
    def poll_completed(self) -> list[CompletionEvent]:
        """Non-blocking: completion events observed since the last poll.
        Process/container exit is the authoritative task result, exactly as
        YARN container completion is in the reference (RMCallbackHandler.
        onContainersCompleted:992)."""

    def take_launch_timings(self) -> list[dict]:
        """Drain per-gang bring-up wall timings recorded since the last
        call: ``{"gang", "phase" (provision|stage|dispatch), "seconds",
        "task"?, "cached"?}`` dicts. The coordinator polls this from the
        monitor loop, folds the walls into ``tony_startup_*_seconds``
        gauges, and emits them as jhist LAUNCH events — so where bring-up
        time went is visible live and in replay. Backends without
        startup phases may return []."""
        return []

    def release_all(self) -> list[tuple[str, str]]:
        """Release every allocation to the CALLER without teardown:
        returns ``(slice_name, staging_digest)`` pairs and forgets them.

        This is the cluster daemon's release-to-pool path (docs/
        cluster.md): a finished job's slices stay alive — warm, staged,
        digest-tagged — so the next digest-matching job adopts them via
        ALREADY_EXISTS in ~0.5s instead of paying full bring-up.
        ``stop()`` remains the teardown path (the pool reaps idle
        slices through it).  Backends without durable allocations
        (LocalBackend) return []."""
        return []

    @abc.abstractmethod
    def kill_task(self, task_id: str) -> None: ...

    @abc.abstractmethod
    def kill_all(self) -> None:
        """Stop every running task (session reset / shutdown)."""

    @abc.abstractmethod
    def stop(self) -> None: ...
