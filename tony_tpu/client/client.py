"""Job-submission client.

TPU-native rebuild of the reference's ``TonyClient`` (reference: tony-core/
src/main/java/com/linkedin/tony/TonyClient.java:139-720). Same flow:

1. build the final layered config and freeze it as ``tony-final.xml``
   (``initTonyConf:364``, written :186-192)
2. stage the user's source tree (and optional venv) into a per-application
   job directory — the ``.tony/<appId>`` HDFS staging dir analog (:163-185)
3. launch the coordinator (the AM-launch ``createAMContainerSpec:386`` +
   YARN ``submitApplication``; here a subprocess or a TPU VM)
4. poll status + print task log URLs (``monitorApplication:572``), with a
   client-side timeout kill (:606-613)
5. signal ``finishApplication`` so the coordinator can exit (:710), and
   relaunch the coordinator on crash — the YARN max-app-attempts analog
"""

from __future__ import annotations

import logging
import os
import secrets
import shutil
import subprocess
import sys
import time
import uuid

from tony_tpu import constants
from tony_tpu.cluster.coordinator import COORDINATOR_ADDR_FILE, FINAL_STATUS_FILE
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig
import json

from tony_tpu import storage
from tony_tpu.rpc.client import ApplicationRpcClient, RpcRetryError
from tony_tpu.utils.env import with_framework_path
from tony_tpu.utils.version import inject_version_info

log = logging.getLogger("tony_tpu.client")


def _mint_gcs_credential(spec: str) -> str:
    """Mint the job's GCS credential from ``tony.gcs.service-account``.

    Two forms (the ``tony.other.namenodes`` analog — the reference carries
    a LIST of filesystems, each with its own delegation token,
    TonyConfigurationKeys.java:29, fetched per-namenode in
    TonyClient.java:509-540):

    * a single service account — one identity for every bucket the job
      touches (the common case; returns its bare access token), or
    * comma-separated ``bucket=sa`` pairs (``*`` = default identity) —
      one token is minted per DISTINCT account and the result is an
      opaque JSON blob ``{bucket: token}``. The blob rides the exact
      same plumbing as a bare token (env var → RPC renew push →
      heartbeat fan-out → executor token file); only GcsStorage
      interprets it, selecting by each call's target bucket.
    """
    if "=" not in spec:
        return _mint_gcs_token(spec)
    per_sa: dict[str, str] = {}
    cred: dict[str, str] = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        bucket, _, sa = pair.partition("=")
        bucket = bucket.strip().removeprefix("gs://").strip("/")
        sa = sa.strip()
        if not bucket or not sa:
            raise ValueError(
                f"bad tony.gcs.service-account entry {pair!r}; expected "
                f"'bucket=service-account' (or a single service account)")
        if sa not in per_sa:
            per_sa[sa] = _mint_gcs_token(sa)
        cred[bucket] = per_sa[sa]
    return json.dumps(cred)


def _mint_gcs_token(service_account: str) -> str:
    """Short-lived access token via gcloud impersonation — the client's
    delegation-token fetch (reference TonyClient.java:509). Requires the
    submitter to hold roles/iam.serviceAccountTokenCreator on the target
    account; failure is a submit-time error, not a mid-job surprise.
    ``$TONY_GCLOUD`` overrides the binary (tests substitute a fake)."""
    gcloud = os.environ.get("TONY_GCLOUD", "gcloud")
    try:
        proc = subprocess.run(
            [gcloud, "auth", "print-access-token",
             f"--impersonate-service-account={service_account}"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(
            f"cannot mint GCS token for {service_account}: {e}") from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"gcloud token mint for {service_account} failed "
            f"rc={proc.returncode}: "
            f"{proc.stderr.decode('utf-8', 'replace').strip()}")
    token = proc.stdout.decode("utf-8").strip()
    if not token:
        raise RuntimeError(
            f"gcloud returned an empty token for {service_account}")
    return token


def new_app_id() -> str:
    """application_<ts>_<rand> — shaped like a YARN application id."""
    return f"application_{int(time.time() * 1000)}_{uuid.uuid4().hex[:6]}"


class TonyClient:
    POLL_PERIOD_S = 0.3

    def __init__(self, conf: TonyConfig, task_command: str,
                 src_dir: str | None = None,
                 shell_env: dict[str, str] | None = None,
                 on_tracking_url=None) -> None:
        #: optional callable(url) fired once when the job's tracking URL
        #: (TensorBoard / notebook endpoint) becomes known — the notebook
        #: submitter uses it to start a local proxy (reference:
        #: NotebookSubmitter.java:93-106).
        self.on_tracking_url = on_tracking_url
        self._tracking_url_fired = False
        self.conf = conf
        # Record which build submitted this job (reference: TonyClient ctor
        # TonyClient.java:132) — lands in tony-final.xml for the history UI.
        inject_version_info(conf)
        self.task_command = task_command
        self.src_dir = src_dir
        self.shell_env = shell_env or {}
        self.app_id = new_app_id()
        staging_root = (conf.get(K.STAGING_DIR_KEY) or
                        os.path.join(os.getcwd(), constants.TONY_JOB_DIR_PREFIX))
        # A remote staging root (gs://...) is for fleets whose hosts share
        # no filesystem with the submit host (the reference's HDFS
        # .tony/<appId> staging, TonyClient.java:163-185): the job dir is
        # assembled in a local spool, then pushed wholesale; slice hosts
        # pull it down (the container-localization analog).
        self.remote_job_dir: str | None = None
        if storage.is_remote(staging_root):
            self.remote_job_dir = storage.sjoin(staging_root, self.app_id)
            # mkdtemp: private (0700) and collision-free on multi-user
            # hosts. Holds the coordinator/task logs, so it is left on
            # disk after the run.
            import tempfile
            self.job_dir = tempfile.mkdtemp(prefix=f"tony-{self.app_id}-")
        else:
            self.job_dir = os.path.join(staging_root, self.app_id)
        self.timeout_s = conf.get_int(K.APPLICATION_TIMEOUT_KEY, 0) / 1000.0
        self.am_proc: subprocess.Popen | None = None
        self.rpc: ApplicationRpcClient | None = None
        self._printed_urls = False
        # Control-plane auth (ClientToAMToken analog): generate a per-job
        # secret when tony.application.security.enabled is set. It rides to
        # the coordinator in its launch env, to executors in theirs, and is
        # persisted (0600) in the job dir for out-of-band tooling.
        self.secret: str | None = None
        if conf.get_bool(K.APPLICATION_SECURITY_KEY, False):
            self.secret = secrets.token_hex(16)
        # Per-job GCS identity (tony.gcs.service-account — the delegation-
        # token analog, reference TonyClient.java:509 getTokens): mint a
        # short-lived access token for the scoped service account NOW, so
        # the client's own staging push and every downstream process run
        # under the job identity, never ambient host credentials. Rides
        # env only (like the secret), persisted 0600 for tooling.
        self.gcs_token: str | None = None
        self.gcs_token_minted_at: float = 0.0
        gcs_sa = conf.get(K.GCS_SERVICE_ACCOUNT_KEY)
        if gcs_sa:
            self.gcs_token = _mint_gcs_credential(gcs_sa)
            self.gcs_token_minted_at = time.monotonic()
            storage.register_storage(
                "gs", storage.GcsStorage(token=self.gcs_token))
        # Per-job TLS (rpc/tls.py): cert generated in stage(), paths set
        # once the files exist.
        self.tls_enabled = conf.get_bool(K.TLS_ENABLED_KEY, False)
        self.tls_key_path: str | None = None
        self.tls_cert_path: str | None = None

    # ------------------------------------------------------------------
    def stage(self) -> None:
        """Create the job dir and localize sources (reference :163-192)."""
        # Fail fast in THIS process on malformed resource asks (e.g.
        # instances vs slice-topology host count) — the actionable message
        # must reach the submitting user, not die in coordinator stderr
        # (the reference's early ask-truncation, TonyClient.java:145-157).
        self.conf.task_requests()
        os.makedirs(self.job_dir, exist_ok=True)
        os.makedirs(os.path.join(self.job_dir, constants.TONY_LOG_DIR),
                    exist_ok=True)
        if self.src_dir:
            dst = os.path.join(self.job_dir,
                               os.path.basename(os.path.normpath(self.src_dir)))
            # The staging root usually lives under cwd (./.tony); when
            # src_dir contains it (tony submit --src_dir .), copytree must
            # not descend into the tree it is growing (infinite recursion
            # until ENAMETOOLONG — the reference avoided this by staging to
            # HDFS, a different filesystem).
            skip = {os.path.realpath(os.path.dirname(self.job_dir)),
                    os.path.realpath(self.job_dir)}

            def _skip_staging(dirpath, names):
                return {n for n in names if os.path.realpath(
                    os.path.join(dirpath, n)) in skip}

            shutil.copytree(self.src_dir, dst, dirs_exist_ok=True,
                            ignore=_skip_staging)
        venv = self.conf.get(K.PYTHON_VENV_KEY)
        if venv and os.path.exists(venv):
            shutil.copy(venv, os.path.join(self.job_dir, constants.TONY_VENV_ZIP))
        # Freeze the history dirs as ABSOLUTE paths anchored at the submit
        # cwd: the coordinator runs with cwd=job_dir, and a relative path
        # frozen as-is would resolve somewhere a stock history server (run
        # from the submit dir) never looks.
        from tony_tpu.events import events as ev
        dirs = ev.HistoryDirs.from_conf(self.conf).absolutized()
        self.conf.set(K.HISTORY_LOCATION_KEY, dirs.location)
        self.conf.set(K.HISTORY_INTERMEDIATE_KEY, dirs.intermediate)
        self.conf.set(K.HISTORY_FINISHED_KEY, dirs.finished)
        if self.remote_job_dir:
            # Frozen into tony-final.xml so every slice host knows where to
            # pull the job dir from (the localization contract, reference:
            # TonyApplicationMaster.java:1090-1104).
            self.conf.set(K.REMOTE_JOB_DIR_KEY, self.remote_job_dir)
        self.conf.write_xml(os.path.join(self.job_dir, constants.TONY_FINAL_XML))
        if self.remote_job_dir:
            # Push the assembled job dir in one shot (the HDFS staging
            # upload, TonyClient.java:163-185). The per-job secret is
            # written only AFTER the push: it rides to processes via env,
            # and must never land in a (possibly team-readable) bucket.
            storage.storage_for(self.remote_job_dir).put_tree(
                self.job_dir, self.remote_job_dir)
        if self.secret:
            secret_path = os.path.join(self.job_dir, constants.TONY_SECRET_FILE)
            fd = os.open(secret_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, "w") as f:
                f.write(self.secret)
        if self.gcs_token:
            # like the secret: written AFTER the remote push so the job
            # credential never lands in the bucket it scopes
            tok_path = os.path.join(self.job_dir, ".gcs-token")
            fd = os.open(tok_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, "w") as f:
                f.write(self.gcs_token)
        if self.tls_enabled:
            # Generated AFTER any remote push, like the secret: the key
            # must never land in a (possibly team-readable) bucket — it
            # travels only over scp (backend staging) with mode 0600.
            from tony_tpu.rpc import tls as _tls
            self.tls_key_path, self.tls_cert_path = _tls.generate_self_signed(
                self.job_dir)

    def launch_coordinator(self, attempt: int) -> None:
        """Start the coordinator process (the AM launch, reference
        buildCommand:430)."""
        cmd = [sys.executable, "-m", "tony_tpu.cluster.coordinator",
               "--conf_file", os.path.join(self.job_dir, constants.TONY_FINAL_XML),
               "--app_id", self.app_id,
               "--job_dir", self.job_dir,
               "--task_command", self.task_command]
        env = with_framework_path(dict(os.environ))
        env.update(self.shell_env)
        env[constants.ATTEMPT_NUMBER] = str(attempt)
        if self.secret:
            env[constants.TONY_SECRET] = self.secret
        if self.gcs_token:
            env[constants.TONY_GCS_TOKEN] = self.gcs_token
        if self.tls_cert_path:
            env[constants.TONY_TLS_CERT] = self.tls_cert_path
            env[constants.TONY_TLS_KEY] = self.tls_key_path
        logs = os.path.join(self.job_dir, constants.TONY_LOG_DIR)
        out = open(os.path.join(logs, "am.stdout"), "ab")
        err = open(os.path.join(logs, "am.stderr"), "ab")
        self.am_proc = subprocess.Popen(cmd, env=env, cwd=self.job_dir,
                                        stdout=out, stderr=err)
        log.info("launched coordinator attempt %d as pid %d", attempt,
                 self.am_proc.pid)

    def _read_coordinator_addr(self) -> str | None:
        """Non-blocking read of the coordinator's published RPC address."""
        path = os.path.join(self.job_dir, COORDINATOR_ADDR_FILE)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read().strip() or None

    def _wait_for_coordinator_addr(self, timeout_s: float = 30.0) -> str | None:
        deadline = time.monotonic() + timeout_s
        while True:
            addr = self._read_coordinator_addr()
            if addr:
                return addr
            if self.am_proc and self.am_proc.poll() is not None:
                return None
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.1)

    def _read_final_status(self) -> dict | None:
        path = os.path.join(self.job_dir, FINAL_STATUS_FILE)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def _print_task_urls(self) -> None:
        if (self._printed_urls and self._tracking_url_fired) or not self.rpc:
            return
        if self._printed_urls and self.on_tracking_url is None:
            return
        try:
            urls = self.rpc.get_task_urls()
        except Exception:
            return
        if urls and not self._printed_urls:
            self._printed_urls = True
            for u in urls:
                log.info("task %s:%s logs: %s", u.name, u.index, u.url)
        if self.on_tracking_url is not None and not self._tracking_url_fired:
            for u in urls:
                if u.name == constants.TRACKING_URL_TASK_NAME:
                    self._tracking_url_fired = True
                    try:
                        self.on_tracking_url(u.url)
                    except Exception:
                        log.warning("on_tracking_url callback failed",
                                    exc_info=True)
                    break

    def _connect(self, addr: str) -> ApplicationRpcClient:
        """Coordinator channel with this job's auth secret and TLS cert
        (one definition for the three connect sites)."""
        return ApplicationRpcClient(addr, secret=self.secret,
                                    tls_cert=self.tls_cert_path)

    # ------------------------------------------------------------------
    def monitor(self) -> int:
        """Poll until the job finishes (reference: monitorApplication:572).
        Returns the process-style exit code (0 success)."""
        started = time.monotonic()
        renew_s = self.conf.get_int(K.GCS_TOKEN_RENEW_MS_KEY,
                                    2_700_000) / 1000.0
        # anchor the cadence to MINT time, not monitor() start: tokens
        # expire ~1h after minting, and staging/launch before monitor()
        # (plus any stretch where rpc is not yet connected) counts
        # against that budget — the `now >= next_renew` check below then
        # renews immediately once the rpc comes up late
        next_renew = (self.gcs_token_minted_at or started) + renew_s
        while True:
            time.sleep(self.POLL_PERIOD_S)
            if (self.gcs_token and self.rpc is not None
                    and time.monotonic() >= next_renew):
                # a failed mint/push retries in a minute, not a full
                # period — the next full period would land past the
                # current token's ~1h expiry
                ok = self._renew_gcs_token()
                next_renew = time.monotonic() + (renew_s if ok else 60.0)
            final = self._read_final_status()
            if final is not None:
                status = final["status"]
                log.info("application %s finished: %s %s", self.app_id, status,
                         final.get("message", ""))
                self._signal_finish()
                return 0 if status == "SUCCEEDED" else 1
            if self.timeout_s > 0 and time.monotonic() - started > self.timeout_s:
                log.error("client-side timeout after %.0fs — killing job",
                          self.timeout_s)
                self.kill()
                return 1
            if self.am_proc and self.am_proc.poll() is not None:
                # Coordinator died without a final status — crash.
                return self._handle_am_crash()
            if self.rpc is None:
                addr = self._read_coordinator_addr()
                if addr:
                    self.rpc = self._connect(addr)
            self._print_task_urls()

    def _renew_gcs_token(self) -> bool:
        """Re-mint the scoped token and push it to the coordinator (the
        delegation-token renewal the reference delegates to the RM): the
        heartbeat channel fans it out to executors, which republish to
        the token file user processes re-read per storage call. Renewal
        failure is non-fatal here — the current token stays valid until
        its own expiry, and the caller retries on a short fuse."""
        sa = self.conf.get(K.GCS_SERVICE_ACCOUNT_KEY)
        try:
            # multi-identity specs re-mint EVERY identity on the same
            # cadence (one blob, one push)
            token = _mint_gcs_credential(sa)
            self.rpc.renew_gcs_token(token)
        except Exception:
            log.warning("GCS token renewal failed (will retry shortly)",
                        exc_info=True)
            return False
        self.gcs_token = token
        self.gcs_token_minted_at = time.monotonic()
        os.environ[constants.TONY_GCS_TOKEN] = token
        storage.register_storage(
            "gs", storage.GcsStorage(token=token))
        tok_path = os.path.join(self.job_dir, ".gcs-token")
        fd = os.open(tok_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(token)
        log.info("per-job GCS token renewed and pushed to coordinator")
        return True

    def _handle_am_crash(self) -> int:
        """Coordinator crash → relaunch with attempt+1 if retries remain (the
        YARN max-app-attempts analog driving the TEST_AM_CRASH E2E)."""
        retries = self.conf.get_int(K.AM_RETRY_COUNT_KEY, 0)
        self._attempt = getattr(self, "_attempt", 0) + 1
        if self._attempt > retries:
            log.error("coordinator exited with %s and no final status — FAILED",
                      self.am_proc.returncode)
            return 1
        log.warning("coordinator crashed (attempt %d/%d) — relaunching",
                    self._attempt, retries)
        for stale in (COORDINATOR_ADDR_FILE,):
            p = os.path.join(self.job_dir, stale)
            if os.path.exists(p):
                os.remove(p)
        self.rpc = None
        self._printed_urls = False
        # The relaunched executors register a fresh tracking URL (new
        # notebook port) — let the callback re-point the proxy.
        self._tracking_url_fired = False
        self.launch_coordinator(self._attempt)
        return self.monitor()

    def _signal_finish(self) -> None:
        """Let the coordinator exit (reference: TonyClient.main:710 finally
        calls amRpcClient.finishApplication())."""
        if self.rpc is None:
            addr = self._wait_for_coordinator_addr(timeout_s=1)
            if addr:
                self.rpc = self._connect(addr)
        if self.rpc:
            try:
                # Best-effort: the coordinator may already be gone (e.g.
                # after an out-of-band `tony kill` it exits on its own) —
                # a long UNAVAILABLE retry loop here would stall the client
                # for minutes after the job is already final.
                self.rpc.finish_application(retries=2)
            except Exception:
                pass
        if self.am_proc:
            try:
                self.am_proc.wait(timeout=40)
            except subprocess.TimeoutExpired:
                self.am_proc.kill()

    def kill(self) -> None:
        if self.am_proc and self.am_proc.poll() is None:
            self.am_proc.terminate()
            try:
                self.am_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.am_proc.kill()

    # ------------------------------------------------------------------
    def run(self) -> int:
        """Submit and babysit to completion (reference: run:139)."""
        self.stage()
        self._attempt = 0
        self.launch_coordinator(0)
        addr = self._wait_for_coordinator_addr()
        if addr:
            self.rpc = self._connect(addr)
            log.info("coordinator up at %s; job dir %s", addr, self.job_dir)
        try:
            return self.monitor()
        finally:
            self.kill()
