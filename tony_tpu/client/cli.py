"""Command-line submitters.

Analog of the reference's tony-cli module (reference: tony-cli/src/main/java/
com/linkedin/tony/cli/ClusterSubmitter.java:37-88, LocalSubmitter.java:33-71,
NotebookSubmitter.java:43-126). One binary, subcommand per submitter:

  python -m tony_tpu.client.cli submit  --src_dir src --executes 'python m.py' \\
      --conf tony.worker.instances=2 [--conf_file tony.xml]
  python -m tony_tpu.client.cli local    ... (forces the local backend —
      the zero-install LocalSubmitter experience)
  python -m tony_tpu.client.cli notebook --executes 'jupyter lab' (single-node
      notebook job with a long default timeout)

CLI option names follow the reference's common options
(Utils.getCommonOptions:234: --conf, --conf_file, --src_dir, --executes,
--python_venv, --shell_env, --task_params)."""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from tony_tpu import constants
from tony_tpu.client.client import TonyClient
from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig, parse_cli_confs


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tony", description="TPU-native distributed ML job orchestrator")
    sub = parser.add_subparsers(dest="command", required=True)
    k = sub.add_parser("kill", help="kill a running job by its job dir")
    k.add_argument("job_dir", help="the job's staging dir "
                                   "(<tony.staging.dir>/<app_id>)")
    st = sub.add_parser("status",
                        help="show a job's status and task URLs by job dir")
    st.add_argument("job_dir", help="the job's staging dir "
                                    "(<tony.staging.dir>/<app_id>)")
    lg = sub.add_parser(
        "logs", help="print task logs from a job dir (the `yarn logs "
                     "-applicationId` analog)")
    lg.add_argument("job_dir", help="the job's staging dir "
                                    "(<tony.staging.dir>/<app_id>)")
    lg.add_argument("--task", default="",
                    help="only this task, e.g. worker:0 (default: all)")
    lg.add_argument("--tail", type=int, default=0, metavar="N",
                    help="last N lines of each log (default: everything)")
    cl = sub.add_parser(
        "cluster",
        help="talk to the multi-tenant cluster daemon (docs/cluster.md): "
             "submit/status/cancel/list/stats")
    cl.add_argument("action",
                    choices=("submit", "status", "cancel", "list", "stats"))
    cl.add_argument("--home",
                    help="daemon home dir (reads <home>/daemon.port)")
    cl.add_argument("--host", default="127.0.0.1")
    cl.add_argument("--port", type=int, default=0,
                    help="daemon port (overrides --home)")
    cl.add_argument("--job-id", default="",
                    help="job id for status/cancel (optional on submit)")
    cl.add_argument("--user", default=os.environ.get("USER", "anon"))
    cl.add_argument("--slices", type=int, default=1,
                    help="gang size (granted all-or-nothing)")
    cl.add_argument("--priority", type=int, default=0)
    cl.add_argument("--digest", default="",
                    help="staging digest for warm-pool affinity")
    cl.add_argument("--elastic", action="store_true",
                    help="job tolerates induced shrinks (preemptible)")
    c = sub.add_parser(
        "convert", add_help=False,
        help="convert data files to TONY1 framed records "
             "(see python -m tony_tpu.io.convert --help)")
    c.add_argument("convert_args", nargs=argparse.REMAINDER)
    for name, help_text in (
            ("submit", "submit a job (ClusterSubmitter analog)"),
            ("local", "submit forcing the local subprocess backend"),
            ("notebook", "run a single-node notebook job")):
        p = sub.add_parser(name, help=help_text)
        p.add_argument("--executes", required=(name != "notebook"),
                       default="jupyter lab" if name == "notebook" else None,
                       help="command each task runs (the training script)")
        p.add_argument("--conf_file", help="job config (tony.xml or k=v file)")
        p.add_argument("--conf", action="append", default=[],
                       help="config override key=value (repeatable)")
        p.add_argument("--src_dir", help="source tree staged to every task")
        p.add_argument("--python_venv", help="venv zip staged to every task")
        p.add_argument("--python_binary_path",
                       help="python used to launch executors")
        p.add_argument("--shell_env", action="append", default=[],
                       help="extra env forwarded to tasks (k=v, repeatable)")
        p.add_argument("--task_params", default="",
                       help="extra args appended to --executes")
    return parser


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw[:1] == ["convert"]:
        # Forward EVERYTHING (including a leading --option or --help) to
        # the converter's own parser — argparse.REMAINDER on a subparser
        # refuses option-first argument lists.
        from tony_tpu.io.convert import main as convert_main
        return convert_main(raw[1:])
    args = build_parser().parse_args(raw)
    if args.command == "kill":
        return kill_job(args.job_dir)
    if args.command == "status":
        return job_status(args.job_dir)
    if args.command == "logs":
        return job_logs(args.job_dir, task=args.task, tail=args.tail)
    if args.command == "cluster":
        return cluster_cmd(args)
    overrides = parse_cli_confs(args.conf)
    conf = TonyConfig.load(args.conf_file, cli_overrides=overrides)
    if args.python_venv:
        conf.set(K.PYTHON_VENV_KEY, args.python_venv)
    if args.python_binary_path:
        conf.set(K.PYTHON_BINARY_PATH_KEY, args.python_binary_path)
    if args.command == "local":
        conf.set(K.SCHEDULER_BACKEND_KEY, "local")
    elif args.command == "notebook":
        # Single-node, long-lived (reference: NotebookSubmitter 24h timeout)
        conf.set(K.APPLICATION_SINGLE_NODE_KEY, "true")
        if K.instances_key(constants.NOTEBOOK_JOB_NAME) not in conf:
            conf.set(K.instances_key(constants.NOTEBOOK_JOB_NAME), "1")
        if conf.get_int(K.APPLICATION_TIMEOUT_KEY, 0) == 0:
            conf.set(K.APPLICATION_TIMEOUT_KEY, str(24 * 3600 * 1000))
    command = args.executes
    if args.task_params:
        command = f"{command} {args.task_params}"
    shell_env = {}
    for pair in args.shell_env:
        k, _, v = pair.partition("=")
        shell_env[k] = v
    on_tracking_url = None
    if args.command == "notebook":
        on_tracking_url = _start_notebook_proxy
    # --src_dir flag, else the (default-empty) conf key — both explicit, so
    # a missing directory is a loud error, never a silent skip.
    src_dir = args.src_dir or conf.get(K.SRC_DIR_KEY) or None
    if src_dir and not os.path.isdir(src_dir):
        raise SystemExit(f"src_dir {src_dir} does not exist")
    try:
        client = TonyClient(conf, command, src_dir=src_dir,
                            shell_env=shell_env,
                            on_tracking_url=on_tracking_url)
        return client.run()
    except ValueError as e:
        # Config validation failures (bad resource asks, topology vs
        # instances) are user errors: one actionable line, no traceback.
        raise SystemExit(f"tony: {e}")


def cluster_cmd(args) -> int:
    """Daemon-plane client ops (docs/cluster.md §Submission API)."""
    from tony_tpu.cluster.daemon import DaemonClient, DaemonError
    if not args.port and not args.home:
        raise SystemExit("tony: cluster needs --home or --port")
    try:
        client = (DaemonClient(args.host, args.port) if args.port
                  else DaemonClient.from_home(args.home, host=args.host))
        with client:
            if args.action == "submit":
                out = client.submit(user=args.user, slices=args.slices,
                                    priority=args.priority,
                                    digest=args.digest,
                                    elastic=args.elastic,
                                    job_id=args.job_id or None)
            elif args.action == "list":
                out = {"jobs": client.list_jobs()}
            elif args.action == "stats":
                out = client.stats()
            else:
                if not args.job_id:
                    raise SystemExit(
                        f"tony: cluster {args.action} needs --job-id")
                out = (client.status(args.job_id)
                       if args.action == "status"
                       else client.cancel(args.job_id))
        print(json.dumps(out, indent=1))
        return 0
    except (DaemonError, OSError) as e:
        raise SystemExit(f"tony: {e}")


def _coordinator_rpc(job_dir: str):
    """RPC client for the job's coordinator, or None when no coordinator
    address has been written (job never started / dir wrong). Reads the
    per-job secret if security is on — same handshake as `tony kill`."""
    from tony_tpu.cluster.coordinator import COORDINATOR_ADDR_FILE
    from tony_tpu.rpc.client import ApplicationRpcClient

    addr_path = os.path.join(job_dir, COORDINATOR_ADDR_FILE)
    if not os.path.exists(addr_path):
        return None
    with open(addr_path, encoding="utf-8") as f:
        addr = f.read().strip()
    secret = None
    secret_path = os.path.join(job_dir, constants.TONY_SECRET_FILE)
    if os.path.exists(secret_path):
        with open(secret_path, encoding="utf-8") as f:
            secret = f.read().strip()
    # TLS jobs: pin to the job cert staged next to the secret — a
    # plaintext channel would fail the coordinator's TLS handshake.
    cert_path = os.path.join(job_dir, constants.TONY_TLS_CERT_FILE)
    tls_cert = cert_path if os.path.exists(cert_path) else None
    return ApplicationRpcClient(addr, secret=secret, max_retries=3,
                                tls_cert=tls_cert)


def job_status(job_dir: str) -> int:
    """Out-of-band status: final-status.json for finished jobs, a live
    getApplicationStatus + task-URL listing for running ones (the
    reference exposes status only through the polling client /
    `yarn application -status`; this is the job-dir-keyed analog)."""
    import json

    from tony_tpu.cluster.coordinator import FINAL_STATUS_FILE

    final_path = os.path.join(job_dir, FINAL_STATUS_FILE)
    if os.path.exists(final_path):
        with open(final_path, encoding="utf-8") as f:
            final = json.load(f)
        print(f"status: {final.get('status', '?')} (finished)")
        # the keys Coordinator.stop() actually records
        for key in ("app_id", "message", "tensorboard_url"):
            if final.get(key) not in (None, ""):
                print(f"{key}: {final[key]}")
        return 0
    rpc = _coordinator_rpc(job_dir)
    if rpc is None:
        print(f"no job found under {job_dir}", file=sys.stderr)
        return 1
    try:
        st = rpc.get_application_status()
        print(f"status: {st.status} (session {st.session_id})")
        if st.message:
            print(f"message: {st.message}")
        for url in rpc.get_task_urls():
            print(f"  {url.name}:{url.index}  {url.url}")
    except Exception as e:
        print(f"coordinator at {rpc.address} unreachable ({e}) — job may "
              f"have been killed without writing a final status",
              file=sys.stderr)
        return 1
    finally:
        rpc.close()
    return 0


def job_logs(job_dir: str, task: str = "", tail: int = 0) -> int:
    """Print task logs from a job dir — the ``yarn logs -applicationId``
    analog. Task logs live where the coordinator wrote them: the
    ``tony.container.log-dir`` override from the job's frozen
    tony-final.xml when set, else ``<job_dir>/logs`` (which always holds
    the coordinator's own am.stdout/stderr)."""
    import collections
    dirs = [os.path.join(job_dir, constants.TONY_LOG_DIR)]
    final_xml = os.path.join(job_dir, constants.TONY_FINAL_XML)
    if os.path.exists(final_xml):
        override = TonyConfig.load(final_xml).get(
            K.CONTAINER_LOG_DIR_KEY) or ""
        if override and os.path.abspath(override) != os.path.abspath(dirs[0]):
            dirs.append(override)
    if not any(os.path.isdir(d) for d in dirs):
        print(f"tony: no logs directory under {job_dir}", file=sys.stderr)
        return 1
    want_stem = constants.task_log_stem(task) if task else ""
    printed = 0
    for log_dir in dirs:
        if not os.path.isdir(log_dir):
            continue
        for name in sorted(os.listdir(log_dir)):
            stem = name.rsplit(".", 1)[0]
            if want_stem and stem != want_stem:
                continue
            path = os.path.join(log_dir, name)
            if not os.path.isfile(path):
                continue
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                # bounded: --tail on a multi-GB training log must not
                # materialize the whole file
                lines = (list(collections.deque(f, maxlen=tail)) if tail > 0
                         else f.readlines())
            print(f"==== {name} ====")
            sys.stdout.writelines(lines)
            if lines and not lines[-1].endswith("\n"):
                print()
            printed += 1
    if not printed:
        print(f"tony: no logs matching {task!r} under "
              f"{', '.join(dirs)}", file=sys.stderr)
        return 1
    return 0


def kill_job(job_dir: str) -> int:
    """Signal a running job's coordinator to tear down (the out-of-band
    kill the reference lacked — its only kills were client timeout/Ctrl-C).
    Reads the coordinator address (and per-job secret, if security is on)
    from the job dir and calls finishApplication; a finish with tasks still
    running reduces to final status KILLED."""
    import json
    from tony_tpu.cluster.coordinator import FINAL_STATUS_FILE

    final_path = os.path.join(job_dir, FINAL_STATUS_FILE)
    if os.path.exists(final_path):
        # coordinator.addr outlives the job; the final status is what
        # distinguishes "already finished" from "unreachable".
        with open(final_path, encoding="utf-8") as f:
            status = json.load(f).get("status", "?")
        print(f"job already finished with status {status}; nothing to kill")
        return 0
    rpc = _coordinator_rpc(job_dir)
    if rpc is None:
        print(f"no running coordinator found under {job_dir}",
              file=sys.stderr)
        return 1
    try:
        rpc.finish_application()
    except Exception as e:
        print(f"kill failed: coordinator at {rpc.address} unreachable ({e})",
              file=sys.stderr)
        return 1
    finally:
        rpc.close()
    print(f"kill signalled to coordinator at {rpc.address}")
    return 0


_notebook_proxy = None


def _start_notebook_proxy(url: str):
    """Proxy a local gateway port to the notebook host (reference:
    NotebookSubmitter.java:93-106 + tony-proxy ProxyServer). Called again
    after a coordinator retry (new notebook endpoint): the stale proxy is
    stopped so it cannot keep forwarding to the dead host."""
    global _notebook_proxy
    from tony_tpu.proxy import ProxyServer
    if _notebook_proxy is not None:
        _notebook_proxy.stop()
    hostport = url.split("//")[-1].rstrip("/")
    host, _, port = hostport.rpartition(":")
    proxy = ProxyServer(host, int(port), local_port=0)
    local_port = proxy.start()
    _notebook_proxy = proxy
    logging.getLogger("tony_tpu.client").info(
        "notebook proxied at http://localhost:%d — from a remote gateway, "
        "run `ssh -L 18888:localhost:%d <gateway>` and open "
        "http://localhost:18888", local_port, local_port)
    return proxy


if __name__ == "__main__":
    sys.exit(main())
