"""Disaggregated prefill/decode serving: KV-shipping prefill gangs
feeding decode gangs over tensor channels.

A colocated :class:`~tony_tpu.serving.server.ServingServer` interleaves
prefill and decode dispatches on ONE device queue, so every admission's
prefill stalls the in-flight decode chunk — inter-token latency spikes
with prompt length whenever admissions are concurrent (the TTFT/ITL
histograms can see it; nothing colocated can fix it). Disaggregation
specializes two gangs to the two workloads:

- :class:`PrefillServer` (the prefill tier, STATELESS per request):
  accepts ADMITs, runs the bucketed
  :func:`~tony_tpu.models.serve.prefill_ship_rows` program on waves of
  queued prompts, and ships each row's K/V + last-real logits + rng
  stream state as one :mod:`~tony_tpu.serving.kvship` blob over a
  TONYC1 tensor channel (CH_TENSOR byte-blob frames — bounded window,
  reconnect-with-resume) to the decode gang named in the ADMIT; a
  ``HANDOFF`` frame tells the submitter (the router) which gang adopted
  the row.
- :class:`DecodeServer` (the decode tier): a normal serving engine
  whose admissions arrive as KV packages through its
  :class:`~tony_tpu.channels.channel.ChannelHub` — landing is a
  scatter (:func:`~tony_tpu.models.serve.land_kv_rows`), never a model
  forward, so decode chunks are NEVER preempted by prefill work. Token
  deltas push to the connection that declared itself the delta sink
  (``BIND`` — the router's link).

Deployed behind :class:`~tony_tpu.serving.router.ServingRouter` in
disaggregated placement mode (``decode_replicas=``): ADMIT goes to the
prefill replica with the shallowest queue, TOKENS stream from the
decode replica that adopted the row, and a decode-replica loss re-
admits its streams through a surviving prefill replica with the
streamed prefix folded into the prompt (the PR-5 failover path — zero
duplicated/dropped tokens, test-pinned).

Token identity (greedy AND sampled) vs the colocated engine is
test-pinned end-to-end across two real processes: both tiers run the
same bucket ladder and the same prefill program, the shipment carries
the exact buffers colocated admission would have landed, and the
per-request rng key ships with them. Speculative serving is EXPLICITLY
not supported disaggregated (the shipment carries no draft-model
cache); shared-prefix templates likewise stay colocated.

Observability: ``tony_prefill_queue_depth`` /
``tony_prefill_requests_total`` (prefill tier),
``tony_kv_ship_seconds`` / ``tony_kv_ship_bytes_total`` (the KV
handoff wall, prefill side), ``tony_kv_land_seconds`` /
``tony_decode_idle_slots`` (decode side), plus the channel plane's
``tony_channel_*`` series. The request trace grows a ``kv.ship`` child
under the prefill tier's ``engine.request`` span, and the decode
tier's ``engine.request`` parents under it — the TTFT decomposition
stays causal across the two gangs.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from tony_tpu.channels.channel import (ChannelClosed, ChannelError,
                                       ChannelHub, ChannelSender)
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.runtime import tracing
from tony_tpu.serving import kvship
from tony_tpu.serving import protocol as P
from tony_tpu.serving.prefix import PrefixHost, fingerprint, match_prefix
from tony_tpu.serving.server import FrameConn, FrameServerBase
from tony_tpu.serving.weightstore import WeightHost, pack_weights, \
    tree_digest

log = logging.getLogger(__name__)

#: the channel every KV shipment rides (one hub port per decode task
#: multiplexes by name, so prefill replicas all share it)
KV_CHANNEL = "kvship"


class _PrefillItem:
    """One admitted prompt waiting for (or undergoing) prefill."""

    __slots__ = ("conn", "rid", "prompt", "budget", "decode", "stream",
                 "rng_off", "cancelled", "done", "span", "queued_span",
                 "prefix", "cls")

    def __init__(self, conn: FrameConn, rid: int, prompt: list[int],
                 budget: int, decode: str, stream: int,
                 trace_ctx: dict | None,
                 prefix: str | None = None, rng_off: int = 0,
                 cls: str = "standard") -> None:
        self.conn = conn
        self.rid = rid
        self.prompt = prompt
        self.budget = budget
        self.decode = decode
        self.stream = stream
        #: QoS class: orders the tier's waves (interactive prompts
        #: never wait a wave behind batch) and ships in the KV meta so
        #: the decode tier's class floors apply to the adopted row
        self.cls = cls
        #: stream positions already consumed by a previous placement
        #: (router-coordinated migration): shipped in the KV meta so the
        #: adopting decode row draws its first sample at this offset
        self.rng_off = rng_off
        #: the resident-prefix id this prompt continues (ADMIT's
        #: ``prefix`` field) — resolved against the tier's store at
        #: wave time; a miss just full-prefills
        self.prefix = prefix
        self.cancelled = False
        self.done = False       # a terminal frame (or conn loss) settled it
        tr = tracing.get_tracer()
        # the prefill tier's leg of the request trace: engine.request
        # (role=prefill) ▸ engine.queued ▸ kv.ship; the decode tier's
        # engine.request parents under this one via the shipped context
        self.span = tr.start_span("engine.request", ctx=trace_ctx,
                                  role="prefill",
                                  prompt_tokens=len(prompt),
                                  budget=budget)
        self.queued_span = tr.start_span("engine.queued",
                                         parent=self.span)


class PrefillServer(WeightHost, PrefixHost, FrameServerBase):
    """The prefill tier of disaggregated serving (see module
    docstring). Stateless per request — no persistent KV cache, no
    decode loop: ADMIT → bucketed prefill wave → KV shipment →
    HANDOFF.

    ``max_batch`` rows prefill per wave (padded to exactly that many,
    so each bucket compiles ONE program); requests are validated
    against ``max_len`` exactly as the decode tier's batcher will
    (identical ladder, identical ceiling — a prompt the decode gang
    cannot land is rejected HERE, before any compute). Rolling (ring)
    cache configs take the exact-length
    :func:`~tony_tpu.models.serve.prefill_ship_row` path and ship the
    full capacity ring.

    The tier is a :class:`~tony_tpu.serving.prefix.PrefixHost` too
    (prefix reuse composes with disaggregation): a wave item whose
    prompt continues a resident prefix runs only its SUFFIX through
    the model (:func:`~tony_tpu.models.serve.prefix_ship_rows` against
    the stored template) and ships the full prefix+suffix row — the
    decode gang needs no prefix knowledge. Templates arrive over the
    same install path as the colocated server's (PREFIX ops or a
    peer's template ship); ring configs degrade prefix-blind with one
    warning."""

    def __init__(self, params, cfg, *, max_len: int, seed: int = 0,
                 max_batch: int = 4, admission_buckets=None,
                 bind_host: str = "127.0.0.1", port: int = 0,
                 channel_window: int = 8,
                 ship_timeout_s: float = 30.0, registry=None,
                 weights_version: str | None = None,
                 weights_digest: str | None = None,
                 max_queue_depth: int = 128,
                 busy_retry_ms: int = 250) -> None:
        super().__init__(bind_host, port)
        import jax

        self.params = params
        self.cfg = cfg
        #: the weights generation this tier serves (HELLO/STATS) — the
        #: router's version-pinned placement signal (rolling upgrades)
        self.weights_version = weights_version
        #: content digest of the served weight tree (computed at
        #: start() when not given) — the unversioned pinning fallback
        #: and the peer-pull artifact name (warm scale-up)
        self.weights_digest = weights_digest
        self.max_len = int(max_len)
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.admission_buckets = (tuple(sorted({int(b) for b in
                                                admission_buckets}))
                                  if admission_buckets else None)
        self.ship_timeout_s = ship_timeout_s
        self.channel_window = channel_window
        #: overload bound on the tier's wait queue (0 disables): past
        #: it, non-interactive admissions shed with BUSY — prefill is
        #: where the work would be WASTED under overload, so the tier
        #: says no before computing anything
        self.max_queue_depth = int(max_queue_depth)
        self.busy_retry_ms = int(busy_retry_ms)
        self._ring = bool(cfg.kv_cache_capacity)
        self._base_key = jax.random.PRNGKey(seed)
        self._cv = threading.Condition()
        self._queue: deque[_PrefillItem] = deque()
        self._items: dict[tuple[int, int], _PrefillItem] = {}
        self._inflight = 0
        self._next_stream = 0
        self._senders: dict[str, ChannelSender] = {}
        self._senders_lock = threading.Lock()
        self._worker: threading.Thread | None = None
        reg = registry or metrics_mod.get_default()
        self._reg = reg
        self._qdepth_g = reg.gauge(
            "tony_prefill_queue_depth",
            help="prompts waiting for a prefill wave (the router's "
                 "prefill-tier placement signal)")
        self._reqs_c = reg.counter(
            "tony_prefill_requests_total",
            help="prompts prefilled and shipped by the prefill tier")
        self._ship_h = reg.histogram(
            "tony_kv_ship_seconds",
            help="KV handoff wall per request, prefill side: extract "
                 "+ serialize + channel send + the decode gang's ack")
        self._ship_bytes_c = reg.counter(
            "tony_kv_ship_bytes_total",
            help="KV shipment payload bytes sent to decode gangs")
        self._fwd_tok_c = reg.counter(
            "tony_prefill_forward_tokens_total",
            help="true prompt/suffix tokens run through a prefill or "
                 "extend forward at the prefill tier (the FLOPs proxy "
                 "the prefix fast path shrinks)")
        self._pref_tok_c = reg.counter(
            "tony_prefill_prefix_tokens_total",
            help="prefix positions served from a resident template "
                 "instead of a forward at the prefill tier")
        self._shed_c = {
            c: reg.counter(
                "tony_serve_shed_total",
                help="admissions refused with BUSY under overload",
                **{"class": c})
            for c in P.QOS_CLASSES}
        self._qdepth_g.set(0)
        #: resident prefix templates: id -> (tokens, template). Grown
        #: only; entries immutable — lock-free reads at wave time.
        self._prefix_store: dict[str, tuple] = {}
        self._ring_prefix_warned = False
        self._proto_bufs = None          # lazy layout prototype
        self._init_prefix_host(reg)
        # weights lane shares the prefix hub port (kind-tagged blobs)
        self._init_weight_host(
            reg, exporter=lambda: pack_weights(
                self.params, version=self.weights_version),
            hub=self._prefix_hub)

    # -- resident prefix templates (PrefixHost hooks) -----------------------
    def install_prefix(self, tokens, prefix_id: str | None = None):
        """Compute ``tokens``' K/V template on this tier and make it
        resident; None when degraded (ring layout)."""
        from tony_tpu.models.serve import prefix_template

        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("prefix tokens must be non-empty")
        if self._ring:
            if not self._ring_prefix_warned:
                self._ring_prefix_warned = True
                log.warning("prefill tier: rolling (ring) caches cannot "
                            "host prefix templates; serving prefix-blind")
            return None
        if len(tokens) + 2 > self.max_len:
            raise ValueError(
                f"prefix of {len(tokens)} tokens leaves no room for a "
                f"suffix + generation under max_len {self.max_len}")
        pid = prefix_id or fingerprint(tokens)
        template = prefix_template(self.params, tokens, self.cfg)
        self._prefix_store[str(pid)] = (tokens, template)
        return str(pid)

    def install_prefix_template(self, meta, bufs) -> str:
        from tony_tpu.models.serve import validate_template_bufs

        if int(meta["vocab"]) != self.cfg.vocab_size:
            raise ValueError(
                f"template vocab {meta['vocab']} != this model's "
                f"{self.cfg.vocab_size} (shipped from a different "
                f"model?)")
        if self._ring:
            raise ValueError("rolling-cache layout cannot host prefix "
                             "templates (degraded prefix-blind)")
        tokens = [int(t) for t in meta["tokens"]]
        if len(tokens) + 2 > self.max_len:
            # same room check as the local install paths: a too-long
            # shipped template would otherwise install, get ADVERTISED
            # (steering the router's prefix placement here), yet never
            # serve a single admissible prompt
            raise ValueError(
                f"prefix of {len(tokens)} tokens leaves no room for a "
                f"suffix + generation under max_len {self.max_len}")
        if self._proto_bufs is None:
            from tony_tpu.models.decode import _kv_bufs, init_kv_cache
            self._proto_bufs = _kv_bufs(init_kv_cache(self.cfg, 1, 1))
        template = validate_template_bufs(self._proto_bufs, tokens, bufs)
        pid = str(meta["id"])
        self._prefix_store[pid] = (tokens, template)
        return pid

    def resident_prefixes(self) -> list:
        return sorted(self._prefix_store)

    def _prefix_blob(self, prefix_id: str) -> bytes:
        entry = self._prefix_store.get(str(prefix_id))
        if entry is None:
            raise ValueError(f"prefix {prefix_id!r} is not resident")
        tokens, template = entry
        return kvship.pack_template(
            str(prefix_id), tokens,
            {n: np.asarray(a) for n, a in template.items()},
            self.cfg.vocab_size)

    def _resolve_item(self, item: _PrefillItem):
        """(tokens, template) the item's prompt continues, or None:
        the explicit ADMIT prefix id first, else the longest resident
        token-boundary match."""
        if self._ring or not self._prefix_store:
            return None
        if item.prefix is not None:
            ent = self._prefix_store.get(item.prefix)
            if (ent is not None and len(ent[0]) < len(item.prompt)
                    and item.prompt[:len(ent[0])] == ent[0]):
                return item.prefix, ent
        entries = list(self._prefix_store.items())
        pid = match_prefix(item.prompt,
                           ((p, e[0]) for p, e in entries))
        return next(((p, e) for p, e in entries if p == pid), None) \
            if pid is not None else None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        if self.weights_digest is None:
            try:
                self.weights_digest = tree_digest(self.params)
            except Exception as e:          # noqa: BLE001 — advisory
                log.warning("weights digest not computed: %s", e)
        self._worker = threading.Thread(target=self._work_loop,
                                        name="tony-prefill-worker",
                                        daemon=True)
        self._worker.start()
        self._start_prefix_host()
        self._start_weight_host()
        port = super().start()
        log.info("prefill tier on %s:%s (%d-row waves; prefix lane on "
                 ":%s)", self.bind_host, port, self.max_batch,
                 self.prefix_port)
        return port

    def stop(self) -> None:
        self._stopping.set()
        self._close_listener()
        with self._cv:
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=60)
        self._stop_prefix_host()
        self._stop_weight_host()
        with self._senders_lock:
            senders, self._senders = list(self._senders.values()), {}
        for s in senders:
            s.close(drain=True, timeout=10.0)
        self._close_conns()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    # -- frame handling (reader threads) ------------------------------------
    def _hello_payload(self) -> dict:
        return {"v": 1, "role": "prefill", "slots": self.max_batch,
                "prefixes": self.resident_prefixes(),
                "ring": self._ring, "prefix_port": self.prefix_port,
                "weights_version": self.weights_version,
                "weights_digest": self.weights_digest,
                "weight_port": self.weight_port,
                "weights_resident": self.weight_store.resident_digests()}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype == P.ADMIT:
            self._admit(conn, rid, payload)
        elif ftype == P.CANCEL:
            self._cancel(conn, rid)
        elif ftype == P.STATS:
            conn.send(P.STATS, 0, P.pack_json(self.stats()))
        elif ftype == P.PREFIX:
            self._handle_prefix_frame(conn, rid, payload)
        elif ftype == P.WEIGHTS:
            self._handle_weights_frame(conn, rid, payload)
        else:
            raise P.ProtocolError(
                f"unexpected frame type {P.FRAME_NAMES.get(ftype, ftype)}"
                f" at the prefill tier")

    def stats(self) -> dict:
        with self._cv:
            depth, active = len(self._queue), self._inflight
            by_cls = {c: 0 for c in P.QOS_CLASSES}
            for it in self._queue:
                by_cls[it.cls] += 1
        return {"queue_depth": depth, "active": active,
                "queue_depths": by_cls,
                "slots": self.max_batch, "role": "prefill",
                "prefixes": self.resident_prefixes(),
                "ring": self._ring,
                "weights_version": self.weights_version,
                "weights_digest": self.weights_digest,
                "weights_resident": self.weight_store.resident_digests()}

    def _admit(self, conn: FrameConn, rid: int, payload: bytes) -> None:
        prompt, max_new, _stream = P.parse_admit(payload)
        obj = P.unpack_json(payload)
        decode = P.parse_decode_target(obj)
        if rid == 0:
            raise P.ProtocolError("ADMIT rid must be nonzero")
        err = None
        if decode is None:
            err = ("disaggregated ADMIT must name its decode target "
                   "({'decode': 'host:port'})")
        elif not prompt:
            err = "empty prompt"
        elif max_new <= 0:
            err = f"max_new_tokens must be positive, got {max_new}"
        elif not self._ring and len(prompt) + max_new > self.max_len:
            err = (f"prompt {len(prompt)} + {max_new} new tokens "
                   f"exceeds max_len {self.max_len}")
        if err is not None:
            conn.send(P.ERROR, rid, P.pack_json({"message": err}))
            return
        try:
            cls = P.parse_class(obj)
        except ValueError as e:
            conn.send(P.ERROR, rid, P.pack_json({"message": str(e)}))
            return
        key = (conn.id, rid)
        rng = P.parse_rng(obj)
        # duplicate-rid/BUSY replies go out AFTER the condition is
        # dropped: the send can block on a slow client and every
        # prefill worker waits on this condition (TL001)
        shed = False
        with self._cv:
            duplicate = key in self._items
            if (not duplicate and self.max_queue_depth
                    and cls != "interactive"
                    and len(self._queue) >= self.max_queue_depth):
                # overload shed at the tier where refused work costs
                # nothing yet; interactive admissions ride through —
                # the wave order and the decode tier's preemption are
                # what they paid for
                shed = True
            elif not duplicate:
                item = _PrefillItem(conn, rid, prompt, max_new, decode,
                                    (self._next_stream if rng is None
                                     else int(rng[0])),
                                    P.parse_trace_ctx(obj),
                                    prefix=P.parse_prefix_id(obj),
                                    rng_off=0 if rng is None else int(rng[1]),
                                    cls=cls)
                if rng is None:
                    self._next_stream += 1
                self._items[key] = item
                self._queue.append(item)
                self._qdepth_g.set(len(self._queue))
                self._cv.notify_all()
        if duplicate:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": f"request id {rid} is already active"}))
            return
        if shed:
            self._shed_c[cls].inc()
            conn.send(P.BUSY, rid, P.pack_json(
                {"retry_after_ms": self.busy_retry_ms}))
            return

    def _cancel(self, conn: FrameConn, rid: int) -> None:
        """Cancel a QUEUED prompt (idempotent; an already-shipped
        request is the decode tier's to cancel — the router fans the
        CANCEL to both tiers)."""
        with self._cv:
            item = self._items.pop((conn.id, rid), None)
            if item is None or item.cancelled:
                return
            item.cancelled = True
            try:
                self._queue.remove(item)
            except ValueError:
                return      # already in a wave; _ship_item retires it
            item.done = True
            self._qdepth_g.set(len(self._queue))
        item.queued_span.end()
        item.span.end(reason="cancelled")
        item.conn.send(P.RETIRED, item.rid, P.pack_json(
            {"reason": "cancelled", "tokens": 0}))

    def _on_conn_closed(self, conn: FrameConn) -> None:
        with self._cv:
            doomed = [it for key, it in list(self._items.items())
                      if it.conn is conn]
            for it in doomed:
                self._items.pop((conn.id, it.rid), None)
                it.cancelled = True
                it.done = True      # conn gone: no terminal frame possible
                try:
                    self._queue.remove(it)
                except ValueError:
                    pass
            self._qdepth_g.set(len(self._queue))
        for it in doomed:
            it.queued_span.end()
            it.span.end(reason="disconnected")

    # -- the prefill worker -------------------------------------------------
    def _take_wave(self) -> list[_PrefillItem] | None:
        with self._cv:
            while not self._queue:
                if self._stopping.is_set():
                    return None
                self._cv.wait(timeout=0.25)
            # the wave takes classes in priority order, FIFO within a
            # class (stable sort): an interactive prompt admitted last
            # still prefills ahead of every waiting batch prompt
            order = {c: i for i, c in enumerate(P.QOS_CLASSES)}
            live = [it for it in self._queue if not it.cancelled]
            live.sort(key=lambda it: order.get(it.cls, len(order)))
            wave = live[:self.max_batch]
            taken = {id(it) for it in wave}
            self._queue = deque(it for it in self._queue
                                if not it.cancelled
                                and id(it) not in taken)
            self._inflight = len(wave)
            self._qdepth_g.set(len(self._queue))
            return wave

    def _work_loop(self) -> None:
        from tony_tpu.models.serve import bucket_for

        while True:
            wave = self._take_wave()
            if wave is None:
                return
            try:
                if self._ring:
                    for item in wave:
                        self._prefill_group([item], 0)
                else:
                    # group by (resident prefix, bucket): a prefix-hit
                    # group pays only its suffixes' prefill compute
                    groups: dict[tuple, list] = {}
                    entries: dict = {None: None}
                    for item in wave:
                        hit = self._resolve_item(item)
                        if hit is None:
                            key = (None,
                                   bucket_for(len(item.prompt),
                                              self.max_len,
                                              self.admission_buckets))
                        else:
                            pid, ent = hit
                            entries[pid] = ent
                            cap = self.max_len - len(ent[0])
                            key = (pid,
                                   bucket_for(len(item.prompt)
                                              - len(ent[0]), cap,
                                              self.admission_buckets))
                        groups.setdefault(key, []).append(item)
                    for pid, bucket in sorted(
                            groups, key=lambda k: (k[0] or "", k[1])):
                        self._prefill_group(groups[(pid, bucket)],
                                            bucket, entries[pid])
            except Exception as e:  # noqa: BLE001 — thread survival
                # the tier's ONLY worker: an unexpected wave failure
                # must cost this wave, never the thread (a dead worker
                # queues every future admission forever)
                log.exception("prefill wave processing failed")
                # every wave item not yet settled by a terminal frame is
                # doomed — including one a mid-wave CANCEL popped from
                # self._items whose RETIRED was deferred to _ship_item
                # (membership in self._items would miss it)
                for item in [it for it in wave if not it.done]:
                    if item.cancelled:
                        with self._cv:
                            self._items.pop((item.conn.id, item.rid),
                                            None)
                            item.done = True
                        item.queued_span.end()
                        item.span.end(reason="cancelled")
                        item.conn.send(P.RETIRED, item.rid, P.pack_json(
                            {"reason": "cancelled", "tokens": 0}))
                    else:
                        self._fail_item(item,
                                        f"prefill wave failed: {e}")
            finally:
                with self._cv:
                    self._inflight = 0

    def _prefill_group(self, grp: list[_PrefillItem], bucket: int,
                       entry: tuple | None = None) -> None:
        """Prefill one bucket group (padded to ``max_batch`` rows — one
        compiled program per bucket) and ship each real row. ``entry``
        is a resident-prefix ``(tokens, template)`` pair: the group
        then runs only its SUFFIXES through the model
        (:func:`~tony_tpu.models.serve.prefix_ship_rows`) and ships
        prefix+suffix rows. Overridden hooks: the bench's
        deterministic arm injects its prefill compute floor around
        this."""
        import jax

        from tony_tpu.models.decode import extract_kv_rows
        from tony_tpu.models.serve import (prefill_ship_row,
                                           prefill_ship_rows,
                                           prefix_ship_rows)
        import jax.numpy as jnp

        for item in grp:
            item.queued_span.end()
        try:
            if self._ring:
                (item,) = grp
                lg, mini = prefill_ship_row(
                    self.params,
                    jnp.asarray(item.prompt, jnp.int32)[None], self.cfg)
                widths = [mini["k"].shape[2]]
                lengths = [len(item.prompt)]
                fwd = len(item.prompt)
            elif entry is not None:
                p_toks, template = entry
                p_len = len(p_toks)
                toks = np.zeros((self.max_batch, bucket), np.int64)
                lens = np.ones((self.max_batch,), np.int32)
                for i, item in enumerate(grp):
                    suffix = item.prompt[p_len:]
                    toks[i, :len(suffix)] = suffix
                    lens[i] = len(suffix)
                lg, mini = prefix_ship_rows(
                    self.params, template,
                    jnp.asarray(toks, jnp.int32), jnp.asarray(lens),
                    self.cfg)
                # the shipped row is the FULL prefix+suffix frontier —
                # the decode gang lands it like any other package
                widths = [len(item.prompt) for item in grp]
                lengths = widths
                fwd = sum(len(item.prompt) - p_len for item in grp)
                self._pref_tok_c.inc(p_len * len(grp))
            else:
                toks = np.zeros((self.max_batch, bucket), np.int64)
                lens = np.ones((self.max_batch,), np.int32)
                for i, item in enumerate(grp):
                    toks[i, :len(item.prompt)] = item.prompt
                    lens[i] = len(item.prompt)
                lg, mini = prefill_ship_rows(
                    self.params, jnp.asarray(toks, jnp.int32),
                    jnp.asarray(lens), self.cfg)
                widths = [len(item.prompt) for item in grp]
                lengths = widths
                fwd = sum(widths)
            rows = extract_kv_rows(mini, widths)
            lg_host = jax.device_get(lg)
            self._fwd_tok_c.inc(fwd)
        except Exception as e:            # device failure: request-scoped
            log.exception("prefill wave failed")
            for item in grp:
                self._fail_item(item, f"prefill failed: {e}")
            return
        for i, item in enumerate(grp):
            self._ship_item(item, rows[i], lg_host[i], lengths[i])

    def _ship_item(self, item: _PrefillItem, bufs: dict, logits,
                   length: int) -> None:
        import jax

        if item.cancelled:
            # a CANCEL caught this prompt mid-wave: the prefill compute
            # is sunk, but the row must NOT ship — nothing downstream
            # would ever speak for the rid (the decode tier drops
            # tombstoned packages), so the terminal frame is ours
            with self._cv:
                self._items.pop((item.conn.id, item.rid), None)
                item.done = True
            item.span.end(reason="cancelled")
            item.conn.send(P.RETIRED, item.rid, P.pack_json(
                {"reason": "cancelled", "tokens": 0}))
            return
        t0 = time.perf_counter()
        ship_span = tracing.get_tracer().start_span("kv.ship",
                                                    parent=item.span,
                                                    decode=item.decode)
        key = np.asarray(jax.random.fold_in(self._base_key,
                                            item.stream), np.uint32)
        ctx = item.span.context if item.span.recording else None
        meta = kvship.pack_kv_meta(item.rid, item.budget, length, key,
                                   rng_off=item.rng_off, cls=item.cls,
                                   trace=ctx)
        blob = kvship.pack_shipment(meta, dict(bufs, logits=logits))
        try:
            # sync: HANDOFF transfers the session's fate to the decode
            # gang, so it must not be sent until the gang ACKED the
            # package — an async "success" can be a frame parked in the
            # send window of a dying endpoint, lost with no owner
            self._sender_for(item.decode).send_bytes(
                blob, sync=True, timeout=self.ship_timeout_s)
        except ChannelError as e:
            # the decode gang is unreachable: evict the sender (its seq
            # state would mismatch a restarted hub) and fail the
            # request RETRYABLE — the router re-places it toward a
            # different decode replica instead of erroring the client
            with self._senders_lock:
                s = self._senders.pop(item.decode, None)
            if s is not None:
                s.close(drain=False)
            ship_span.end(error=str(e)[:200])
            self._fail_item(item, f"kv ship to {item.decode} failed: {e}",
                            retryable=True)
            return
        wall = time.perf_counter() - t0
        self._ship_h.observe(wall)
        self._ship_bytes_c.inc(len(blob))
        self._reqs_c.inc()
        ship_span.end(bytes=len(blob))
        item.span.end(reason="handed_off")
        with self._cv:
            self._items.pop((item.conn.id, item.rid), None)
            item.done = True
        item.conn.send(P.HANDOFF, item.rid, P.pack_json(
            {"decode": item.decode, "bytes": len(blob),
             "wall_s": round(wall, 6)}))

    def _fail_item(self, item: _PrefillItem, message: str,
                   retryable: bool = False) -> None:
        """Fail one request back to the submitter. ``retryable`` marks
        a placement fault (the named decode gang unreachable), not a
        request fault — the router re-places those on another decode
        replica instead of surfacing the error to the client."""
        with self._cv:
            self._items.pop((item.conn.id, item.rid), None)
            item.done = True
        item.span.end(reason="error")
        body = {"message": message}
        if retryable:
            body["retryable"] = True
        item.conn.send(P.ERROR, item.rid, P.pack_json(body))

    def _sender_for(self, addr: str) -> ChannelSender:
        with self._senders_lock:
            sender = self._senders.get(addr)
            if sender is None:
                sender = ChannelSender(addr, KV_CHANNEL,
                                       window=self.channel_window,
                                       registry=self._reg)
                self._senders[addr] = sender
            return sender


class DecodeServer(WeightHost, FrameServerBase):
    """The decode tier of disaggregated serving: a
    :class:`~tony_tpu.models.serve.ServeEngine` whose admissions arrive
    as KV shipments through a :class:`ChannelHub` instead of as ADMIT
    prompts — landing is a scatter, so decode chunks are never
    preempted by prefill compute (see module docstring).

    Wire surface: ``BIND`` declares the delta sink (the router's link;
    last BIND wins), ``CANCEL``/``STATS`` work as on a colocated
    server, and ``ADMIT`` is refused — prompts belong at the prefill
    tier. The HELLO advertises ``channel_port`` (or
    ``channel_advertise`` when the hub sits behind NAT/a proxy) so the
    router can hand prefill replicas this gang's shipment endpoint."""

    def __init__(self, batcher, *, bind_host: str = "127.0.0.1",
                 port: int = 0, channel_port: int = 0,
                 channel_capacity: int = 8,
                 channel_advertise: int | None = None,
                 registry=None,
                 weights_version: str | None = None,
                 weights_digest: str | None = None,
                 class_floors: dict | None = None,
                 latency_buckets=None) -> None:
        super().__init__(bind_host, port)
        from tony_tpu.models.serve import ServeEngine

        self.weights_version = weights_version
        #: content digest of the served weight tree (computed at
        #: start() when not given) — see the colocated server
        self.weights_digest = weights_digest

        if getattr(batcher, "d_cache", None) is not None:
            raise ValueError(
                "speculative serving is not supported in disaggregated "
                "mode (the KV shipment carries no draft-model cache)")
        if batcher.shared_prefix is not None:
            raise ValueError(
                "shared-prefix serving stays colocated (prefix "
                "templates do not ride the KV shipment)")
        self.batcher = batcher
        self._reg = registry or metrics_mod.get_default()
        # no max_queue_depth here: the decode tier never sheds a
        # landed package — the prefill work is already paid; overload
        # is refused upstream where refusing is still free
        self.engine = ServeEngine(batcher, on_delta=self._on_delta,
                                  on_retired=self._on_retired,
                                  registry=registry,
                                  class_floors=class_floors,
                                  latency_buckets=latency_buckets)
        self.hub = ChannelHub(port=channel_port,
                              capacity=channel_capacity,
                              registry=self._reg)
        self.channel_advertise = channel_advertise
        self._lock = threading.Lock()
        self._sink: FrameConn | None = None
        #: rids cancelled before their shipment landed: a late-arriving
        #: package for one is DROPPED, not adopted into a slot that
        #: would generate into the void (bounded — old tombstones age
        #: out; a rid reused after 4096 later cancels is a router bug)
        self._tombstones: OrderedDict[int, bool] = OrderedDict()
        self._engine_thread: threading.Thread | None = None
        self._land_thread: threading.Thread | None = None
        self._land_h = self._reg.histogram(
            "tony_kv_land_seconds",
            help="KV handoff wall per request, decode side: unpack + "
                 "validate + engine adoption")
        self._idle_g = self._reg.gauge(
            "tony_decode_idle_slots",
            help="decode slots with no live occupant (awaiting KV "
                 "arrivals — the decode tier's headroom signal)")
        self._idle_g.set(batcher.batch)
        # weights lane multiplexes on the KV hub (kind-tagged blobs:
        # a shipment cannot be misread as an artifact or vice versa)
        self._init_weight_host(
            self._reg, exporter=lambda: pack_weights(
                self.batcher.params, version=self.weights_version),
            hub=self.hub)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        if self.weights_digest is None:
            try:
                self.weights_digest = tree_digest(self.batcher.params)
            except Exception as e:          # noqa: BLE001 — advisory
                log.warning("weights digest not computed: %s", e)
        self._engine_thread = threading.Thread(
            target=self.engine.run, name="tony-decode-engine",
            daemon=True)
        self._engine_thread.start()
        self.hub.start()
        self._start_weight_host()
        self._land_thread = threading.Thread(
            target=self._land_loop, name="tony-decode-land", daemon=True)
        self._land_thread.start()
        port = super().start()
        log.info("decode tier on %s:%s (%d slots; kv channel on :%s)",
                 self.bind_host, port, self.batcher.batch, self.hub.port)
        return port

    def stop(self, drain: bool = False,
             drain_timeout_s: float = 600.0) -> None:
        self._close_listener()
        if drain:
            self.engine.drain()
        else:
            self._stopping.set()
            self.engine.stop()
        if self._engine_thread is not None:
            self._engine_thread.join(
                timeout=drain_timeout_s if drain else 60)
            if self._engine_thread.is_alive():
                log.warning("decode tier: engine did not %s; aborting",
                            "drain" if drain else "stop")
                self.engine.stop()
                self._engine_thread.join(timeout=60)
        self._stopping.set()
        self.hub.stop()
        self._stop_weight_host()
        if self._land_thread is not None:
            self._land_thread.join(timeout=10)
        self._close_conns()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def kill(self) -> None:
        """Abrupt replica loss: sever everything first (the router sees
        EOF immediately), then abort the engine — the disaggregated
        failover drill."""
        self._stopping.set()
        self._close_listener()
        self._close_conns()
        self.hub.stop()
        self._stop_weight_host()
        self.engine.stop()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=60)
        if self._land_thread is not None:
            self._land_thread.join(timeout=10)

    # -- frame handling (reader threads) ------------------------------------
    def _hello_payload(self) -> dict:
        return {"v": 1, "role": "decode", "slots": self.batcher.batch,
                "channel_port": (self.channel_advertise
                                 if self.channel_advertise is not None
                                 else self.hub.port),
                "weights_version": self.weights_version,
                "weights_digest": self.weights_digest,
                "weight_port": self.weight_port,
                "weights_resident": self.weight_store.resident_digests()}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype == P.BIND:
            with self._lock:
                self._sink = conn
        elif ftype == P.CANCEL:
            with self._lock:
                self._tombstones[rid] = True
                while len(self._tombstones) > 4096:
                    self._tombstones.popitem(last=False)
            self.engine.cancel(rid)
        elif ftype == P.STATS:
            st = dict(self.engine.stats(), role="decode",
                      channel_port=self.hub.port,
                      weights_version=self.weights_version,
                      weights_digest=self.weights_digest,
                      weights_resident=self.weight_store.resident_digests())
            conn.send(P.STATS, 0, P.pack_json(st))
        elif ftype == P.WEIGHTS:
            self._handle_weights_frame(conn, rid, payload)
        elif ftype in (P.ADMIT, P.POLL):
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": "decode tier takes KV shipments, not "
                            "prompts — ADMIT at the prefill tier"}))
        else:
            raise P.ProtocolError(
                f"unexpected frame type {P.FRAME_NAMES.get(ftype, ftype)}"
                f" at the decode tier")

    def _on_conn_closed(self, conn: FrameConn) -> None:
        """Sink loss == our front door died: cancel every live adopted
        request so its slot frees (the router re-admits each stream
        through a surviving path; generating into a dead link helps
        no one)."""
        with self._lock:
            was_sink = self._sink is conn
            if was_sink:
                self._sink = None
        if was_sink:
            for rid in self.engine.live_requests():
                self.engine.cancel(rid)

    # -- the landing thread -------------------------------------------------
    def _land_loop(self) -> None:
        receiver = self.hub.receiver(KV_CHANNEL)
        while not self._stopping.is_set():
            try:
                blob = receiver.recv_bytes(timeout=0.25)
            except ChannelClosed:
                # hub stopped: nothing can EVER arrive again on this
                # receiver — exit, instead of hot-spinning on instant
                # failures and starving the engine + frame threads
                return
            except ChannelError:
                continue                    # timeout; re-check stopping
            except P.ProtocolError as e:
                log.warning("decode tier: non-shipment channel frame "
                            "dropped: %s", e)
                continue
            try:
                self._land(blob)
            except Exception as e:      # noqa: BLE001 — thread survival
                # a malformed shipment must cost only ITSELF, never the
                # landing thread (a dead lander silently starves every
                # future adoption)
                log.exception("decode tier: KV shipment landing failed; "
                              "dropped")
                tracing.get_flight().record("kv_shipment_rejected",
                                            error=str(e)[:500])

    def _land(self, blob: bytes) -> None:
        from tony_tpu.models.serve import KVPackage

        t0 = time.perf_counter()
        try:
            meta, bufs = kvship.unpack_shipment(blob)
            meta = kvship.parse_kv_meta(meta)
            logits = bufs.pop("logits", None)
            if logits is None or logits.ndim != 1:
                raise P.ProtocolError("shipment carries no [V] logits")
        except (P.ProtocolError, ValueError) as e:
            log.warning("decode tier: malformed KV shipment dropped: %s",
                        e)
            tracing.get_flight().record("kv_shipment_rejected",
                                        error=str(e)[:500])
            return
        rid = meta["rid"]
        with self._lock:
            dropped = self._tombstones.pop(rid, None)
        if dropped:
            # cancelled before arrival: drop the package — but the
            # cancel still needs its terminal frame, and nothing else
            # will ever speak for this rid (the engine never saw it)
            self._push(rid, [(P.RETIRED, P.pack_json(
                {"reason": "cancelled", "tokens": 0}))])
            return
        pkg = KVPackage(bufs, meta["length"], logits, meta["rng"],
                        meta["rng_off"])
        trace_ctx = (P.parse_trace_ctx({"trace": meta["trace"]})
                     if "trace" in meta else None)
        try:
            self.engine.submit_prefilled(rid, pkg, meta["budget"],
                                         trace_ctx=trace_ctx,
                                         request_class=meta["class"])
        except (ValueError, RuntimeError) as e:
            log.warning("decode tier: shipment for rid %s rejected: %s",
                        rid, e)
            self._push(rid, [(P.ERROR,
                              P.pack_json({"message": str(e)}))])
            return
        with self._lock:
            # a CANCEL racing this landing can tombstone + engine-cancel
            # BETWEEN the tombstone check above and the submit — its
            # engine.cancel no-oped (the rid was not admitted yet), so
            # re-check now that it is: the cancel must win, not a full
            # budget streamed to a client that asked for death
            cancelled_late = self._tombstones.pop(rid, None)
        if cancelled_late:
            self.engine.cancel(rid)
            return
        self._land_h.observe(time.perf_counter() - t0)
        self._update_idle()

    # -- engine callbacks ---------------------------------------------------
    def _update_idle(self) -> None:
        st = self.engine.stats()
        self._idle_g.set(max(0, st["slots"] - st["active"]))

    def _push(self, rid: int, frames: list) -> None:
        with self._lock:
            sink = self._sink
        if sink is None:
            return
        if not sink.send_many([(t, rid, p) for t, p in frames]):
            # close WITHOUT clearing _sink: the conn's reader thread
            # fires _on_conn_closed, which must still see this conn AS
            # the sink to run its live-request cancel sweep — clearing
            # first would skip the sweep and leave every adopted row
            # generating into the void
            sink.close()

    def _on_delta(self, rid, toks) -> None:
        self._push(rid, [(P.TOKENS, P.pack_tokens(toks))])

    def _on_retired(self, rid, reason: str, n_tokens: int,
                    final_tokens) -> None:
        frames = []
        if final_tokens:
            # the final delta and the retirement share one kernel write
            # (the colocated server's atomic-final contract — what the
            # router's failover reads an unfinished stream off)
            frames.append((P.TOKENS, P.pack_tokens(final_tokens)))
        frames.append((P.RETIRED, P.pack_json(
            {"reason": reason, "tokens": n_tokens})))
        self._push(rid, frames)
        self._update_idle()
