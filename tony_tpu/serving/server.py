"""Streaming serving server: one persistent connection per client, one
:class:`~tony_tpu.models.serve.ServeEngine` per server.

The pre-streaming serving path paid a transport round trip per chunk
and per admission (request/response against the device tunnel — ~70-100
ms each, THE serving bottleneck once the loop itself was pipelined).
Here the engine runs in one thread, each connection gets one reader
thread feeding admissions/cancels straight into the engine's live
queue, and the engine's delta callbacks push TOKENS frames the moment a
chunk is consumed — transport overlaps device compute end-to-end, and
one connection multiplexes any number of in-flight requests.

Robustness contract (test-enforced):

- a malformed or truncated frame is CONNECTION-scoped: the offender
  gets a best-effort ``ERROR`` (rid 0) and a clean close; the server
  and every other connection keep serving;
- an un-servable ADMIT (bad budget, prompt too long, duplicate rid) is
  REQUEST-scoped: ``ERROR`` with that rid, connection stays up;
- a client disconnect cancels all its in-flight requests — their cache
  slots free at the next consumed chunk and readmit from the queue;
- ``CANCEL`` racing retirement is idempotent (engine contract).

``stop(drain=True)`` is the graceful path: no new connections or
admissions, in-flight requests finish and stream out, then the engine
thread exits. ``kill()`` severs client connections first (peers see
EOF immediately) and aborts the engine — the router's replica-loss
drill.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading

from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.serving import protocol as P
from tony_tpu.serving.prefix import PrefixHost, fingerprint
from tony_tpu.serving.weightstore import WeightHost, pack_weights, \
    tree_digest

log = logging.getLogger(__name__)


class FrameConn:
    """One accepted connection: socket + serialized writes. Engine
    callbacks, poll responses, and error replies may send from
    different threads — ``send`` takes the per-connection lock and
    reports (rather than raises) transport failure."""

    def __init__(self, conn_id: int, sock: socket.socket, addr) -> None:
        self.id = conn_id
        self.sock = sock
        self.addr = addr
        self._send_lock = threading.Lock()
        self.alive = True

    def send(self, ftype: int, rid: int, payload: bytes = b"") -> bool:
        return self.send_many([(ftype, rid, payload)])

    def send_many(self, frames) -> bool:
        """Write several frames in ONE sendall — a retiring request's
        final TOKENS and its RETIRED frame share a kernel write, so a
        process killed between them cannot deliver one without the
        other (the router's failover reads an unfinished stream off
        exactly that gap)."""
        buf = b"".join(P.encode_frame(t, r, p) for t, r, p in frames)
        with self._send_lock:
            if not self.alive:
                return False
            try:
                self.sock.sendall(buf)
                return True
            except OSError:
                self.alive = False
                return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class FrameServerBase:
    """Accept loop + per-connection frame reader for the TONYS1
    protocol. Subclasses implement ``_hello_payload()``,
    ``_handle_frame(conn, ftype, rid, payload)`` (raise
    :class:`~tony_tpu.serving.protocol.ProtocolError` for
    connection-scoped violations), and ``_on_conn_closed(conn)``."""

    def __init__(self, bind_host: str = "127.0.0.1", port: int = 0) -> None:
        self.bind_host = bind_host
        self.port = port
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: dict[int, FrameConn] = {}
        self._conn_ids = itertools.count(1)
        self._conns_lock = threading.Lock()
        self._stopping = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((self.bind_host, self.port))
        server.listen(64)
        self.port = server.getsockname()[1]
        self._listener = server
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tony-serve-accept", daemon=True)
        self._accept_thread.start()
        return self.port

    def _close_listener(self) -> None:
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass

    def _close_conns(self) -> None:
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            conn.close()

    # -- accept / read ------------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._stopping.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                break                       # listener closed by stop()
            P.set_nodelay(sock)
            conn = FrameConn(next(self._conn_ids), sock, addr)
            with self._conns_lock:
                self._conns[conn.id] = conn
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"tony-serve-conn-{conn.id}",
                             daemon=True).start()

    def _serve_conn(self, conn: FrameConn) -> None:
        try:
            if not P.read_magic(conn.sock):
                log.warning("serving: %s sent no TONYS1 magic; closing",
                            conn.addr)
                return
            conn.send(P.HELLO, 0, P.pack_json(self._hello_payload()))
            while not self._stopping.is_set():
                frame = P.recv_frame(conn.sock)
                if frame is None:
                    break                   # clean disconnect
                self._handle_frame(conn, *frame)
        except P.ProtocolError as e:
            # connection-scoped: report, close THIS connection, keep
            # serving everyone else — and leave a postmortem artifact
            # scoped to the OFFENDING connection (the flight recorder's
            # final entries name it; healthy connections dump nothing)
            log.warning("serving: protocol error from %s: %s",
                        conn.addr, e)
            from tony_tpu.runtime import tracing
            flight = tracing.get_flight()
            flight.record("protocol_error", conn=conn.id,
                          addr=str(conn.addr), error=str(e)[:500])
            flight.dump("protocol_error", conn=conn.id,
                        addr=str(conn.addr))
            conn.send(P.ERROR, 0, P.pack_json({"message": str(e)}))
        except OSError:
            pass                            # connection reset under us
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.pop(conn.id, None)
            self._on_conn_closed(conn)

    # -- subclass surface ---------------------------------------------------
    def _hello_payload(self) -> dict:
        raise NotImplementedError

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        raise NotImplementedError

    def _on_conn_closed(self, conn: FrameConn) -> None:
        raise NotImplementedError


class _Session:
    """Server-side request state. ``stream=True`` pushes deltas as they
    land; ``stream=False`` buffers them for long-POLLs (the
    request/response contrast the streaming arm is measured against)."""

    __slots__ = ("conn", "rid", "stream", "buffer", "retired",
                 "poll_pending")

    def __init__(self, conn: FrameConn, rid: int, stream: bool) -> None:
        self.conn = conn
        self.rid = rid
        self.stream = stream
        self.buffer: list[int] = []
        self.retired: tuple[str, int] | None = None
        self.poll_pending = False


class ServingServer(WeightHost, PrefixHost, FrameServerBase):
    """Drive a batcher's :class:`~tony_tpu.models.serve.ServeEngine`
    behind the TONYS1 streaming protocol.

    Usage::

        server = ServingServer(batcher, port=0)
        port = server.start()          # engine + accept threads
        ...
        server.stop(drain=True)        # finish in-flight, then exit

    PREFIX-AWARE serving (docs/serving.md §Prefix-aware routing): the
    server is a :class:`~tony_tpu.serving.prefix.PrefixHost` — its
    HELLO and STATS advertise the batcher's resident prefix templates
    (and the template lane's ``prefix_port``), ``PREFIX`` frames carry
    install/publish/list ops, and a peer's published template lands
    through the lane into the batcher's store with zero prefill
    forwards. ADMITs naming (or auto-matching) a resident prefix run
    only their suffix through the model.
    """

    def __init__(self, batcher, bind_host: str = "127.0.0.1",
                 port: int = 0, registry=None,
                 weights_version: str | None = None,
                 weights_digest: str | None = None,
                 class_floors: dict | None = None,
                 max_queue_depth: int = 128,
                 busy_retry_ms: int = 250,
                 latency_buckets=None) -> None:
        super().__init__(bind_host, port)
        from tony_tpu.models.serve import ServeEngine
        self.batcher = batcher
        #: the model-weights generation this replica serves, advertised
        #: in HELLO and STATS — what the router's version-pinned
        #: placement (rolling upgrades) keys on. None = unversioned.
        self.weights_version = weights_version
        #: the content digest of the served weight tree (computed at
        #: start() when not given) — the version-pinning fallback for
        #: unversioned fleets, and the name peers pull this replica's
        #: artifact by (warm scale-up).
        self.weights_digest = weights_digest
        self._lock = threading.Lock()
        self._sessions: dict[tuple[int, int], _Session] = {}
        self.engine = ServeEngine(batcher, on_delta=self._on_delta,
                                  on_retired=self._on_retired,
                                  registry=registry,
                                  class_floors=class_floors,
                                  max_queue_depth=max_queue_depth,
                                  busy_retry_ms=busy_retry_ms,
                                  latency_buckets=latency_buckets)
        self._engine_thread: threading.Thread | None = None
        reg = registry or metrics_mod.get_default()
        self._init_prefix_host(reg)
        # the weights lane shares the prefix hub's port (blobs are
        # kind-tagged: neither lane can misread the other's); the
        # exporter lazily packs the live params the first time a peer
        # (or the fleet) asks to seed from this replica
        self._init_weight_host(reg, exporter=self._export_weights_blob,
                               hub=self._prefix_hub)

    # -- resident prefix templates (PrefixHost hooks) -----------------------
    def install_prefix(self, tokens, prefix_id: str | None = None):
        """Compute ``tokens``' K/V template locally and make it
        resident; returns the prefix id (content fingerprint unless
        given), or None when the batcher degraded prefix-blind (ring
        layout)."""
        pid = prefix_id or fingerprint(tokens)
        return pid if self.batcher.install_prefix(pid, tokens) else None

    def install_prefix_template(self, meta, bufs) -> str:
        return self.batcher.install_prefix_template(meta, bufs)

    def resident_prefixes(self) -> list:
        return self.batcher.resident_prefixes()

    def _prefix_blob(self, prefix_id: str) -> bytes:
        return self.batcher.export_prefix_blob(prefix_id)

    # -- the seedable weight artifact (WeightHost exporter) -----------------
    def _export_weights_blob(self) -> bytes:
        return pack_weights(self.batcher.params,
                            version=self.weights_version)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        if self.weights_digest is None:
            try:
                self.weights_digest = tree_digest(self.batcher.params)
            except Exception as e:          # noqa: BLE001 — advisory
                log.warning("weights digest not computed: %s", e)
        self._engine_thread = threading.Thread(
            target=self.engine.run, name="tony-serve-engine", daemon=True)
        self._engine_thread.start()
        self._start_prefix_host()
        self._start_weight_host()
        port = super().start()
        log.info("serving on %s:%s (%d slots; prefix lane on :%s)",
                 self.bind_host, port, self.batcher.batch,
                 self.prefix_port)
        return port

    def stop(self, drain: bool = False,
             drain_timeout_s: float = 600.0) -> None:
        """Stop serving. ``drain=True`` finishes every accepted request
        (clients keep receiving deltas — and may keep CANCELing /
        POLLing / STATSing while the drain runs; only ``_stopping`` is
        deferred, because setting it would make a connection's next
        frame exit its reader loop and cancel that client's in-flight
        streams mid-drain); ``drain=False`` aborts — outstanding
        requests retire as ``"stopped"``. A drain that outlives
        ``drain_timeout_s`` is escalated to an abort, LOUDLY — a silent
        degradation would sever clients the caller believes drained."""
        self._close_listener()              # no new connections
        if drain:
            self.engine.drain()
        else:
            self._stopping.set()
            self.engine.stop()
        if self._engine_thread is not None:
            self._engine_thread.join(
                timeout=drain_timeout_s if drain else 60)
            if self._engine_thread.is_alive():
                log.warning(
                    "serving: engine did not %s within %.0fs; aborting "
                    "outstanding requests",
                    "drain" if drain else "stop",
                    drain_timeout_s if drain else 60)
                self.engine.stop()
                self._engine_thread.join(timeout=60)
        self._stopping.set()
        self._stop_prefix_host()
        self._stop_weight_host()
        self._close_conns()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def kill(self) -> None:
        """Abrupt replica loss: sever client connections FIRST (peers
        see EOF immediately — what a crashed host looks like), then
        abort the engine."""
        self._stopping.set()
        self._close_listener()
        self._close_conns()
        self._stop_prefix_host()
        self._stop_weight_host()
        self.engine.stop()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=60)

    # -- frame handling (reader threads) ------------------------------------
    def _hello_payload(self) -> dict:
        # "role" lets a disaggregation-aware router sanity-check what
        # it connected to (a colocated engine serves prompts end-to-end);
        # "prefixes"/"ring"/"prefix_port" seed residency-aware routing
        return {"v": 1, "slots": self.batcher.batch, "role": "engine",
                "prefixes": self.batcher.resident_prefixes(),
                "ring": self.batcher._ring,
                "prefix_port": self.prefix_port,
                "weights_version": self.weights_version,
                "weights_digest": self.weights_digest,
                "weight_port": self.weight_port,
                "weights_resident": self.weight_store.resident_digests()}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype == P.ADMIT:
            self._admit(conn, rid, payload)
        elif ftype == P.CANCEL:
            self.engine.cancel((conn.id, rid))
        elif ftype == P.POLL:
            self._poll(conn, rid)
        elif ftype == P.STATS:
            conn.send(P.STATS, 0, P.pack_json(dict(
                self.engine.stats(),
                prefixes=self.batcher.resident_prefixes(),
                ring=self.batcher._ring,
                weights_version=self.weights_version,
                weights_digest=self.weights_digest,
                weights_resident=self.weight_store.resident_digests())))
        elif ftype == P.PREFIX:
            self._handle_prefix_frame(conn, rid, payload)
        elif ftype == P.WEIGHTS:
            self._handle_weights_frame(conn, rid, payload)
        else:
            raise P.ProtocolError(
                f"unexpected frame type {P.FRAME_NAMES.get(ftype, ftype)}")

    def _admit(self, conn: FrameConn, rid: int, payload: bytes) -> None:
        # structural violations are connection-scoped (raise), an
        # un-servable request is request-scoped (ERROR with its rid)
        prompt, max_new, stream = P.parse_admit(payload)
        trace_ctx = P.parse_trace_ctx(payload)
        prefix_id = P.parse_prefix_id(payload)
        rng = P.parse_rng(payload)
        if rid == 0:
            raise P.ProtocolError("ADMIT rid must be nonzero")
        try:
            # absent = "standard" (old wires unchanged); an UNKNOWN
            # class is a request-scoped error — the client asked for a
            # tier that does not exist and must hear "no", not silently
            # serve at a different one
            cls = P.parse_class(payload)
        except ValueError as e:
            conn.send(P.ERROR, rid, P.pack_json({"message": str(e)}))
            return
        key = (conn.id, rid)
        # the duplicate-rid reply is sent AFTER the lock is dropped: a
        # frame send can block on a slow client socket, and this lock
        # serializes admission/poll for every connection (TL001)
        with self._lock:
            duplicate = key in self._sessions
            if not duplicate:
                self._sessions[key] = _Session(conn, rid, stream)
        if duplicate:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": f"request id {rid} is already active"}))
            return
        # local import: models.serve pulls in jax, and this module must
        # stay importable without it (router/simfleet/daemon only want
        # FrameConn); by the time a request is admitted the engine --
        # and therefore jax -- is already loaded
        from tony_tpu.models.serve import EngineBusy
        try:
            self.engine.submit(key, prompt, max_new, trace_ctx=trace_ctx,
                               prefix_id=prefix_id, rng=rng,
                               request_class=cls)
        except EngineBusy as e:
            # the explicit shed: terminal for this rid, a statement
            # about LOAD — the client re-admits after the hint
            with self._lock:
                self._sessions.pop(key, None)
            conn.send(P.BUSY, rid, P.pack_json(
                {"retry_after_ms": e.retry_after_ms}))
        except (ValueError, RuntimeError) as e:
            with self._lock:
                self._sessions.pop(key, None)
            conn.send(P.ERROR, rid, P.pack_json({"message": str(e)}))

    def _poll(self, conn: FrameConn, rid: int) -> None:
        key = (conn.id, rid)
        reply = None
        with self._lock:
            sess = self._sessions.get(key)
            if sess is None:
                reply = (P.ERROR, P.pack_json(
                    {"message": f"unknown request id {rid}"}))
            elif sess.buffer:
                toks, sess.buffer = sess.buffer, []
                reply = (P.TOKENS, P.pack_tokens(toks))
            elif sess.retired is not None:
                reason, n = sess.retired
                del self._sessions[key]
                reply = (P.RETIRED,
                         P.pack_json({"reason": reason, "tokens": n}))
            else:
                sess.poll_pending = True    # answered when data lands
        if reply is not None:
            conn.send(reply[0], rid, reply[1])

    def _on_conn_closed(self, conn: FrameConn) -> None:
        """A disconnected client's requests are cancelled — their slots
        free at the next consumed chunk and readmit from the queue."""
        with self._lock:
            doomed = [key for key, s in self._sessions.items()
                      if s.conn is conn]
            for key in doomed:
                del self._sessions[key]
        for key in doomed:
            self.engine.cancel(key)

    # -- engine callbacks (engine thread; cancels: any thread) --------------
    def _on_delta(self, key, toks) -> None:
        reply = None
        with self._lock:
            sess = self._sessions.get(key)
            if sess is None:
                return                      # late delta for a dead session
            if sess.stream:
                reply = (sess.conn, P.TOKENS, sess.rid,
                         P.pack_tokens(toks))
            else:
                sess.buffer.extend(int(t) for t in toks)
                if sess.poll_pending:
                    sess.poll_pending = False
                    buf, sess.buffer = sess.buffer, []
                    reply = (sess.conn, P.TOKENS, sess.rid,
                             P.pack_tokens(buf))
        if reply is not None and not reply[0].send(*reply[1:]):
            self._drop_dead_conn(reply[0])

    def _on_retired(self, key, reason: str, n_tokens: int,
                    final_tokens) -> None:
        conn = None
        frames: list = []
        with self._lock:
            sess = self._sessions.get(key)
            if sess is None:
                return
            conn = sess.conn
            body = P.pack_json({"reason": reason, "tokens": n_tokens})
            if sess.stream:
                del self._sessions[key]
                # the final delta and the retirement go out in ONE
                # write (see FrameConn.send_many)
                if final_tokens:
                    frames.append((P.TOKENS, sess.rid,
                                   P.pack_tokens(final_tokens)))
                frames.append((P.RETIRED, sess.rid, body))
            else:
                sess.buffer.extend(int(t) for t in final_tokens)
                sess.retired = (reason, n_tokens)
                if sess.poll_pending:
                    sess.poll_pending = False
                    if sess.buffer:
                        buf, sess.buffer = sess.buffer, []
                        frames.append((P.TOKENS, sess.rid,
                                       P.pack_tokens(buf)))
                    else:
                        del self._sessions[key]
                        frames.append((P.RETIRED, sess.rid, body))
        if frames and not conn.send_many(frames):
            self._drop_dead_conn(conn)

    def _drop_dead_conn(self, conn: FrameConn) -> None:
        """A send failed mid-stream: the peer is gone. Close the socket
        so its reader thread unblocks and runs the disconnect cleanup
        (cancel + slot free)."""
        conn.close()
