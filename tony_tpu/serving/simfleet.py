"""Deterministic simulated serving fleet: the chaos harness's model.

A :class:`SimReplica` speaks the full TONYS1 replica surface (HELLO
with slots/weights_version, ADMIT honoring the router's ``rng`` pin,
streamed TOKENS at an injected inter-token compute floor, atomic
TOKENS+RETIRED finals, CANCEL, STATS pings) with NO model stack — the
"generation" is a pure position-indexed token oracle,
:func:`sim_token`. That makes fleet-scale behavior testable exactly:

- any observer who knows a session's prompt can compute the ONE
  correct token sequence, so zero-dup/zero-drop across any number of
  migrations, failovers, and crashes is a strict equality check —
  the oracle keys on the prompt's first token and the ABSOLUTE
  position (the rng offset plus tokens emitted), which is precisely
  the contract the router's rng pin promises a real sampled engine
  reproduces;
- a 100-replica fleet runs in one process in milliseconds of wall
  time per token, so migration storms (drain 30 replicas at once) and
  seeded crash/drain chaos mixes are tier-1-affordable at small scale
  and @slow at full scale (tests/test_fleet.py);
- :class:`SimFleet` wires N replicas behind a real
  :class:`~tony_tpu.serving.router.ServingRouter` (real sockets, real
  frames — only the model is simulated) and exposes kill/spawn/reap
  for chaos and autoscale (:class:`SimProvider` plugs into
  :class:`~tony_tpu.serving.fleet.FleetController`).

This is the serving twin of the bench's ``_disagg_arm`` pattern
(LatencyProxy + injected compute floors instead of real math), promoted
from a bench trick to a first-class harness.
"""

from __future__ import annotations

import collections
import threading
import time

from tony_tpu.serving import protocol as P
from tony_tpu.serving.server import FrameConn, FrameServerBase


def sim_token(seed: int, pos: int) -> int:
    """The simulated model: token at absolute position ``pos`` of the
    stream seeded by ``seed`` (a session's first prompt token). Pure,
    stateless, collision-scrambled — any two (seed, pos) pairs disagree
    enough that a dup/drop/cross-session mixup cannot pass the equality
    check by accident. Values stay under 2**30 (engine token range)."""
    x = (seed & 0x3FFFFF) * 1315423911 + pos * 2654435761 + 97531
    x ^= x >> 13
    return x & 0x3FFFFFFF


class _SimSession:
    __slots__ = ("conn", "rid", "seed", "off", "emitted", "max_new",
                 "ready_at", "cls")

    def __init__(self, conn: FrameConn, rid: int, seed: int, off: int,
                 max_new: int, ready_at: float,
                 cls: str = "standard") -> None:
        self.conn = conn
        self.rid = rid
        self.seed = seed
        self.off = off                      # rng offset: tokens already
        self.emitted = 0                    # delivered by PRIOR placements
        self.max_new = max_new
        self.ready_at = ready_at
        self.cls = cls


class SimReplica(FrameServerBase):
    """One simulated serving replica. ``itl_s`` is the injected
    inter-token compute floor (one pump tick emits one token per live
    session); ``ttft_s`` the injected prefill floor before a session's
    first token. ``kill()`` is a crash: listener and every connection
    sever mid-stream, no goodbye frames."""

    def __init__(self, bind_host: str = "127.0.0.1", port: int = 0,
                 itl_s: float = 0.002, ttft_s: float = 0.0,
                 slots: int = 16, weights_version: str | None = None,
                 max_queue_depth: int = 128,
                 busy_retry_ms: int = 50) -> None:
        super().__init__(bind_host, port)
        self.itl_s = itl_s
        self.ttft_s = ttft_s
        self.slots = slots
        self.weights_version = weights_version
        # overload discipline mirrors the real engine: admissions past
        # ``slots`` wait in per-class queues, interactive waiters may
        # preempt a decoding batch row (demoted back to its queue, oracle
        # positions intact), and non-interactive admissions past
        # ``max_queue_depth`` waiting sessions are shed with BUSY
        self.max_queue_depth = max_queue_depth
        self.busy_retry_ms = busy_retry_ms
        self._slock = threading.Lock()
        self._sessions: dict = {}           # (conn.id, rid) -> _SimSession
        # waiting (not-yet-decoding) sessions per class, FIFO within one
        self._waitq: dict = {c: collections.deque() for c in P.QOS_CLASSES}
        self.preemptions = 0                # batch rows evicted-to-queue
        self._pump_thread: threading.Thread | None = None
        self.addr = ""

    def start(self) -> int:
        port = super().start()
        self.addr = f"{self.bind_host}:{port}"
        self._pump_thread = threading.Thread(
            target=self._pump_loop, name=f"tony-sim-pump-{port}",
            daemon=True)
        self._pump_thread.start()
        return port

    # -- replica protocol surface --------------------------------------------
    def _hello_payload(self) -> dict:
        return {"v": 1, "role": "engine", "slots": self.slots,
                "weights_version": self.weights_version, "sim": True}

    def _stats_payload(self) -> dict:
        with self._slock:
            active = len(self._sessions)
            depths = {c: len(q) for c, q in self._waitq.items()}
        return {"queue_depth": sum(depths.values()), "active": active,
                "slots": self.slots, "queue_depths": depths,
                "weights_version": self.weights_version}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype == P.ADMIT:
            prompt, max_new, stream = P.parse_admit(payload)
            if rid == 0 or not stream or max_new <= 0 or not prompt:
                conn.send(P.ERROR, rid, P.pack_json(
                    {"message": "sim replica: bad ADMIT"}))
                return
            try:
                cls = P.parse_class(P.unpack_json(payload))
            except ValueError as e:
                conn.send(P.ERROR, rid, P.pack_json({"message": str(e)}))
                return
            rng = P.parse_rng(payload)
            off = rng[1] if rng is not None else 0
            # the oracle seed is the ORIGINAL prompt's first token:
            # folded-in streamed prefixes append, so it survives every
            # re-placement of the session
            sess = _SimSession(conn, rid, seed=prompt[0], off=off,
                               max_new=max_new, ready_at=0.0, cls=cls)
            shed = False
            with self._slock:
                waiting = sum(len(q) for q in self._waitq.values())
                if (self.max_queue_depth and cls != "interactive"
                        and waiting >= self.max_queue_depth):
                    shed = True
                else:
                    # all admissions queue; the pump grants slots in
                    # class order, so ready_at is stamped at grant time
                    self._waitq[cls].append(((conn.id, rid), sess))
            if shed:
                conn.send(P.BUSY, rid, P.pack_json(
                    {"retry_after_ms": self.busy_retry_ms}))
        elif ftype == P.CANCEL:
            with self._slock:
                sess = self._sessions.pop((conn.id, rid), None)
                if sess is None:
                    for q in self._waitq.values():
                        for i, (key, s) in enumerate(q):
                            if key == (conn.id, rid):
                                sess = s
                                del q[i]
                                break
                        if sess is not None:
                            break
            if sess is not None:
                conn.send(P.RETIRED, rid, P.pack_json(
                    {"reason": "cancelled", "tokens": sess.emitted}))
        elif ftype == P.STATS:
            conn.send(P.STATS, 0, P.pack_json(self._stats_payload()))
        else:
            raise P.ProtocolError(
                f"sim replica: unexpected frame "
                f"{P.FRAME_NAMES.get(ftype, ftype)}")

    def _on_conn_closed(self, conn: FrameConn) -> None:
        with self._slock:
            for key in [k for k in self._sessions if k[0] == conn.id]:
                self._sessions.pop(key, None)
            for q in self._waitq.values():
                kept = [(k, s) for (k, s) in q if k[0] != conn.id]
                q.clear()
                q.extend(kept)

    # -- the simulated engine ------------------------------------------------
    def _grant_locked(self, now: float) -> None:
        """Fill free decode slots from the wait queues in class-priority
        order; if interactive work still waits once every slot is held,
        evict the least-advanced decoding batch row back to the FRONT of
        its queue (emitted count intact — on re-grant the stream resumes
        at ``sim_token(seed, off + emitted)``: zero dup/drop by
        construction, exactly the engine's evict-to-queue semantics)."""
        for cls in P.QOS_CLASSES:
            q = self._waitq[cls]
            while q and len(self._sessions) < self.slots:
                key, sess = q.popleft()
                # prefill floor is paid at grant time (and paid AGAIN on
                # re-grant after a preemption, like a real re-prefill)
                sess.ready_at = now + self.ttft_s
                self._sessions[key] = sess
        iq = self._waitq["interactive"]
        while iq:
            batch = [(k, s) for k, s in self._sessions.items()
                     if s.cls == "batch"]
            if not batch:
                break
            key, victim = min(batch, key=lambda kv: kv[1].emitted)
            self._sessions.pop(key)
            self._waitq["batch"].appendleft((key, victim))
            self.preemptions += 1
            nk, ns = iq.popleft()
            ns.ready_at = now + self.ttft_s
            self._sessions[nk] = ns

    def _pump_loop(self) -> None:
        while not self._stopping.wait(self.itl_s):
            now = time.monotonic()
            with self._slock:
                self._grant_locked(now)
                items = list(self._sessions.items())
            for key, s in items:
                if now < s.ready_at:
                    continue
                tok = sim_token(s.seed, s.off + s.emitted)
                s.emitted += 1
                if s.emitted >= s.max_new:
                    with self._slock:
                        self._sessions.pop(key, None)
                    # final delta + retirement share one kernel write:
                    # a crash cannot deliver one without the other
                    s.conn.send_many([
                        (P.TOKENS, s.rid, P.pack_tokens([tok])),
                        (P.RETIRED, s.rid, P.pack_json(
                            {"reason": "budget", "tokens": s.emitted}))])
                else:
                    if not s.conn.send(P.TOKENS, s.rid,
                                       P.pack_tokens([tok])):
                        with self._slock:
                            self._sessions.pop(key, None)

    # -- lifecycle -----------------------------------------------------------
    def stop(self) -> None:
        self._stopping.set()
        self._close_listener()
        self._close_conns()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def kill(self) -> None:
        """Crash, not shutdown: sever everything mid-stream."""
        self.stop()


class SimFleet:
    """N simulated replicas behind a real router. ``start`` returns the
    router's client port. Chaos surface: :meth:`kill` (crash replica
    i), :meth:`spawn` (stand up a new replica and return its address —
    NOT yet routed; pair with ``router.add_replicas`` or use
    :class:`SimProvider`), :meth:`reap` (stop a spawned replica)."""

    def __init__(self, n: int, itl_s: float = 0.002,
                 ttft_s: float = 0.0, slots: int = 16,
                 weights_version: str | None = None,
                 health_interval_s: float = 0.1,
                 max_missed_pings: int = 3, registry=None,
                 max_queue_depth: int = 128,
                 busy_retry_ms: int = 50) -> None:
        self.n = n
        self.itl_s = itl_s
        self.ttft_s = ttft_s
        self.slots = slots
        self.weights_version = weights_version
        self.health_interval_s = health_interval_s
        self.max_missed_pings = max_missed_pings
        self.registry = registry
        self.max_queue_depth = max_queue_depth
        self.busy_retry_ms = busy_retry_ms
        self.replicas: dict = {}            # addr -> SimReplica
        self.router = None

    def start(self) -> int:
        from tony_tpu.serving.router import ServingRouter

        for _ in range(self.n):
            self.spawn()
        self.router = ServingRouter(
            list(self.replicas), health_interval_s=self.health_interval_s,
            max_missed_pings=self.max_missed_pings,
            registry=self.registry)
        return self.router.start()

    def spawn(self, weights_version: str | None = None,
              itl_s: float | None = None) -> str:
        rep = SimReplica(
            itl_s=self.itl_s if itl_s is None else itl_s,
            ttft_s=self.ttft_s, slots=self.slots,
            weights_version=(self.weights_version
                             if weights_version is None
                             else weights_version),
            max_queue_depth=self.max_queue_depth,
            busy_retry_ms=self.busy_retry_ms)
        rep.start()
        self.replicas[rep.addr] = rep
        return rep.addr

    def kill(self, addr: str) -> None:
        rep = self.replicas.get(addr)
        if rep is not None:
            rep.kill()

    def reap(self, addr: str) -> None:
        rep = self.replicas.pop(addr, None)
        if rep is not None:
            rep.stop()

    def addrs(self) -> list:
        return list(self.replicas)

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
        for rep in self.replicas.values():
            rep.stop()
        self.replicas.clear()


class SimWarmer:
    """:class:`~tony_tpu.serving.weightstore.FleetWarmer` over a
    :class:`SimFleet` — deterministic twin of ``ChannelWarmer`` for
    chaos and bench runs. A peer ship takes ``ship_s`` of injected
    wall time, a storage load ``load_s`` (typically >> ship_s: that
    gap IS the cold-start the warm path kills). ``ship`` raises when
    the seeder replica was killed (crash mid-ship chaos), which
    :func:`~tony_tpu.serving.weightstore.warm_fanout` absorbs by
    condemning the seeder and (with ``fallback``) minting a fresh one
    off storage — the fleet never wedges. Warmed replicas get
    ``version`` stamped as their weights_version, so the router pins
    sessions to the new generation exactly as with real replicas."""

    def __init__(self, fleet: SimFleet, version: str,
                 seeders=(), ship_s: float = 0.0, load_s: float = 0.0,
                 fallback: bool = True) -> None:
        self.fleet = fleet
        self.version = version
        self.seeders = list(seeders)
        self.ship_s = ship_s
        self.load_s = load_s
        self.fallback = fallback
        self.loads = 0                      # storage loads consumed
        self.last: dict | None = None       # last warm_fanout summary

    def warm(self, targets) -> dict:
        from tony_tpu.serving.weightstore import warm_fanout

        self.last = warm_fanout(
            list(targets), self._ship, seeders=list(self.seeders),
            fallback=self._load if self.fallback else None)
        # freshly-warmed replicas stay seeders for the NEXT pass too
        for addr in self.last["warmed"] + self.last["fallback"]:
            if addr not in self.seeders:
                self.seeders.append(addr)
        return self.last

    def _alive(self, addr: str):
        rep = self.fleet.replicas.get(addr)
        if rep is None or rep._stopping.is_set():
            return None
        return rep

    def _ship(self, src: str, dst: str) -> None:
        if self._alive(src) is None:
            raise RuntimeError(f"seeder {src} crashed mid-ship")
        if self.ship_s:
            time.sleep(self.ship_s)
        if self._alive(src) is None:        # crashed DURING the ship
            raise RuntimeError(f"seeder {src} crashed mid-ship")
        self._mark(dst)

    def _load(self, dst: str) -> None:
        if self.load_s:
            time.sleep(self.load_s)
        self.loads += 1
        self._mark(dst)

    def _mark(self, dst: str) -> None:
        rep = self.fleet.replicas.get(dst)
        if rep is not None:
            rep.weights_version = self.version


class SimProvider:
    """:class:`~tony_tpu.serving.fleet.CapacityProvider` over a
    :class:`SimFleet` — what the autoscale tests grow and shrink."""

    def __init__(self, fleet: SimFleet,
                 weights_version: str | None = None) -> None:
        self.fleet = fleet
        self.weights_version = weights_version

    def grow(self, n: int) -> list:
        return [self.fleet.spawn(weights_version=self.weights_version)
                for _ in range(n)]

    def release(self, addrs) -> None:
        for addr in addrs:
            self.fleet.reap(addr)


def open_loop_load(port: int, classes, *, interval_s: float = 0.0,
                   max_new: int = 8, prompt_len: int = 4,
                   retries: int = 0, seed_base: int = 1000,
                   host: str = "127.0.0.1",
                   event_timeout: float = 30.0) -> list:
    """Open-loop multi-class load generator: one submission every
    ``interval_s`` seconds REGARDLESS of completions (overload does not
    self-throttle — that is the point of open-loop), one request per
    entry of ``classes`` (a class name, or ``""``/``None`` for a
    classless ADMIT). Each request drains on its own thread and yields
    a record::

        {"cls", "ttft_s", "tokens", "shed", "retry_after_ms",
         "error", "ok"}

    where ``ok`` means the stream passed the oracle token-identity
    check — exactly ``max_new`` tokens equal to
    ``sim_token(seed_base + i, pos)`` for every position, across every
    preemption/requeue/failover the request survived. ``ttft_s`` counts
    from submit, so queueing and shedding delay show up in it."""
    from tony_tpu.serving.client import (ServerBusy,
                                         ServingConnectionError,
                                         StreamingClient)

    records = [{"cls": c or "standard", "ttft_s": None, "tokens": [],
                "shed": False, "retry_after_ms": 0, "error": None,
                "ok": False} for c in classes]

    with StreamingClient(host, port) as client:
        def drain(i: int, rid: int, t_submit: float) -> None:
            rec = records[i]
            try:
                for delta in client.deltas(rid, timeout=event_timeout):
                    if rec["ttft_s"] is None:
                        rec["ttft_s"] = time.monotonic() - t_submit
                    rec["tokens"].extend(delta)
            except ServerBusy as e:
                rec["shed"] = True
                rec["retry_after_ms"] = e.retry_after_ms
                return
            except ServingConnectionError as e:
                rec["error"] = str(e)
                return
            seed = seed_base + i
            want = [sim_token(seed, p) for p in range(max_new)]
            rec["ok"] = rec["tokens"] == want

        threads = []
        t0 = time.monotonic()
        for i, cls in enumerate(classes):
            wait = t0 + i * interval_s - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            prompt = [seed_base + i] * prompt_len
            rid = client.submit(prompt, max_new,
                                request_class=cls or None,
                                retries=retries)
            th = threading.Thread(
                target=drain, name=f"tony-sim-load-{i}",
                args=(i, rid, time.monotonic()), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=event_timeout + 5.0)
    return records
