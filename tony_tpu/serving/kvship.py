"""KV shipment wire codec: how a prefilled row travels prefill gang →
decode gang (disaggregated serving).

A shipment is ONE opaque blob — JSON metadata plus the row's named
cache buffers concatenated raw — that rides the TONYC1 tensor plane as
a single 1-D uint8 tensor frame (:meth:`ChannelSender.send_bytes`), so
the channel plane needs no knowledge of cache layouts and the shipment
inherits the channel's bounded-window backpressure, reconnect-with-
resume, and exactly-once delivery for free.

Wire layout (little-endian)::

    head_len   4 bytes  u32    JSON header length
    header     head_len bytes  {"v": 1, "meta": {...},
                                "bufs": [{"name", "dtype", "shape"}...]}
    payload    concatenated C-contiguous buffer bytes, in header order

``meta`` carries the adoption record: ``rid`` (the router's request
id), ``budget`` (remaining new tokens), ``length`` (the row's
frontier), ``rng`` (two u32 words of the per-request stream key) +
``rng_off`` (stream position — the state that makes SAMPLED
disaggregated output identical to colocated serving), and an optional
``trace`` span context so the decode gang's engine spans join the
request's trace.

Buffers ship in their STORAGE dtype: an int8-quantized cache ships
int8 values + f32 scales (~half the bytes of dequantizing to bf16 —
test-pinned), bf16 ships bf16. numpy alone cannot name ``bfloat16``;
jax's ``ml_dtypes`` dependency can, so dtype resolution falls back to
it — this module stays importable without jax (the codec tests and any
jax-free relay can round-trip shipments).

Anything structurally off raises the serving wire's
:class:`~tony_tpu.serving.protocol.ProtocolError` (channel-scoped at
the hub, request-scoped at the decode server's landing thread).
"""

from __future__ import annotations

import json
import math
import struct

import numpy as np

from tony_tpu.serving.protocol import ProtocolError

_HLEN = struct.Struct("<I")

#: sanity cap on the JSON header alone (buffer entries are dozens of
#: bytes each; megabytes of "header" is a corrupt length prefix)
MAX_HEADER_BYTES = 1 << 20


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype string, including the ml_dtypes extensions
    (bfloat16 et al.) plain numpy cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError) as e:
        raise ProtocolError(f"unknown shipment dtype {name!r}") from e


def pack_shipment(meta: dict, bufs: dict) -> bytes:
    """-> one shipment blob. ``bufs``: {name: ndarray}; arrays are
    serialized C-contiguous in sorted-name order (deterministic wire
    bytes for identical inputs)."""
    entries, blobs = [], []
    for name in sorted(bufs):
        a = np.asarray(bufs[name])
        shape = list(a.shape)          # before ascontiguousarray: it
        if not a.flags["C_CONTIGUOUS"]:   # promotes 0-d to 1-d
            a = np.ascontiguousarray(a)
        entries.append({"name": name, "dtype": str(a.dtype),
                        "shape": shape})
        blobs.append(a.tobytes())
    head = json.dumps({"v": 1, "meta": meta, "bufs": entries},
                      separators=(",", ":")).encode("utf-8")
    return _HLEN.pack(len(head)) + head + b"".join(blobs)


def unpack_shipment(blob: bytes) -> tuple[dict, dict]:
    """Parse a shipment blob -> (meta, {name: ndarray}). Arrays view
    the blob's memory (frombuffer — no copy); callers that outlive the
    blob hold a reference through the arrays automatically."""
    if len(blob) < _HLEN.size:
        raise ProtocolError("shipment shorter than its header prefix")
    (hlen,) = _HLEN.unpack_from(blob, 0)
    if hlen > MAX_HEADER_BYTES or _HLEN.size + hlen > len(blob):
        raise ProtocolError(f"implausible shipment header length {hlen}")
    try:
        head = json.loads(blob[_HLEN.size:_HLEN.size + hlen]
                          .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"malformed shipment header: {e}") from e
    if not isinstance(head, dict) or not isinstance(head.get("meta"),
                                                    dict):
        raise ProtocolError(f"shipment header is not an object: {head!r}")
    entries = head.get("bufs")
    if not isinstance(entries, list):
        raise ProtocolError("shipment header missing buffer table")
    bufs: dict = {}
    off = _HLEN.size + hlen
    for e in entries:
        if (not isinstance(e, dict) or not isinstance(e.get("name"), str)
                or not isinstance(e.get("dtype"), str)
                or not isinstance(e.get("shape"), list)
                or not all(isinstance(d, int) and not isinstance(d, bool)
                           and d >= 0 for d in e["shape"])):
            raise ProtocolError(f"malformed buffer entry: {e!r}")
        dt = _np_dtype(e["dtype"])
        # python-int math: np.prod would WRAP on adversarial shapes
        # ([2**32, 2**32] -> 0), sneaking a bogus buffer past the
        # bounds check into a reshape crash
        count = math.prod(e["shape"])
        n = count * dt.itemsize
        if off + n > len(blob):
            raise ProtocolError(
                f"shipment truncated: buffer {e['name']!r} promises "
                f"{n} bytes past the blob end")
        bufs[e["name"]] = np.frombuffer(
            blob, dtype=dt, count=count,
            offset=off).reshape(e["shape"])
        off += n
    if off != len(blob):
        raise ProtocolError(
            f"shipment carries {len(blob) - off} trailing bytes beyond "
            f"its buffer table")
    return head["meta"], bufs


def pack_kv_meta(rid: int, budget: int, length: int, rng_key,
                 rng_off: int = 0,
                 trace: dict | None = None) -> dict:
    """The adoption-record meta for one prefilled row (see module
    docstring); ``rng_key`` is the [2] uint32 per-request stream key."""
    k = np.asarray(rng_key, np.uint32).reshape(-1)
    meta = {"rid": int(rid), "budget": int(budget),
            "length": int(length),
            "rng": [int(k[0]), int(k[1])], "rng_off": int(rng_off)}
    if trace is not None:
        meta["trace"] = trace
    return meta


#: the ``kind`` tag distinguishing a prefix-template blob from a KV row
#: shipment sharing the same header+raw-buffers wire shape (a template
#: arriving on the kvship lane fails ``parse_kv_meta``; a row shipment
#: arriving on the prefix lane fails ``unpack_template`` — neither can
#: be silently misread as the other)
TEMPLATE_KIND = "prefix_template"

#: sanity cap on a template's token list (a prefix is a system prompt /
#: few-shot header, not a corpus; a million-token "prefix" is a corrupt
#: or adversarial header)
MAX_TEMPLATE_TOKENS = 1 << 20


def pack_template(prefix_id: str, tokens, bufs: dict, vocab: int) -> bytes:
    """Pack a shared-prefix K/V template for publication to a peer
    replica: the same header+raw-buffers wire shape as a row shipment
    (:func:`pack_shipment`), with the meta carrying the template's
    identity — ``id``, the prefix ``tokens`` (the installer registers
    them for prompt matching and suffix splitting), and the producing
    model's ``vocab`` (a template from a differently-shaped model must
    be rejected at install, not discovered as garbage logits mid-
    serve). ``bufs`` ship in their STORAGE dtype exactly like row
    shipments — an int8-quantized cache's template is int8 values +
    f32 scales, bf16 stays bf16 (bit-identical round trip,
    test-pinned)."""
    meta = {"kind": TEMPLATE_KIND, "id": str(prefix_id),
            "tokens": [int(t) for t in tokens], "vocab": int(vocab)}
    return pack_shipment(meta, bufs)


def unpack_template(blob: bytes) -> tuple[dict, dict]:
    """Parse + validate a template blob -> (meta, {name: ndarray}).
    Anything structurally off — including a KV row shipment routed onto
    the template lane — raises ProtocolError; the install thread drops
    the blob and keeps serving."""
    meta, bufs = unpack_shipment(blob)
    if meta.get("kind") != TEMPLATE_KIND:
        raise ProtocolError(
            f"not a prefix template (kind={meta.get('kind')!r})")
    pid = meta.get("id")
    tokens = meta.get("tokens")
    vocab = meta.get("vocab")
    if not isinstance(pid, str) or not 0 < len(pid) <= 128:
        raise ProtocolError(f"malformed template id: {pid!r}")
    if (not isinstance(tokens, list) or not tokens
            or len(tokens) > MAX_TEMPLATE_TOKENS
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and t >= 0 for t in tokens)):
        raise ProtocolError("malformed template token list")
    if isinstance(vocab, bool) or not isinstance(vocab, int) or vocab < 1:
        raise ProtocolError(f"malformed template vocab: {vocab!r}")
    if not bufs:
        raise ProtocolError("template carries no buffers")
    return meta, bufs


def parse_kv_meta(meta: dict) -> dict:
    """Validate an adoption record (the decode server's landing thread
    calls this before touching the engine); returns the meta with
    ``rng`` normalized to a [2] uint32 array. Malformed -> ProtocolError
    (the shipment is dropped; the channel keeps delivering)."""
    rid = meta.get("rid")
    budget = meta.get("budget")
    length = meta.get("length")
    rng = meta.get("rng")
    off = meta.get("rng_off", 0)
    if (isinstance(rid, bool) or not isinstance(rid, int)
            or isinstance(budget, bool) or not isinstance(budget, int)
            or isinstance(length, bool) or not isinstance(length, int)
            or isinstance(off, bool) or not isinstance(off, int)):
        raise ProtocolError(f"malformed shipment meta: {meta!r}")
    if (not isinstance(rng, list) or len(rng) != 2
            or not all(isinstance(w, int) and not isinstance(w, bool)
                       and 0 <= w < (1 << 32) for w in rng)):
        raise ProtocolError(f"malformed shipment rng state: {rng!r}")
    out = dict(meta)
    out["rng"] = np.asarray(rng, np.uint32)
    return out
