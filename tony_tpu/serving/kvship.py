"""KV shipment wire codec: how a prefilled row travels prefill gang →
decode gang (disaggregated serving).

A shipment is ONE opaque blob — JSON metadata plus the row's named
cache buffers concatenated raw — that rides the TONYC1 tensor plane as
byte-blob frames (:meth:`ChannelSender.send_bytes`), so the channel
plane needs no knowledge of cache layouts and the shipment inherits
the channel's bounded-window backpressure, reconnect-with-resume, and
exactly-once delivery for free.

The wire shape itself (header + raw buffers, kind-tagged) lives in
:mod:`tony_tpu.serving.blobcodec` — ONE codec shared by the three blob
lanes (KV rows here, prefix templates below, weight artifacts in
:mod:`tony_tpu.serving.weightstore`); this module binds the serving
semantics: the KV adoption record, the template identity checks.

``meta`` carries the adoption record: ``rid`` (the router's request
id), ``budget`` (remaining new tokens), ``length`` (the row's
frontier), ``rng`` (two u32 words of the per-request stream key) +
``rng_off`` (stream position — the state that makes SAMPLED
disaggregated output identical to colocated serving), and an optional
``trace`` span context so the decode gang's engine spans join the
request's trace.

Buffers ship in their STORAGE dtype: an int8-quantized cache ships
int8 values + f32 scales (~half the bytes of dequantizing to bf16 —
test-pinned), bf16 ships bf16. numpy alone cannot name ``bfloat16``;
jax's ``ml_dtypes`` dependency can, so dtype resolution falls back to
it — this module stays importable without jax (the codec tests and any
jax-free relay can round-trip shipments).

Anything structurally off raises the serving wire's
:class:`~tony_tpu.serving.protocol.ProtocolError` (channel-scoped at
the hub, request-scoped at the decode server's landing thread).
"""

from __future__ import annotations

import numpy as np

from tony_tpu.serving import blobcodec
from tony_tpu.serving.blobcodec import (MAX_HEADER_BYTES,  # noqa: F401
                                        _HLEN, np_dtype as _np_dtype)
from tony_tpu.serving.protocol import QOS_CLASSES, ProtocolError

#: the ``kind`` tags distinguishing the three blob lanes sharing one
#: wire shape (a template arriving on the kvship lane fails
#: ``unpack_shipment``'s kind gate; a row shipment arriving on the
#: prefix lane fails ``unpack_template`` — neither can be silently
#: misread as the other). Re-exported for back-compat; the codec
#: itself lives in :mod:`tony_tpu.serving.blobcodec`.
KV_ROW_KIND = blobcodec.KV_ROW_KIND
TEMPLATE_KIND = blobcodec.TEMPLATE_KIND

#: sanity cap on a template's token list (a prefix is a system prompt /
#: few-shot header, not a corpus; a million-token "prefix" is a corrupt
#: or adversarial header)
MAX_TEMPLATE_TOKENS = 1 << 20


def pack_shipment(meta: dict, bufs: dict) -> bytes:
    """-> one KV row shipment blob (kind-tagged ``kv_row``). ``bufs``:
    {name: ndarray}; arrays are serialized C-contiguous in sorted-name
    order (deterministic wire bytes for identical inputs)."""
    return blobcodec.KV_ROW.pack(meta, bufs)


def unpack_shipment(blob: bytes) -> tuple[dict, dict]:
    """Parse a KV row shipment blob -> (meta, {name: ndarray}). Arrays
    view the blob's memory (frombuffer — no copy). A parse-clean blob
    belonging to ANOTHER lane (a prefix template, a weight artifact)
    is refused at the kind gate."""
    return blobcodec.KV_ROW.unpack(blob)


def pack_kv_meta(rid: int, budget: int, length: int, rng_key,
                 rng_off: int = 0, cls: str = "standard",
                 trace: dict | None = None) -> dict:
    """The adoption-record meta for one prefilled row (see module
    docstring); ``rng_key`` is the [2] uint32 per-request stream key.
    ``cls`` is the request's QoS class — shipped only when non-default
    (old wires unchanged) so the decode tier's class floors and
    preemption apply to the adopted row."""
    k = np.asarray(rng_key, np.uint32).reshape(-1)
    meta = {"rid": int(rid), "budget": int(budget),
            "length": int(length),
            "rng": [int(k[0]), int(k[1])], "rng_off": int(rng_off)}
    if cls != "standard":
        meta["class"] = str(cls)
    if trace is not None:
        meta["trace"] = trace
    return meta


def pack_template(prefix_id: str, tokens, bufs: dict, vocab: int) -> bytes:
    """Pack a shared-prefix K/V template for publication to a peer
    replica: the shared blob wire shape (:mod:`~tony_tpu.serving.
    blobcodec`), with the meta carrying the template's identity —
    ``id``, the prefix ``tokens`` (the installer registers them for
    prompt matching and suffix splitting), and the producing model's
    ``vocab`` (a template from a differently-shaped model must be
    rejected at install, not discovered as garbage logits mid-serve).
    ``bufs`` ship in their STORAGE dtype exactly like row shipments —
    an int8-quantized cache's template is int8 values + f32 scales,
    bf16 stays bf16 (bit-identical round trip, test-pinned)."""
    meta = {"id": str(prefix_id),
            "tokens": [int(t) for t in tokens], "vocab": int(vocab)}
    return blobcodec.PREFIX_TEMPLATE.pack(meta, bufs)


def unpack_template(blob: bytes) -> tuple[dict, dict]:
    """Parse + validate a template blob -> (meta, {name: ndarray}).
    Anything structurally off — including a KV row shipment or weight
    artifact routed onto the template lane — raises ProtocolError; the
    install thread drops the blob and keeps serving."""
    try:
        meta, bufs = blobcodec.PREFIX_TEMPLATE.unpack(blob)
    except ProtocolError as e:
        if "lane" in str(e):
            raise ProtocolError(
                f"not a prefix template "
                f"(kind={blobcodec.unpack_blob(blob)[0].get('kind')!r})"
            ) from e
        raise
    pid = meta.get("id")
    tokens = meta.get("tokens")
    vocab = meta.get("vocab")
    if not isinstance(pid, str) or not 0 < len(pid) <= 128:
        raise ProtocolError(f"malformed template id: {pid!r}")
    if (not isinstance(tokens, list) or not tokens
            or len(tokens) > MAX_TEMPLATE_TOKENS
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and t >= 0 for t in tokens)):
        raise ProtocolError("malformed template token list")
    if isinstance(vocab, bool) or not isinstance(vocab, int) or vocab < 1:
        raise ProtocolError(f"malformed template vocab: {vocab!r}")
    if not bufs:
        raise ProtocolError("template carries no buffers")
    return meta, bufs


def parse_kv_meta(meta: dict) -> dict:
    """Validate an adoption record (the decode server's landing thread
    calls this before touching the engine); returns the meta with
    ``rng`` normalized to a [2] uint32 array. Malformed -> ProtocolError
    (the shipment is dropped; the channel keeps delivering)."""
    rid = meta.get("rid")
    budget = meta.get("budget")
    length = meta.get("length")
    rng = meta.get("rng")
    off = meta.get("rng_off", 0)
    if (isinstance(rid, bool) or not isinstance(rid, int)
            or isinstance(budget, bool) or not isinstance(budget, int)
            or isinstance(length, bool) or not isinstance(length, int)
            or isinstance(off, bool) or not isinstance(off, int)):
        raise ProtocolError(f"malformed shipment meta: {meta!r}")
    if (not isinstance(rng, list) or len(rng) != 2
            or not all(isinstance(w, int) and not isinstance(w, bool)
                       and 0 <= w < (1 << 32) for w in rng)):
        raise ProtocolError(f"malformed shipment rng state: {rng!r}")
    cls = meta.get("class", "standard")
    if cls not in QOS_CLASSES:
        raise ProtocolError(f"malformed shipment class: {cls!r}")
    out = dict(meta)
    out["rng"] = np.asarray(rng, np.uint32)
    out["class"] = cls
    return out
