"""Serving router: one front door, N replica serving hosts.

The router speaks the TONYS1 streaming protocol on BOTH sides — clients
connect to it exactly as they would to a single
:class:`~tony_tpu.serving.server.ServingServer`, and it holds one
persistent link per replica. Per session it:

- **places** by load: the replica whose last-reported
  ``tony_serve_queue_depth`` gauge + busy slots (the STATS frame, read
  straight off the replica's metrics registry — the PR-2 metrics plane)
  is smallest, tie-broken by the router's own not-yet-reported
  assignment count so a burst of admissions spreads before the next
  stats refresh;
- **streams** replica deltas through to the client as they land,
  remembering every token it forwarded;
- **health-checks** replicas: a STATS ping per interval, with link EOF
  / errors marking a replica down immediately and 3 consecutive
  UNANSWERED pings marking a hung-but-connected one down (unanswered
  pings, not wall-clock staleness — the router's own scheduling stalls
  must not down healthy replicas);
- **fails over** on replica loss: every unfinished session re-admits on
  a surviving replica with the already-streamed prefix folded into the
  prompt (``prompt + streamed``) and the remaining budget — greedy
  continuations are token-identical, so the client sees no duplicated
  and no dropped tokens (test-enforced).

**Disaggregated placement mode** (``decode_replicas=``): the replica
set splits into a prefill tier and a decode tier — ADMIT goes to the
prefill replica with the shallowest queue (naming a decode replica's
channel endpoint as the KV shipment target), TOKENS stream from the
decode replica that adopted the row, and the failover contract above
extends across the split: a decode loss re-prefills unfinished streams
through a surviving prefill replica. See
``tony_tpu/serving/disagg.py`` and docs/serving.md §Disaggregated
prefill/decode.

**Fleet operations** (planned, not reactive — the drain/upgrade path):
:meth:`ServingRouter.drain` fences a replica against new placements
and LIVE-MIGRATES every session off it: each stream re-admits on a
survivor with the streamed prefix folded into the prompt and its rng
stream pinned (the ADMIT ``rng`` field), the OLD replica keeps
streaming until the new placement's first delta ACKs the takeover,
then a CANCEL tombstones the old half — zero duplicated and zero
dropped tokens, greedy AND sampled, colocated AND disaggregated
(test-pinned). Replicas advertise a ``weights_version`` in
HELLO/STATS; placement prefers a session's pinned version when any
same-version replica survives, which is what makes drain-by-drain
rolling weight upgrades session-transparent. :meth:`add_replicas` /
:meth:`remove_replica` change fleet membership live. The ``DRAIN`` and
``MIGRATE`` frames expose drain / single-session migration to remote
operator clients.

Router-side series (default registry): ``tony_router_replica_up`` /
``tony_router_replica_queue_depth`` (gauges, ``replica=host:port``),
``tony_router_sessions_total{replica=...}``,
``tony_router_failovers_total``, ``tony_router_handoffs_total``,
``tony_router_migrations_total``, ``tony_router_drains_total``,
``tony_router_place_seconds``.

The router never touches the model stack — it is deployable on a
jax-free gateway host.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time

from tony_tpu.conf.keys import (DEFAULTS, ROUTER_HEALTH_INTERVAL_MS_KEY,
                                ROUTER_MAX_MISSED_PINGS_KEY)
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.serving import protocol as P
from tony_tpu.serving.prefix import fingerprint, match_prefix
from tony_tpu.serving.server import FrameConn, FrameServerBase

log = logging.getLogger(__name__)

#: first rng stream index the router hands out — 0, matching what an
#: engine's own submission counter would assign the same admissions in
#: the same order. That keeps the serving identity contract (routed
#: sampled output == the colocated engine's, bit-for-bit, test-pinned)
#: while making streams unique FLEET-wide instead of per-replica. A
#: direct client bypassing a routed replica can share a stream index
#: with a routed session — a sampling correlation, never a correctness
#: issue, and no worse than the per-replica counters it replaces.
ROUTER_STREAM_BASE = 0

#: how many replica BUSY sheds one batch session rides out via router
#: re-queue before the shed propagates to the client. The cap exists to
#: end the game when EVERY replica is shedding — by then the fleet is
#: saying "come back later" and the client should hear it.
BUSY_REQUEUE_CAP = 3


class _ReplicaLink:
    """One persistent connection to a replica server, with a reader
    thread dispatching its pushed frames back into the router.
    ``role`` is the tier this link fronts: ``"engine"`` (a colocated
    ServingServer), ``"prefill"``, or ``"decode"`` (disaggregated
    mode)."""

    def __init__(self, addr: str, router: "ServingRouter",
                 role: str = "engine") -> None:
        self.addr = addr
        self.role = role
        self._router = router
        host, _, port = addr.rpartition(":")
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=10)
        P.set_nodelay(self._sock)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self.alive = True
        #: last STATS-reported load (queue_depth + active slots)
        self.reported_load = 0
        self.last_stats = time.monotonic()
        #: health pings sent without a reply since the last one. Health
        #: is judged on THIS, not on wall time since the last reply — a
        #: wall-clock threshold also counts the router's own scheduling
        #: stalls (GC, an in-process jax compile) and would down every
        #: healthy replica at once after one long stall.
        self.pings_unanswered = 0
        #: sessions assigned here and not yet retired (router-side)
        self.assigned = 0
        #: fenced against NEW placements (a drain in progress) — live
        #: sessions keep streaming until their migration ACKs
        self.draining = False
        self._sock.sendall(P.MAGIC)
        hello = P.recv_frame(self._sock)
        if hello is None or hello[0] != P.HELLO:
            self._sock.close()
            raise ConnectionError(f"replica {addr}: no HELLO")
        self.hello = P.unpack_json(hello[2])
        #: the decode tier's channel-hub endpoint port (what prefill
        #: replicas are told to ship this gang's KV packages to)
        self.channel_port = self.hello.get("channel_port")
        #: resident shared-prefix templates this replica advertised
        #: (HELLO at connect, refreshed by every STATS reply) — what
        #: prefix-aware placement reads
        self.prefixes = self._parse_prefixes(self.hello)
        #: rolling-cache layout: positional prefix templates cannot be
        #: resident here — the router places prefix traffic on it
        #: PREFIX-BLIND (one warning, never an error)
        self.ring = bool(self.hello.get("ring"))
        #: the weights generation this replica advertised (HELLO,
        #: refreshed by STATS) — version-pinned placement (rolling
        #: upgrades) keys on it; None = unversioned
        self.weights_version = self.hello.get("weights_version")
        #: the content digest of the served weight tree (HELLO,
        #: refreshed by STATS) — when the operator never named a
        #: version, the digest IS the generation: sessions pin on it,
        #: so an unversioned rolling upgrade still never mixes weight
        #: generations mid-stream
        self.weights_digest = self.hello.get("weights_digest")
        if self.weights_version is None and isinstance(
                self.weights_digest, str):
            self.weights_version = self.weights_digest
        self.slots = int(self.hello.get("slots", 0) or 0)
        #: decode slots with no live occupant per the last STATS — the
        #: equal-queue-depth placement tiebreak
        self.idle_slots = self.slots
        #: per-class waiting counts from the replica's last STATS
        #: (class-aware engines report ``queue_depths``); a classless
        #: replica never populates it and everything falls back to the
        #: aggregate load gauge
        self.queue_depths: dict[str, int] = {}
        got_role = self.hello.get("role")
        if role != "engine" and got_role != role:
            self._sock.close()
            raise ConnectionError(
                f"replica {addr} reports role {got_role!r}; the "
                f"disaggregated router expected a {role!r} tier there")
        self._reader = threading.Thread(
            target=self._read_loop, name=f"tony-router-link-{addr}",
            daemon=True)
        self._reader.start()

    def send(self, ftype: int, rid: int, payload: bytes = b"") -> bool:
        with self._send_lock:
            if not self.alive:
                return False
            try:
                P.send_frame(self._sock, ftype, rid, payload)
                return True
            except OSError:
                return False

    def _read_loop(self) -> None:
        router = self._router
        try:
            while True:
                frame = P.recv_frame(self._sock)
                if frame is None:
                    break
                ftype, rid, payload = frame
                if ftype == P.TOKENS:
                    router._replica_delta(self, rid,
                                          P.unpack_tokens(payload))
                elif ftype == P.RETIRED:
                    obj = P.unpack_json(payload)
                    router._replica_retired(
                        self, rid, obj.get("reason", "unknown"))
                elif ftype == P.ERROR:
                    obj = P.unpack_json(payload)
                    msg = obj.get("message", "error")
                    if rid == 0:
                        break               # replica dropped our link
                    router._replica_error(self, rid, msg,
                                          retryable=bool(
                                              obj.get("retryable")))
                elif ftype == P.HANDOFF:
                    router._replica_handoff(self, rid,
                                            P.unpack_json(payload))
                elif ftype == P.BUSY:
                    obj = P.unpack_json(payload)
                    router._replica_busy(
                        self, rid,
                        int(obj.get("retry_after_ms", 0) or 0))
                elif ftype == P.STATS:
                    obj = P.unpack_json(payload)
                    self.reported_load = (int(obj.get("queue_depth", 0))
                                          + int(obj.get("active", 0)))
                    if "slots" in obj:
                        self.slots = int(obj.get("slots", 0) or 0)
                    self.idle_slots = max(
                        0, self.slots - int(obj.get("active", 0)))
                    got_d = obj.get("queue_depths")
                    if isinstance(got_d, dict):
                        self.queue_depths = {
                            c: int(n) for c, n in got_d.items()
                            if c in P.QOS_CLASSES
                            and isinstance(n, int)
                            and not isinstance(n, bool)}
                    if "weights_digest" in obj:
                        self.weights_digest = obj.get("weights_digest")
                    if "weights_version" in obj:
                        got_v = obj.get("weights_version")
                        if got_v is None and isinstance(
                                self.weights_digest, str):
                            got_v = self.weights_digest
                        self.weights_version = got_v
                    if "prefixes" in obj:
                        got = self._parse_prefixes(obj)
                        if got != self.prefixes:
                            # residency gauges refresh only on an
                            # actual change, not every health ping
                            self.prefixes = got
                            router._refresh_prefix_residency()
                    self.last_stats = time.monotonic()
                    self.pings_unanswered = 0
                    router._note_stats(self)
        except (P.ProtocolError, OSError):
            pass
        router._replica_down(self)

    @staticmethod
    def _parse_prefixes(obj: dict) -> set:
        got = obj.get("prefixes")
        if not isinstance(got, list):
            return set()
        return {p for p in got if isinstance(p, str) and len(p) <= 128}

    def close(self) -> None:
        self.alive = False
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _Migration:
    """One in-flight coordinated migration: a SECOND placement of a
    live session, started while the old one keeps streaming. The first
    delta from the new placement is the ACK — ownership swaps there,
    the regenerated overlap (tokens the old side streamed after the
    snapshot) is discarded count-exactly (token-identical by the rng
    pin), and a CANCEL tombstones the old half."""

    __slots__ = ("snap_len", "new_link", "new_prefill", "new_rrid",
                 "acked", "discard", "handed_off")

    def __init__(self, snap_len: int, new_link, new_prefill,
                 new_rrid: int) -> None:
        #: len(streamed) at the snapshot the new ADMIT carried
        self.snap_len = snap_len
        self.new_link = new_link        # token link of the new placement
        self.new_prefill = new_prefill  # its prefill half (disagg)
        self.new_rrid = new_rrid
        self.acked = False
        #: regenerated overlap tokens still to drop from the new stream
        self.discard = 0
        #: the NEW placement's HANDOFF was observed pre-ACK (disagg)
        self.handed_off = False


class _RouterSession:
    __slots__ = ("conn", "crid", "prompt", "budget", "streamed", "link",
                 "prefill_link", "handed_off", "rrid", "cancelled",
                 "trace_ctx", "prefix_id", "stream", "pinned_version",
                 "migrating", "wlock", "cls", "t_submit", "t_last",
                 "busy_retries")

    def __init__(self, conn: FrameConn, crid: int, prompt: list[int],
                 budget: int, trace_ctx: dict | None = None,
                 prefix_id: str | None = None, stream: int = 0,
                 cls: str = "standard") -> None:
        self.conn = conn
        self.crid = crid
        self.prompt = prompt
        self.budget = budget
        #: the session's QoS class, forwarded on EVERY placement
        #: (initial, failover, migration) so replica-side floors and
        #: queue priority follow the session wherever it lands
        self.cls = cls
        #: admission wall-clock + last-delta wall-clock: the router's
        #: own per-class TTFT/intertoken series (0.0 = no delta yet).
        #: The router measures what the CLIENT experiences — replica
        #: queueing, placement retries, and BUSY re-queues included —
        #: which replica-side series by construction cannot see.
        self.t_submit = time.monotonic()
        self.t_last = 0.0
        #: replica BUSY sheds this session already rode out (batch
        #: re-queue is capped — past the cap the shed propagates)
        self.busy_retries = 0
        #: the fleet-unique rng stream this session is pinned to — every
        #: placement (initial, failover, migration) forwards it with the
        #: already-streamed count as the offset, so SAMPLED
        #: continuations are token-identical across replicas
        self.stream = stream
        #: weights_version of the first placement: later placements
        #: prefer same-version replicas while any survive (rolling
        #: upgrades migrate tier-by-tier without mixing generations
        #: mid-stream); continuity beats pinning when none do
        self.pinned_version = None
        #: the in-flight coordinated migration, if any
        self.migrating: _Migration | None = None
        #: per-session delta ORDER lock: the old and new placements'
        #: deltas forward from different link reader threads around the
        #: ACK swap — append+send must be atomic per delta or the client
        #: could see positions out of order. Lock order: wlock, then
        #: the router lock; never the reverse.
        self.wlock = threading.Lock()
        #: the shared prefix this session continues (ADMIT's prefix
        #: field, or the router's tokenized match): prefix-aware
        #: placement prefers replicas where it is resident, and the id
        #: is forwarded on every replica ADMIT — including failover
        #: re-placements (a cold survivor just full-prefills)
        self.prefix_id = prefix_id
        self.streamed: list[int] = []       # every token forwarded
        #: the link TOKENS flow from: the replica itself (colocated) or
        #: the DECODE link of a disaggregated placement pair
        self.link: _ReplicaLink | None = None
        #: disaggregated mode only: the prefill link the ADMIT went to;
        #: once ``handed_off`` (the HANDOFF frame), losing it no longer
        #: affects this session — the row lives on the decode gang
        self.prefill_link: _ReplicaLink | None = None
        self.handed_off = False
        self.rrid = 0
        #: the client asked for this session's death; a failover must
        #: finish it as cancelled, never resurrect it on a survivor
        self.cancelled = False
        #: the client's span context, forwarded on every replica ADMIT
        #: (including failover re-placements) so the engine's spans join
        #: the client's trace across the router hop
        self.trace_ctx = trace_ctx


class ServingRouter(FrameServerBase):
    """Front-door spreading streaming sessions across replica serving
    hosts. ``replicas``: ``["host:port", ...]`` of running
    :class:`~tony_tpu.serving.server.ServingServer` instances.

    DISAGGREGATED placement mode (``decode_replicas=``): ``replicas``
    becomes the PREFILL tier
    (:class:`~tony_tpu.serving.disagg.PrefillServer`) and
    ``decode_replicas`` the decode tier
    (:class:`~tony_tpu.serving.disagg.DecodeServer`). A placement is
    then a PAIR — the ADMIT goes to the least-loaded prefill replica
    (queue depth, the STATS gauge) naming the least-loaded decode
    replica's channel endpoint as the KV shipment target; TOKENS stream
    back over the decode replica's link (the router BINDs itself as
    each decode replica's delta sink). A ``HANDOFF`` frame moves the
    session's fate off the prefill link; losing a DECODE replica
    re-admits its unfinished streams through a surviving prefill
    replica with the streamed prefix folded into the prompt — the same
    zero-dup/zero-drop failover contract as colocated replica loss
    (test-pinned)."""

    def __init__(self, replicas, bind_host: str = "127.0.0.1",
                 port: int = 0, health_interval_s: float | None = None,
                 decode_replicas=None, registry=None,
                 prefixes=None, max_missed_pings: int | None = None) -> None:
        super().__init__(bind_host, port)
        self._replica_addrs = list(replicas)
        self._decode_addrs = list(decode_replicas or [])
        self._disagg = bool(self._decode_addrs)
        if not self._replica_addrs:
            raise ValueError("router needs at least one replica")
        self._lock = threading.Lock()
        self._links: list[_ReplicaLink] = []
        self._sessions: dict[tuple[int, int], _RouterSession] = {}
        self._by_rrid: dict[int, _RouterSession] = {}
        self._next_rrid = itertools.count(1)
        self._next_stream = itertools.count(ROUTER_STREAM_BASE)
        self._downed: set[int] = set()      # id()s of links already torn
        # health knobs (tony.router.health-interval-ms /
        # tony.router.max-missed-pings): kwargs override the config
        # defaults — the sim harness runs hundreds of replicas at
        # millisecond cadence through exactly these
        if health_interval_s is None:
            health_interval_s = float(
                DEFAULTS[ROUTER_HEALTH_INTERVAL_MS_KEY]) / 1000.0
        self.health_interval_s = health_interval_s
        self.max_missed_pings = (
            int(DEFAULTS[ROUTER_MAX_MISSED_PINGS_KEY])
            if max_missed_pings is None else int(max_missed_pings))
        self._health_thread: threading.Thread | None = None
        self._stopped = False               # stop() ran (idempotence)
        #: the prefix-matching catalog: id -> token list. ADMITs naming
        #: no prefix are matched here (longest proper token-boundary
        #: prefix); residency still comes from the replicas' own
        #: advertisements, so a stale catalog can only cost fast-path
        #: hits, never correctness.
        self._prefix_catalog: dict[str, list[int]] = {}
        self._ring_warned: set[str] = set()
        reg = registry or metrics_mod.get_default()
        self._reg = reg
        self._failovers_c = reg.counter(
            "tony_router_failovers_total",
            help="sessions re-admitted after a replica loss")
        self._handoffs_c = reg.counter(
            "tony_router_handoffs_total",
            help="prefill->decode KV handoffs observed (disaggregated "
                 "placement mode)")
        self._prefix_hits_c = reg.counter(
            "tony_router_prefix_hits_total",
            help="prefix-naming sessions placed on a replica where the "
                 "prefix KV is already resident")
        self._prefix_misses_c = reg.counter(
            "tony_router_prefix_misses_total",
            help="prefix-naming sessions placed prefix-blind (no live "
                 "replica had the prefix resident)")
        self._migrations_c = reg.counter(
            "tony_router_migrations_total",
            help="planned session migrations completed (ownership "
                 "swapped to the new placement with zero dup/drop)")
        self._drains_c = reg.counter(
            "tony_router_drains_total",
            help="replica drains completed (fence + migrate-all; "
                 "zero-session drains count too)")
        self._busy_requeued_c = reg.counter(
            "tony_router_busy_requeues_total",
            help="batch sessions re-placed after a replica shed them "
                 "with BUSY (the client never saw the shed)")
        self._preempt_requeued_c = reg.counter(
            "tony_router_preempt_requeues_total",
            help="sessions re-placed after a decode-tier preemption "
                 "eviction (the row could not fold back replica-side)")
        # the router's own per-class latency series share the engine's
        # names: in a shared-registry process the series are literally
        # shared (get-or-create), and on a jax-free gateway host the
        # router is the ONLY producer — the fleet dashboard reads one
        # name either way
        self._ttft_by_cls = {
            c: reg.histogram(
                "tony_serve_ttft_seconds",
                help="time to first streamed token",
                **{"class": c})
            for c in P.QOS_CLASSES}
        self._itl_by_cls = {
            c: reg.histogram(
                "tony_serve_intertoken_seconds",
                help="gap between consecutive streamed tokens",
                **{"class": c})
            for c in P.QOS_CLASSES}
        self._cls_depth_g = {
            c: reg.gauge(
                "tony_router_class_queue_depth",
                help="fleet-wide waiting requests of the class (sum of "
                     "the replicas' per-class STATS depths)",
                **{"class": c})
            for c in P.QOS_CLASSES}
        self._place_h = reg.histogram(
            "tony_router_place_seconds",
            help="wall time of one placement decision + forwarded "
                 "ADMIT (initial admissions; the router's tail-latency "
                 "signal under migration storms)")
        self._up_g = {}
        self._depth_g = {}
        self._placed_c = {}
        self._resident_g: dict[str, object] = {}
        if prefixes:
            # after the registry fields: register_prefix refreshes the
            # residency gauges
            for pid, toks in dict(prefixes).items():
                self.register_prefix(toks, prefix_id=pid)

    # -- lifecycle ----------------------------------------------------------
    def _connect(self, role: str, addr: str) -> _ReplicaLink:
        """Create one replica link (and its per-replica metric series).
        Gauges BEFORE the link: the link's reader thread may run
        _replica_down (instant replica death) the moment the link
        exists, and that path writes these gauges."""
        self._up_g[addr] = self._reg.gauge(
            "tony_router_replica_up",
            help="1 while the replica link is healthy", replica=addr)
        self._depth_g[addr] = self._reg.gauge(
            "tony_router_replica_queue_depth",
            help="replica's last-reported tony_serve_queue_depth "
                 "+ busy slots", replica=addr)
        self._placed_c[addr] = self._reg.counter(
            "tony_router_sessions_total",
            help="sessions placed on the replica", replica=addr)
        self._up_g[addr].set(1)
        link = _ReplicaLink(addr, self, role=role)
        self._warn_if_ring(link)
        if role == "decode":
            if link.channel_port is None:
                link.close()
                raise ConnectionError(
                    f"decode replica {addr} advertised no "
                    f"channel_port — not a DecodeServer?")
            # we are this gang's delta sink: every KV-adopted row's
            # TOKENS/RETIRED frames push down this link
            link.send(P.BIND, 0)
        return link

    def start(self) -> int:
        roles = ([("prefill" if self._disagg else "engine", a)
                  for a in self._replica_addrs]
                 + [("decode", a) for a in self._decode_addrs])
        for role, addr in roles:
            self._links.append(self._connect(role, addr))
        self._refresh_prefix_residency()
        port = super().start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="tony-router-health",
            daemon=True)
        self._health_thread.start()
        log.info("router on %s:%s over %d replicas", self.bind_host,
                 port, len(self._links))
        return port

    # -- prefix catalog -----------------------------------------------------
    def register_prefix(self, tokens, prefix_id: str | None = None) -> str:
        """Add a shared prefix to the matching catalog (callable before
        or after :meth:`start`, and remotely via the ``PREFIX``
        ``register`` op); returns its id — the content fingerprint
        unless given, so it names the same prefix the replicas
        installed. Bounded like the template wire codec: ids cap at
        128 chars and token lists at ``kvship.MAX_TEMPLATE_TOKENS``
        (the register op is remote-reachable; an unbounded catalog
        would grow router memory AND every unnamed ADMIT's match
        cost)."""
        from tony_tpu.serving.kvship import MAX_TEMPLATE_TOKENS

        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("prefix tokens must be non-empty")
        if len(tokens) > MAX_TEMPLATE_TOKENS:
            raise ValueError(
                f"prefix of {len(tokens)} tokens exceeds the "
                f"{MAX_TEMPLATE_TOKENS}-token cap — a prefix is a "
                f"system prompt, not a corpus")
        if prefix_id is not None and (
                not isinstance(prefix_id, str)
                or not 0 < len(prefix_id) <= 128):
            raise ValueError(f"prefix id must be a 1..128-char string, "
                             f"got {prefix_id!r}")
        pid = prefix_id if prefix_id else fingerprint(tokens)
        self._prefix_catalog[pid] = tokens
        self._refresh_prefix_residency()
        return pid

    def _warn_if_ring(self, link: _ReplicaLink) -> None:
        """A rolling-cache replica can never host a resident prefix —
        say so ONCE and keep placing on it prefix-blind (graceful
        degradation, never an error)."""
        if link.ring and link.addr not in self._ring_warned:
            self._ring_warned.add(link.addr)
            log.warning(
                "router: replica %s serves a rolling (ring) cache; "
                "prefix-aware placement is disabled for it "
                "(prefix-blind)", link.addr)

    def _refresh_prefix_residency(self) -> None:
        """Recompute the per-prefix residency gauges
        (``tony_router_prefix_resident_replicas{prefix=...}``) over
        the LIVE links' advertisements."""
        links = list(self._links)       # snapshot vs concurrent callers
        pids = set(self._prefix_catalog)
        for link in links:
            pids |= link.prefixes
        for pid in pids:
            g = self._resident_g.get(pid)
            if g is None:
                g = self._resident_g[pid] = self._reg.gauge(
                    "tony_router_prefix_resident_replicas",
                    help="live replicas advertising this prefix's KV "
                         "template as resident", prefix=pid)
            g.set(sum(1 for l in links
                      if l.alive and pid in l.prefixes))

    # -- fleet membership (rolling upgrades) --------------------------------
    def add_replicas(self, addrs, role: str | None = None) -> None:
        """Connect new replicas into a RUNNING fleet (the rolling
        upgrade's first step: stand the new-version tier up next to the
        old one). ``role`` defaults to the fleet's token tier
        (``engine`` colocated, ``prefill`` disaggregated); pass
        ``"decode"`` to grow that tier. A replica that refuses the
        handshake raises — nothing is half-added."""
        role = role or ("prefill" if self._disagg else "engine")
        for addr in addrs:
            link = self._connect(role, addr)
            with self._lock:
                self._links.append(link)
            target = (self._decode_addrs if role == "decode"
                      else self._replica_addrs)
            if addr not in target:
                target.append(addr)
        self._refresh_prefix_residency()

    def remove_replica(self, addr: str) -> int:
        """Disconnect ``addr`` from the fleet (the rolling upgrade's
        last step, after :meth:`drain` emptied it). Sessions still on
        it — a drain skipped or timed out — go through the
        crash-failover re-placement, so removal is never worse than the
        replica dying. Returns the number of links removed."""
        with self._lock:
            victims = [l for l in self._links if l.addr == addr]
            for l in victims:
                self._links.remove(l)
        for l in victims:
            l.close()
            self._replica_down(l)
        for addrs in (self._replica_addrs, self._decode_addrs):
            while addr in addrs:
                addrs.remove(addr)
        self._refresh_prefix_residency()
        return len(victims)

    def stop(self) -> None:
        """Stop the router. Idempotent — a second stop is a no-op. Any
        session still live (including mid-migration) is swept into a
        terminal client ERROR before its connection closes: a stop
        racing an in-flight migration must never strand a stream
        without exactly one terminal frame."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._stopping.set()
        self._close_listener()
        for link in list(self._links):
            link.close()
        with self._lock:
            doomed = list(self._sessions.values())
            self._sessions.clear()
            self._by_rrid.clear()
        for s in doomed:
            s.conn.send(P.ERROR, s.crid,
                        P.pack_json({"message": "router stopping"}))
        self._close_conns()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    # -- placement ----------------------------------------------------------
    @staticmethod
    def _load_key(link: _ReplicaLink):
        """Placement order: the load gauge first (the metrics-plane
        signal), then — at EQUAL queue depths — the link with more
        idle decode slots (headroom that admits without queueing),
        then the router's own not-yet-reported assignment count
        (spreads a burst between stats refreshes)."""
        return (link.reported_load, -link.idle_slots, link.assigned)

    def _pick_link(self, exclude=None, role: str | None = None,
                   prefer_prefix: str | None = None,
                   prefer_version=None, cls: str = "standard"):
        """Least-loaded live, non-draining link of ``role``.
        ``exclude`` is one link or an iterable of links (a migration
        storm / multi-replica failure excludes a SET). Preference
        order: ``prefer_version`` first (a version-pinned session stays
        on its weights generation while any same-version replica
        survives — continuity beats pinning when none do), then
        ``prefer_prefix`` restricts to replicas advertising that prefix
        as RESIDENT when any exist (sessions go where the prefix KV
        already lives), falling back to the full pool on a cold
        fleet. An ``interactive`` session further narrows to links with
        an idle decode slot whenever any exist — the queue is exactly
        what the class is paying to skip."""
        if exclude is None:
            ex = ()
        elif isinstance(exclude, _ReplicaLink):
            ex = (exclude,)
        else:
            ex = tuple(exclude)
        with self._lock:
            live = [l for l in self._links
                    if l.alive and not l.draining
                    and all(l is not e for e in ex)
                    and (role is None or l.role == role)]
            if not live:
                return None
            if prefer_version is not None:
                same = [l for l in live
                        if l.weights_version == prefer_version]
                if same:
                    live = same
            if prefer_prefix is not None:
                resident = [l for l in live
                            if prefer_prefix in l.prefixes]
                if resident:
                    live = resident
            if cls == "interactive":
                idle = [l for l in live if l.idle_slots > 0]
                if idle:
                    live = idle
            return min(live, key=self._load_key)

    def _unassign_locked(self, sess: _RouterSession) -> None:
        """Release a session's assignment counts (BOTH halves of a
        disaggregated pair). Call exactly once per removal from
        ``_by_rrid`` — the pairing invariant the load tiebreak rests
        on."""
        for link in {sess.link, sess.prefill_link}:
            if link is not None:
                link.assigned -= 1

    def _health_loop(self) -> None:
        while not self._stopping.wait(self.health_interval_s):
            for link in list(self._links):
                if not link.alive:
                    continue
                if link.pings_unanswered >= self.max_missed_pings:
                    log.warning("router: replica %s unresponsive (%d "
                                "unanswered stats pings); marking down",
                                link.addr, link.pings_unanswered)
                    link.close()            # reader EOF -> _replica_down
                    continue
                link.pings_unanswered += 1
                if not link.send(P.STATS, 0):
                    link.close()

    def _note_stats(self, link: _ReplicaLink) -> None:
        self._depth_g[link.addr].set(link.reported_load)
        if link.queue_depths:
            # fleet-wide per-class backlog: the autoscaler's signal
            # (FleetController reads interactive pressure, never the
            # batch backlog). list() copy — no lock on a reader thread.
            totals = {c: 0 for c in P.QOS_CLASSES}
            for l in list(self._links):
                if l.alive:
                    for c, n in l.queue_depths.items():
                        totals[c] = totals.get(c, 0) + n
            for c, g in self._cls_depth_g.items():
                g.set(totals.get(c, 0))

    # -- client side (reader threads) ---------------------------------------
    def _hello_payload(self) -> dict:
        return {"v": 1, "router": True,
                "replicas": len(self._replica_addrs)}

    def _handle_frame(self, conn: FrameConn, ftype: int, rid: int,
                      payload: bytes) -> None:
        if ftype == P.ADMIT:
            self._admit(conn, rid, payload)
        elif ftype == P.CANCEL:
            # capture (links, rrid) under the SAME lock that marks the
            # cancel: a failover re-placement assigns them as a pair,
            # and an unlocked read could pair the new link with the old
            # rrid — a CANCEL the surviving replica would no-op. In
            # disaggregated mode the CANCEL fans to BOTH tiers: the
            # prefill tier drops a still-queued prompt, the decode tier
            # tombstones the rid so a late-arriving shipment is never
            # adopted into a slot generating into the void.
            targets = []
            with self._lock:
                sess = self._sessions.get((conn.id, rid))
                if sess is not None:
                    sess.cancelled = True
                    targets = [(l, sess.rrid)
                               for l in (sess.link, sess.prefill_link)
                               if l is not None]
                    mig = sess.migrating
                    if mig is not None:
                        # mid-migration: the NEW placement dies too —
                        # its pre-ACK retirement is swallowed (the old
                        # side owns the terminal frame), so the client
                        # still sees exactly one
                        targets += [(l, mig.new_rrid)
                                    for l in (mig.new_link,
                                              mig.new_prefill)
                                    if l is not None]
            for link, rrid_t in targets:
                link.send(P.CANCEL, rrid_t)
        elif ftype == P.STATS:
            conn.send(P.STATS, 0, P.pack_json(self.stats()))
        elif ftype == P.PREFIX:
            self._handle_prefix_op(conn, rid, payload)
        elif ftype == P.DRAIN:
            obj = P.unpack_json(payload)
            replica = obj.get("replica")
            if not isinstance(replica, str) or not replica:
                conn.send(P.ERROR, rid, P.pack_json(
                    {"message": "DRAIN needs {'replica': 'host:port'}"}))
                return
            timeout = obj.get("timeout_s")
            timeout = float(timeout) if isinstance(
                timeout, (int, float)) and not isinstance(
                timeout, bool) else 120.0
            # a drain blocks until every session left the replica —
            # never on the operator connection's reader thread
            threading.Thread(
                target=self._drain_and_reply,
                args=(conn, rid, replica, timeout),
                name=f"tony-router-drain-{replica}", daemon=True).start()
        elif ftype == P.MIGRATE:
            with self._lock:
                sess = self._sessions.get((conn.id, rid))
            ok = sess is not None and self._migrate_session(sess)
            conn.send(P.MIGRATE, rid, P.pack_json({"ok": bool(ok)}))
        elif ftype == P.POLL:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": "router supports streaming requests only"}))
        else:
            raise P.ProtocolError(
                f"unexpected frame type {P.FRAME_NAMES.get(ftype, ftype)}")

    def _handle_prefix_op(self, conn: FrameConn, rid: int,
                          payload: bytes) -> None:
        """Router-side ``PREFIX`` ops: ``register`` (grow the matching
        catalog) and ``list`` (catalog + fleet residency). Failures are
        request-scoped replies, never connection deaths."""
        obj = P.unpack_json(payload)
        op = obj.get("op")
        try:
            if op == "register":
                tokens = obj.get("tokens")
                if (not isinstance(tokens, list) or not tokens
                        or not all(isinstance(t, int)
                                   and not isinstance(t, bool)
                                   for t in tokens)):
                    raise ValueError("register needs a non-empty token "
                                     "list")
                pid = self.register_prefix(tokens,
                                           prefix_id=obj.get("id"))
                body = {"ok": True, "id": pid,
                        "catalog": sorted(self._prefix_catalog)}
            elif op == "list":
                body = {"ok": True,
                        "catalog": sorted(self._prefix_catalog),
                        "resident": {
                            l.addr: sorted(l.prefixes)
                            for l in self._links if l.alive}}
            else:
                body = {"ok": False,
                        "error": f"unknown router prefix op {op!r} "
                                 f"(install/publish go to replicas)"}
        except ValueError as e:
            body = {"ok": False, "error": str(e)}
        conn.send(P.PREFIX, rid, P.pack_json(body))

    def _admit(self, conn: FrameConn, rid: int, payload: bytes) -> None:
        prompt, max_new, stream = P.parse_admit(payload)
        if rid == 0:
            raise P.ProtocolError("ADMIT rid must be nonzero")
        if not stream:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": "router supports streaming requests only"}))
            return
        if max_new <= 0:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": f"max_new_tokens must be positive, "
                            f"got {max_new}"}))
            return
        # the session's prefix identity: the ADMIT's explicit id, or
        # the router's tokenized longest-match against the catalog (the
        # fallback for clients that know nothing about prefixes)
        prefix_id = P.parse_prefix_id(payload)
        if prefix_id is None and self._prefix_catalog:
            prefix_id = match_prefix(prompt, self._prefix_catalog)
        try:
            # absent = "standard" (old wires unchanged); an unknown
            # class is a request-scoped error, not a silent downgrade
            cls = P.parse_class(payload)
        except ValueError as e:
            conn.send(P.ERROR, rid, P.pack_json({"message": str(e)}))
            return
        key = (conn.id, rid)
        # duplicate-rid reply goes out AFTER the lock is dropped: the
        # send can block on a slow client and this lock is the router's
        # whole control plane (TL001)
        with self._lock:
            duplicate = key in self._sessions
            if not duplicate:
                sess = _RouterSession(conn, rid, prompt, max_new,
                                      trace_ctx=P.parse_trace_ctx(payload),
                                      prefix_id=prefix_id,
                                      stream=next(self._next_stream),
                                      cls=cls)
                self._sessions[key] = sess
        if duplicate:
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": f"request id {rid} is already active"}))
            return
        t0 = time.perf_counter()
        placed = self._place(sess, exclude=None)
        self._place_h.observe(time.perf_counter() - t0)
        if not placed:
            with self._lock:
                self._sessions.pop(key, None)
            conn.send(P.ERROR, rid, P.pack_json(
                {"message": "no live replicas"}))

    def _place(self, sess: _RouterSession, exclude) -> bool:
        """Assign (or re-assign) a session to the least-loaded replica;
        the replica prompt carries the already-streamed prefix so a
        failover continues exactly where the stream left off, and the
        session's pinned rng stream rides the ADMIT with the streamed
        count as its offset — SAMPLED continuations are token-identical
        too. In disaggregated mode the placement is a PAIR: the ADMIT
        goes to a prefill link naming a decode link's channel endpoint,
        and TOKENS will flow back over the decode link. A failed ADMIT
        send is handled HERE (tear the link down, retry on the next
        replica): the link's reader thread may already have run its
        one-shot ``_replica_down`` sweep before this session was
        registered, so relying on it would strand the session."""
        if self._disagg:
            plink = self._pick_link(exclude=exclude, role="prefill",
                                    prefer_prefix=sess.prefix_id,
                                    prefer_version=sess.pinned_version)
            dlink = self._pick_link(exclude=exclude, role="decode",
                                    prefer_version=sess.pinned_version,
                                    cls=sess.cls)
            if plink is None or dlink is None:
                return False
            admit_link, token_link = plink, dlink
        else:
            plink = None
            admit_link = token_link = self._pick_link(
                exclude=exclude, prefer_prefix=sess.prefix_id,
                prefer_version=sess.pinned_version, cls=sess.cls)
            if admit_link is None:
                return False
        if sess.prefix_id is not None:
            # the placement's prefix outcome: resident (the prefill
            # pays only the suffix) or blind (a cold/ring fleet)
            if sess.prefix_id in admit_link.prefixes:
                self._prefix_hits_c.inc()
            else:
                self._prefix_misses_c.inc()
        rrid = next(self._next_rrid)
        with self._lock:
            # the session may have died while it was between homes: a
            # client disconnect removed it from _sessions (re-admitting
            # would burn a survivor's slot generating into a closed
            # connection), or a CANCEL raced the failover
            if self._sessions.get((sess.conn.id, sess.crid)) is not sess:
                return True
            if sess.cancelled:
                self._sessions.pop((sess.conn.id, sess.crid), None)
                doomed = True
            else:
                doomed = False
                sess.link = token_link
                sess.prefill_link = plink
                sess.handed_off = False
                sess.rrid = rrid
                if sess.pinned_version is None:
                    sess.pinned_version = token_link.weights_version
                self._by_rrid[rrid] = sess
                token_link.assigned += 1
                if plink is not None:
                    plink.assigned += 1
        if doomed:
            sess.conn.send(P.RETIRED, sess.crid, P.pack_json(
                {"reason": "cancelled", "tokens": len(sess.streamed)}))
            return True
        self._placed_c[admit_link.addr].inc()
        if plink is not None:
            self._placed_c[token_link.addr].inc()
        # the router's hop in the request trace: placement decision +
        # forwarded ADMIT, as a child of the client's span (only traced
        # requests — an orphan root per placement would be noise)
        if sess.trace_ctx is not None:
            from tony_tpu.runtime import tracing
            attrs = {"replica": admit_link.addr,
                     "failover": bool(sess.streamed)}
            if plink is not None:
                attrs["decode"] = token_link.addr
            tracing.get_tracer().record_span(
                "router.place", 0.0, ctx=sess.trace_ctx, **attrs)
        body = {"prompt": sess.prompt + sess.streamed,
                "max_new_tokens": sess.budget - len(sess.streamed),
                "stream": True,
                # the session's rng pin: same stream index on every
                # placement, offset = tokens already delivered — a
                # SAMPLED continuation regenerates the identical
                # sequence on any replica sharing the fleet seed
                "rng": {"stream": sess.stream,
                        "off": len(sess.streamed)}}
        if sess.cls != "standard":
            # old wires unchanged: the class field rides only when it
            # says something non-default
            body["class"] = sess.cls
        if sess.prefix_id is not None:
            # forwarded on failover re-placements too: the streamed
            # prefix folds in AFTER the shared prefix, so the re-placed
            # prompt still continues it (replicas verify the tokens
            # before taking the fast path regardless)
            body["prefix"] = sess.prefix_id
        if plink is not None:
            # the KV shipment target: the decode gang's channel hub
            host = token_link.addr.rpartition(":")[0]
            body["decode"] = f"{host}:{token_link.channel_port}"
        if sess.trace_ctx is not None:
            body["trace"] = sess.trace_ctx
        ok = admit_link.send(P.ADMIT, rrid, P.pack_json(body))
        if not ok:
            # re-place ONLY if this placement still owns the session:
            # the link's down-sweep may have re-placed it already (it
            # can run between our registration and the failed send),
            # and a second placement would double-serve the request
            with self._lock:
                still_mine = (self._by_rrid.get(rrid) is sess
                              and sess.link is token_link)
                if still_mine:
                    self._by_rrid.pop(rrid, None)
                    self._unassign_locked(sess)
            admit_link.alive = False
            admit_link.close()
            self._replica_down(admit_link)  # idempotent; sweeps others
            if not still_mine:
                return True                 # the sweep owns it now
            return self._place(sess, exclude=admit_link)
        return True

    # -- planned migration (drain / upgrade) ---------------------------------
    def _migrate_session(self, sess: _RouterSession, exclude=()) -> bool:
        """Start a coordinated live migration of one session: place it
        a SECOND time on a surviving replica (prompt + streamed prefix,
        rng pinned at the snapshot offset) while the old placement
        keeps streaming. The new placement's first delta is the
        takeover ACK (see :meth:`_replica_delta`); until then the
        session cannot stall — the old side never stopped. Returns True
        when a migration is in flight (started here or already),
        False when the session has nothing to migrate (retired,
        cancelled, budget-complete, or a disaggregated session still
        pre-handoff — the handoff lands in milliseconds and the drain
        loop's next tick catches it) or no eligible replica exists."""
        with self._lock:
            if self._sessions.get((sess.conn.id, sess.crid)) is not sess:
                return False                # already terminal
            if sess.cancelled:
                return False
            if sess.migrating is not None:
                return True                 # already on its way
            if sess.link is None:
                return False                # between homes; sweep owns it
            if self._disagg and not sess.handed_off:
                return False                # prompt still on the prefill tier
            if len(sess.streamed) >= sess.budget:
                return False                # retirement already due
            old_token = sess.link
            # the snapshot the new ADMIT carries: tokens the old side
            # streams AFTER this become the regenerated overlap the ACK
            # discards count-exactly
            snap_len = len(sess.streamed)
            prompt = sess.prompt + sess.streamed
            prefix_id = sess.prefix_id
            trace_ctx = sess.trace_ctx
            stream = sess.stream
            pinned = sess.pinned_version
            budget = sess.budget
            cls = sess.cls
        ex = set(exclude)
        ex.add(old_token)
        if self._disagg:
            plink = self._pick_link(exclude=ex, role="prefill",
                                    prefer_prefix=prefix_id,
                                    prefer_version=pinned)
            dlink = self._pick_link(exclude=ex, role="decode",
                                    prefer_version=pinned, cls=cls)
            if plink is None or dlink is None:
                return False
            admit_link, token_link = plink, dlink
        else:
            plink = None
            admit_link = token_link = self._pick_link(
                exclude=ex, prefer_prefix=prefix_id,
                prefer_version=pinned, cls=cls)
            if admit_link is None:
                return False
        new_rrid = next(self._next_rrid)
        mig = _Migration(snap_len, token_link, plink, new_rrid)
        with self._lock:
            # re-validate: the session may have retired, cancelled, or
            # crash-failed-over to a DIFFERENT link while we were
            # picking — a stale snapshot must not admit
            if (self._sessions.get((sess.conn.id, sess.crid)) is not sess
                    or sess.cancelled or sess.migrating is not None
                    or sess.link is not old_token):
                return False
            # the picked links may have died between the pick and here
            # (their down-sweep could have run before we registered, so
            # it would never see this migration)
            if not token_link.alive or (
                    plink is not None and not plink.alive):
                return False
            sess.migrating = mig
            self._by_rrid[new_rrid] = sess
            token_link.assigned += 1
            if plink is not None:
                plink.assigned += 1
        self._placed_c[admit_link.addr].inc()
        if plink is not None:
            self._placed_c[token_link.addr].inc()
        if trace_ctx is not None:
            from tony_tpu.runtime import tracing
            attrs = {"replica": admit_link.addr, "snap_len": snap_len}
            if plink is not None:
                attrs["decode"] = token_link.addr
            tracing.get_tracer().record_span(
                "router.migrate", 0.0, ctx=trace_ctx, **attrs)
        body = {"prompt": prompt,
                "max_new_tokens": budget - snap_len,
                "stream": True,
                "rng": {"stream": stream, "off": snap_len}}
        if cls != "standard":
            body["class"] = cls
        if prefix_id is not None:
            body["prefix"] = prefix_id
        if plink is not None:
            host = token_link.addr.rpartition(":")[0]
            body["decode"] = f"{host}:{token_link.channel_port}"
        if trace_ctx is not None:
            body["trace"] = trace_ctx
        if not admit_link.send(P.ADMIT, new_rrid, P.pack_json(body)):
            # roll the second placement back (guarded: the link's
            # down-sweep may have abandoned it for us already) and let
            # the drain loop retry on whatever survives
            with self._lock:
                if self._by_rrid.get(new_rrid) is sess:
                    self._by_rrid.pop(new_rrid, None)
                    for l in {token_link, plink}:
                        if l is not None:
                            l.assigned -= 1
                    if sess.migrating is mig:
                        sess.migrating = None
            admit_link.alive = False
            admit_link.close()
            self._replica_down(admit_link)
            return False
        return True

    def drain(self, replica: str, timeout_s: float = 120.0,
              poll_interval_s: float = 0.05) -> dict:
        """Fence ``replica`` against new placements and live-migrate
        every session off it (planned maintenance / rolling upgrade —
        the zero-dup/zero-drop counterpart of crash failover). Blocks
        until the replica holds no sessions or ``timeout_s`` passes;
        a replica with no sessions drains immediately. The fence stays
        after the drain — lift it with :meth:`remove_replica` (retire)
        or :meth:`undrain` (maintenance cancelled). Returns a summary:
        ``{"replica", "drained", "migrated", "wall_s"}`` plus
        ``"sessions_left"`` on timeout. A session whose migration is
        abandoned (its target died mid-flight) is retried on the next
        poll tick; one that cannot be placed anywhere keeps streaming
        on the draining replica — a drain never degrades a live
        stream."""
        t0 = time.perf_counter()
        with self._lock:
            targets = [l for l in self._links if l.addr == replica]
            for l in targets:
                l.draining = True
        if not targets:
            return {"replica": replica, "drained": False, "migrated": 0,
                    "wall_s": 0.0, "error": "unknown replica"}
        tset = {id(l) for l in targets}
        migrated = 0
        deadline = t0 + timeout_s
        while True:
            with self._lock:
                pending = [
                    s for s in self._sessions.values()
                    if (s.link is not None and id(s.link) in tset)
                    or (s.prefill_link is not None
                        and id(s.prefill_link) in tset
                        and not s.handed_off)]
                busy = {id(s) for s in pending
                        if s.migrating is not None}
            if not pending:
                break
            for s in pending:
                if id(s) in busy:
                    continue
                if self._migrate_session(s):
                    migrated += 1
            if time.perf_counter() >= deadline:
                self._drains_c.inc()
                return {"replica": replica, "drained": False,
                        "migrated": migrated,
                        "sessions_left": len(pending),
                        "wall_s": round(time.perf_counter() - t0, 4)}
            if self._stopping.wait(poll_interval_s):
                # router stopping under the drain: stop() sweeps every
                # session to a terminal ERROR; report honestly
                return {"replica": replica, "drained": False,
                        "migrated": migrated,
                        "sessions_left": len(pending),
                        "wall_s": round(time.perf_counter() - t0, 4),
                        "error": "router stopping"}
        self._drains_c.inc()
        return {"replica": replica, "drained": True,
                "migrated": migrated,
                "wall_s": round(time.perf_counter() - t0, 4)}

    def undrain(self, replica: str) -> None:
        """Lift a drain fence (maintenance cancelled): the replica
        takes new placements again."""
        with self._lock:
            for l in self._links:
                if l.addr == replica:
                    l.draining = False

    def _drain_and_reply(self, conn: FrameConn, rid: int, replica: str,
                         timeout_s: float) -> None:
        """Run a remote-requested drain and reply on its rid (its own
        thread: a drain blocks for its wall time, and the operator
        connection's reader must keep serving other frames)."""
        try:
            result = self.drain(replica, timeout_s=timeout_s)
        except Exception as e:           # noqa: BLE001 - reply, don't die
            log.warning("remote-requested drain of %s failed: %s",
                        replica, e)
            conn.send(P.ERROR, rid,
                      P.pack_json({"message": f"drain failed: {e}"}))
            return
        result["ok"] = bool(result.get("drained"))
        conn.send(P.DRAIN, rid, P.pack_json(result))

    def _on_conn_closed(self, conn: FrameConn) -> None:
        cancels = []
        with self._lock:
            doomed = [s for k, s in list(self._sessions.items())
                      if s.conn is conn]
            for s in doomed:
                self._sessions.pop((conn.id, s.crid), None)
                self._by_rrid.pop(s.rrid, None)
                self._unassign_locked(s)
                cancels += [(l, s.rrid)
                            for l in {s.link, s.prefill_link}
                            if l is not None]
                mig = s.migrating
                if mig is not None and not mig.acked:
                    # the second placement of an in-flight migration
                    # dies with the client too
                    self._by_rrid.pop(mig.new_rrid, None)
                    for l in {mig.new_link, mig.new_prefill}:
                        if l is not None:
                            l.assigned -= 1
                    cancels += [(l, mig.new_rrid)
                                for l in {mig.new_link, mig.new_prefill}
                                if l is not None]
        for link, rrid in cancels:
            link.send(P.CANCEL, rrid)

    # -- replica side (link reader threads) ---------------------------------
    def _replica_delta(self, link: _ReplicaLink, rrid: int,
                       toks: list[int]) -> None:
        """Forward a replica delta. During a migration the session's
        tokens arrive on TWO links from two reader threads — the
        session's ``wlock`` serializes append+send per delta so the
        client never sees positions out of order, and the FIRST delta
        from the new placement is the takeover ACK: ownership swaps to
        the new links, the old half gets a tombstoning CANCEL, and the
        regenerated overlap (tokens the old side streamed after the
        migration snapshot — token-identical by the rng pin) is dropped
        count-exactly."""
        with self._lock:
            sess = self._by_rrid.get(rrid)
        if sess is None:
            return
        send = None
        cancels = []
        completed = False
        with sess.wlock:
            with self._lock:
                if self._by_rrid.get(rrid) is not sess:
                    return                  # swept under us
                mig = sess.migrating
                if mig is not None and rrid == mig.new_rrid:
                    if link is not mig.new_link:
                        return              # not the new token link
                    if not mig.acked:
                        # the ACK: the new placement is live — swap
                        # ownership, release the old placement's
                        # assignment counts (BOTH halves,
                        # unconditionally: a stateless prefill link can
                        # serve both placements and was counted twice)
                        mig.acked = True
                        mig.discard = len(sess.streamed) - mig.snap_len
                        old_rrid = sess.rrid
                        cancels = [(l, old_rrid)
                                   for l in {sess.link, sess.prefill_link}
                                   if l is not None]
                        self._by_rrid.pop(old_rrid, None)
                        for l in {sess.link, sess.prefill_link}:
                            if l is not None:
                                l.assigned -= 1
                        sess.link = mig.new_link
                        sess.prefill_link = mig.new_prefill
                        sess.handed_off = mig.handed_off
                        sess.rrid = mig.new_rrid
                        completed = True
                    # drop the regenerated overlap — the client already
                    # has those exact tokens from the old side
                    if mig.discard:
                        drop = min(mig.discard, len(toks))
                        mig.discard -= drop
                        toks = toks[drop:]
                    if mig.discard == 0:
                        sess.migrating = None
                    if toks:
                        sess.streamed.extend(toks)
                        send = toks
                else:
                    if sess.link is not link or rrid != sess.rrid:
                        return              # stale delta after failover
                    sess.streamed.extend(toks)
                    send = toks
            # still under wlock (delta order), outside the router lock
            for l, r in cancels:
                l.send(P.CANCEL, r)
            if send:
                # the class's latency series, observed BEFORE the
                # client send so a slow client socket never pollutes
                # the serving-plane signal (wlock makes t_last safe)
                now = time.monotonic()
                if sess.t_last == 0.0:
                    self._ttft_by_cls[sess.cls].observe(
                        now - sess.t_submit)
                else:
                    self._itl_by_cls[sess.cls].observe(
                        (now - sess.t_last) / len(send))
                sess.t_last = now
                sess.conn.send(P.TOKENS, sess.crid, P.pack_tokens(send))
        if completed:
            self._migrations_c.inc()

    def _replica_retired(self, link: _ReplicaLink, rrid: int,
                         reason: str) -> None:
        tombstones = []
        requeue = False
        with self._lock:
            sess = self._by_rrid.pop(rrid, None)
            if sess is None:
                return
            mig = sess.migrating
            if mig is not None and not mig.acked and rrid == mig.new_rrid:
                # the NEW placement of an in-flight migration retired
                # before its first delta (a client CANCEL fanned to it,
                # or an instant eos): abandon the migration SILENTLY —
                # the old placement never stopped streaming and still
                # owns the one terminal frame the client will see
                owns = (mig.new_link is link
                        or (mig.new_prefill is link
                            and not mig.handed_off))
                if not owns or reason == "stopped":
                    self._by_rrid[rrid] = sess
                    return
                for l in {mig.new_link, mig.new_prefill}:
                    if l is not None:
                        l.assigned -= 1
                sess.migrating = None
                return
            # the prefill link speaks for a session it still owns (a
            # CANCEL caught the prompt queued or mid-wave, pre-HANDOFF);
            # after the handoff its frames for this rrid are stale
            owns = (sess.link is link
                    or (sess.prefill_link is link and not sess.handed_off))
            if not owns:
                self._by_rrid[rrid] = sess
                return
            if reason == "preempted" and not sess.cancelled:
                # the replica evicted this row to seat an interactive
                # admission and could NOT fold it into its own queue (a
                # KV-adopted decode row — the prompt lives with the
                # router, not the replica): the ROUTER re-queues.
                # Re-place like a failover — prompt + streamed prefix,
                # rng pinned at the delivered count — so the stream
                # resumes token-identically wherever a slot exists; the
                # evicting replica stays eligible (a fresh placement
                # enters its batch queue and waits its turn).
                self._unassign_locked(sess)
                if mig is not None and not mig.acked:
                    self._by_rrid.pop(mig.new_rrid, None)
                    for l in {mig.new_link, mig.new_prefill}:
                        if l is not None:
                            l.assigned -= 1
                    tombstones = [(l, mig.new_rrid)
                                  for l in {mig.new_link, mig.new_prefill}
                                  if l is not None and l.alive]
                sess.migrating = None
                requeue = True
            elif reason == "stopped":
                # replica is draining/dying under us: keep the session,
                # the link-down path re-places it with the prefix trim
                self._by_rrid[rrid] = sess
                return
            else:
                self._sessions.pop((sess.conn.id, sess.crid), None)
                self._unassign_locked(sess)
                if mig is not None and not mig.acked:
                    # the OLD side finished the stream (eos/budget/
                    # cancel) before the migration ACKed: the takeover
                    # is moot — tombstone the pending second placement
                    self._by_rrid.pop(mig.new_rrid, None)
                    for l in {mig.new_link, mig.new_prefill}:
                        if l is not None:
                            l.assigned -= 1
                    tombstones = [(l, mig.new_rrid)
                                  for l in {mig.new_link, mig.new_prefill}
                                  if l is not None and l.alive]
                    sess.migrating = None
        for l, r in tombstones:
            l.send(P.CANCEL, r)
        if requeue:
            self._preempt_requeued_c.inc()
            if self._place(sess, exclude=None):
                return
            with self._lock:
                self._sessions.pop((sess.conn.id, sess.crid), None)
            sess.conn.send(P.ERROR, sess.crid, P.pack_json(
                {"message": "no live replicas"}))
            return
        sess.conn.send(P.RETIRED, sess.crid, P.pack_json(
            {"reason": reason, "tokens": len(sess.streamed)}))

    def _replica_handoff(self, link: _ReplicaLink, rrid: int,
                         obj: dict) -> None:
        """The prefill tier shipped this session's KV package: its fate
        now rides the decode link alone — a prefill replica dying after
        this frame costs the session nothing."""
        with self._lock:
            sess = self._by_rrid.get(rrid)
            if sess is None:
                return
            mig = sess.migrating
            if mig is not None and not mig.acked and rrid == mig.new_rrid:
                # the migration's second placement handed off — ITS
                # prefill half is out of the fate path (recorded on the
                # migration; the ACK swap copies it onto the session)
                if mig.new_prefill is not link:
                    return
                mig.handed_off = True
            else:
                if sess.prefill_link is not link or rrid != sess.rrid:
                    return                  # stale (failover re-placed)
                sess.handed_off = True
        self._handoffs_c.inc()

    def _replica_error(self, link: _ReplicaLink, rrid: int, msg: str,
                       retryable: bool = False) -> None:
        """A replica failed this session. ``retryable`` (the prefill
        tier's kv-ship-failure marker) means the fault is the session's
        PLACEMENT, not the request: re-place it away from the decode
        link the shipment could not reach — the same contract as losing
        that decode link outright, just noticed by the prefill tier
        first."""
        tombstones = []
        with self._lock:
            sess = self._by_rrid.pop(rrid, None)
            if sess is None:
                return
            mig = sess.migrating
            if mig is not None and not mig.acked and rrid == mig.new_rrid:
                # the migration's second placement failed before taking
                # over: abandon it silently — the old half never
                # stopped streaming; the drain loop just retries
                for l in {mig.new_link, mig.new_prefill}:
                    if l is not None:
                        l.assigned -= 1
                sess.migrating = None
                return
            self._unassign_locked(sess)
            if mig is not None and not mig.acked:
                # the OWNING placement failed mid-migration: the
                # pending takeover is torn down with it — the failover
                # re-placement below restarts from the full streamed
                # prefix (never from the stale migration snapshot)
                self._by_rrid.pop(mig.new_rrid, None)
                for l in {mig.new_link, mig.new_prefill}:
                    if l is not None:
                        l.assigned -= 1
                tombstones = [(l, mig.new_rrid)
                              for l in {mig.new_link, mig.new_prefill}
                              if l is not None and l.alive]
            # any residual migration state (including a post-ACK
            # discard countdown) dies with the placement: the failover
            # re-placement below restarts from the full streamed prefix
            sess.migrating = None
            old_link = sess.link
            retry = retryable and not sess.cancelled
            if not retry:
                self._sessions.pop((sess.conn.id, sess.crid), None)
        for l, r in tombstones:
            l.send(P.CANCEL, r)
        if retry:
            # tombstone the old rrid on the decode link the shipment
            # could not (verifiably) reach: "unreachable" may be a
            # delivered frame whose ack timed out, and without the
            # CANCEL a late adoption would burn a decode slot streaming
            # into a stale rrid (same contract as _replica_down's sweep
            # of the surviving half)
            if (old_link is not None and old_link is not link
                    and old_link.alive):
                old_link.send(P.CANCEL, rrid)
            self._failovers_c.inc()
            if self._place(sess, exclude=old_link):
                return
            with self._lock:
                self._sessions.pop((sess.conn.id, sess.crid), None)
            msg = "no live replicas"
        sess.conn.send(P.ERROR, sess.crid, P.pack_json({"message": msg}))

    def _replica_busy(self, link: _ReplicaLink, rrid: int,
                      retry_after_ms: int) -> None:
        """A replica shed this session's admission (its wait queue is
        past the overload bound). BATCH sessions are the router's to
        re-queue: re-place away from the shedding replica, capped at
        :data:`BUSY_REQUEUE_CAP` sheds per session — when every replica
        is saying "come back later", the client should hear it. For
        every other class the shed PROPAGATES: BUSY is terminal for the
        rid and the retry hint rides through untouched."""
        with self._lock:
            sess = self._by_rrid.pop(rrid, None)
            if sess is None:
                return
            mig = sess.migrating
            if mig is not None and not mig.acked and rrid == mig.new_rrid:
                # the migration's second placement was shed: abandon it
                # silently — the old half never stopped streaming; the
                # drain loop just retries a less-loaded target
                for l in {mig.new_link, mig.new_prefill}:
                    if l is not None:
                        l.assigned -= 1
                sess.migrating = None
                return
            self._unassign_locked(sess)
            sess.migrating = None
            sess.busy_retries += 1
            retry = (sess.cls == "batch" and not sess.cancelled
                     and sess.busy_retries <= BUSY_REQUEUE_CAP)
            if not retry:
                self._sessions.pop((sess.conn.id, sess.crid), None)
        if retry:
            self._busy_requeued_c.inc()
            if self._place(sess, exclude=link):
                return
            with self._lock:
                self._sessions.pop((sess.conn.id, sess.crid), None)
        sess.conn.send(P.BUSY, sess.crid, P.pack_json(
            {"retry_after_ms": retry_after_ms}))

    def _replica_down(self, link: _ReplicaLink) -> None:
        """Replica loss: drain its sessions onto survivors, streamed
        prefix trimmed into the prompt, remaining budget only. In
        disaggregated mode a DECODE loss orphans every session
        streaming from it (they re-prefill — prompt + streamed prefix —
        through a surviving prefill replica toward a surviving decode
        replica); a PREFILL loss orphans only sessions it had NOT yet
        handed off (post-HANDOFF sessions live on the decode gang and
        keep streaming)."""
        with self._lock:
            if id(link) in self._downed:
                return
            self._downed.add(id(link))
        link.alive = False
        link.close()
        self._up_g[link.addr].set(0)
        self._refresh_prefix_residency()
        abandoned = []  # (surviving links, new_rrid): dead migrations
        promoted = []   # (sess, old_rrid, surviving old links): forced ACKs
        orphans = []
        with self._lock:
            seen = set()
            for s in list(self._by_rrid.values()):
                if id(s) in seen:
                    continue                # mapped twice mid-migration
                seen.add(id(s))
                mig = s.migrating
                if (mig is not None and not mig.acked
                        and (mig.new_link is link
                             or (mig.new_prefill is link
                                 and not mig.handed_off))):
                    # a pending migration TARGETED the dead replica:
                    # abandon it — the old placement never stopped
                    # streaming; the drain loop just retries
                    self._by_rrid.pop(mig.new_rrid, None)
                    for l in {mig.new_link, mig.new_prefill}:
                        if l is not None:
                            l.assigned -= 1
                    abandoned.append((
                        [l for l in {mig.new_link, mig.new_prefill}
                         if l is not None and l is not link and l.alive],
                        mig.new_rrid))
                    s.migrating = None
                    mig = None
                hit = (s.link is link
                       or (s.prefill_link is link and not s.handed_off))
                if not hit:
                    continue
                if (mig is not None and not mig.acked
                        and mig.new_link.alive
                        and (mig.new_prefill is None or mig.handed_off
                             or mig.new_prefill.alive)):
                    # the OLD half died while a migration toward a
                    # healthy target was pending: PROMOTE it — a forced
                    # ACK. No re-placement, no re-prefill: the new side
                    # is already computing, its deltas just haven't
                    # landed yet; the discard countdown drops the
                    # overlap exactly as a delta-ACK would.
                    old_rrid = s.rrid
                    survivors = [l for l in {s.link, s.prefill_link}
                                 if l is not None and l is not link
                                 and l.alive]
                    mig.acked = True
                    mig.discard = len(s.streamed) - mig.snap_len
                    self._by_rrid.pop(old_rrid, None)
                    for l in {s.link, s.prefill_link}:
                        if l is not None:
                            l.assigned -= 1
                    s.link = mig.new_link
                    s.prefill_link = mig.new_prefill
                    s.handed_off = mig.handed_off
                    s.rrid = mig.new_rrid
                    if mig.discard == 0:
                        s.migrating = None
                    promoted.append((s, old_rrid, survivors))
                    continue
                if mig is not None and not mig.acked:
                    # pending migration whose target ALSO already died:
                    # tear both placements down, re-place fresh below
                    self._by_rrid.pop(mig.new_rrid, None)
                    for l in {mig.new_link, mig.new_prefill}:
                        if l is not None:
                            l.assigned -= 1
                    abandoned.append((
                        [l for l in {mig.new_link, mig.new_prefill}
                         if l is not None and l is not link and l.alive],
                        mig.new_rrid))
                s.migrating = None
                self._by_rrid.pop(s.rrid, None)
                self._unassign_locked(s)
                orphans.append(s)
        for links_, new_rrid in abandoned:
            # tombstone the surviving half of a torn-down second
            # placement (a queued prompt / a pre-adoption rid)
            for l in links_:
                l.send(P.CANCEL, new_rrid)
        for s, old_rrid, survivors in promoted:
            for l in survivors:
                l.send(P.CANCEL, old_rrid)
            self._migrations_c.inc()
        if promoted:
            log.warning("router: replica %s (%s) down; promoted %d "
                        "in-flight migrations", link.addr, link.role,
                        len(promoted))
        if orphans:
            log.warning("router: replica %s (%s) down; re-admitting %d "
                        "sessions", link.addr, link.role, len(orphans))
        for sess in orphans:
            # the surviving half of a split placement holds stale work
            # for the old rrid: tell it to drop (the prefill tier
            # unqueues the prompt; the decode tier tombstones the rid
            # so a late shipment is never adopted)
            for other in {sess.link, sess.prefill_link}:
                if (other is not None and other is not link
                        and other.alive):
                    other.send(P.CANCEL, sess.rrid)
            if sess.cancelled:
                # the client already asked for this session's death —
                # finishing it as cancelled beats resurrecting it on a
                # survivor with full remaining budget
                with self._lock:
                    self._sessions.pop((sess.conn.id, sess.crid), None)
                sess.conn.send(P.RETIRED, sess.crid, P.pack_json(
                    {"reason": "cancelled",
                     "tokens": len(sess.streamed)}))
                continue
            if len(sess.streamed) >= sess.budget:
                # fully streamed; only the RETIRED frame was lost
                with self._lock:
                    self._sessions.pop((sess.conn.id, sess.crid), None)
                sess.conn.send(P.RETIRED, sess.crid, P.pack_json(
                    {"reason": "budget", "tokens": len(sess.streamed)}))
                continue
            self._failovers_c.inc()
            if not self._place(sess, exclude=link):
                with self._lock:
                    self._sessions.pop((sess.conn.id, sess.crid), None)
                sess.conn.send(P.ERROR, sess.crid, P.pack_json(
                    {"message": "no live replicas"}))

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        """Router stats snapshot. Carries the protocol-mandated STATS
        fields (``queue_depth``/``active``/``slots``, here the fleet
        aggregates — a router can front another router) plus the
        per-replica detail."""
        with self._lock:
            live = [l for l in self._links if l.alive]
            return {
                "queue_depth": sum(l.reported_load for l in live),
                "active": len(self._sessions),
                # in disaggregated mode only decode slots hold rows —
                # prefill "slots" are wave widths, not capacity
                "slots": sum(int(l.hello.get("slots", 0))
                             for l in live
                             if not self._disagg or l.role == "decode"),
                "sessions": len(self._sessions),
                # fleet-aggregated per-class backlog (classless
                # replicas contribute nothing — they never report it)
                "queue_depths": {
                    c: sum(l.queue_depths.get(c, 0) for l in live)
                    for c in P.QOS_CLASSES},
                "disaggregated": self._disagg,
                "prefixes": sorted(self._prefix_catalog),
                "replicas": {
                    l.addr: {"up": int(l.alive),
                             "reported_load": l.reported_load,
                             "queue_depths": dict(l.queue_depths),
                             "assigned": l.assigned,
                             "role": l.role,
                             "draining": bool(l.draining),
                             "weights_version": l.weights_version,
                             "weights_digest": l.weights_digest,
                             "prefixes": sorted(l.prefixes),
                             "ring": l.ring}
                    for l in self._links},
            }
