"""Streaming serving data plane: persistent token-push protocol,
engine-backed server, multiplexing client, multi-replica router.

The layer between the in-process serving engine
(``tony_tpu.models.serve.ServeEngine`` — the open-loop
issue/fetch/consume/settle loop over a live admission queue) and
clients on the network:

  protocol — TONYS1 length-prefixed frame codec (ADMIT/CANCEL/POLL
             client→server; TOKENS/RETIRED/ERROR/STATS/HELLO
             server→client), multiplexed request ids on one
             persistent connection
  server   — ServingServer: per-connection reader threads feed the
             engine's live queue; engine delta callbacks push TOKENS
             frames the moment each chunk is consumed
  client   — StreamingClient: submit/cancel/stream many requests over
             one connection (jax-free — runs on gateway hosts)
  router   — ServingRouter: front door spreading sessions across N
             replica servers by the ``tony_serve_queue_depth`` gauge,
             health-checking them, and draining a lost replica's
             sessions onto survivors with the streamed prefix trimmed;
             disaggregated placement mode (``decode_replicas=``) splits
             ADMIT placement (prefill tier) from token streaming
             (decode tier)
  disagg   — PrefillServer / DecodeServer: the two tiers of
             disaggregated serving — prefill gangs ship KV packages to
             decode gangs over TONYC1 tensor channels (kvship is the
             jax-free wire codec), so decode chunks are never preempted
             by prefill compute
  netem    — LatencyProxy: deterministic per-direction latency
             injection for the streamed-vs-request/response bench arm
  fleet    — FleetController + CapacityProvider: metrics-driven
             autoscale (grow/drain/release) and rolling weight
             upgrades over a running router
  simfleet — SimFleet / SimReplica: deterministic simulated replicas
             (token oracle, no model stack) for fleet-scale chaos and
             migration-storm tests

``server`` pulls in the model stack (jax); ``protocol``/``client``/
``router``/``netem`` are stdlib-only, so the lazy re-exports below
keep thin-client imports cheap.
"""

from tony_tpu.serving.protocol import ProtocolError

_LAZY = {
    "ServingServer": ("tony_tpu.serving.server", "ServingServer"),
    "StreamingClient": ("tony_tpu.serving.client", "StreamingClient"),
    "ServingConnectionError": ("tony_tpu.serving.client",
                               "ServingConnectionError"),
    "ServingRouter": ("tony_tpu.serving.router", "ServingRouter"),
    "LatencyProxy": ("tony_tpu.serving.netem", "LatencyProxy"),
    "PrefillServer": ("tony_tpu.serving.disagg", "PrefillServer"),
    "DecodeServer": ("tony_tpu.serving.disagg", "DecodeServer"),
    "FleetController": ("tony_tpu.serving.fleet", "FleetController"),
    "CapacityProvider": ("tony_tpu.serving.fleet", "CapacityProvider"),
    "SimFleet": ("tony_tpu.serving.simfleet", "SimFleet"),
    "SimReplica": ("tony_tpu.serving.simfleet", "SimReplica"),
}

__all__ = ["ProtocolError", *_LAZY]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(
        f"module 'tony_tpu.serving' has no attribute {name!r}")
