"""Metrics-driven fleet control: autoscale + rolling upgrades over a
:class:`~tony_tpu.serving.router.ServingRouter`.

The :class:`FleetController` closes the loop the serving metrics plane
opened: it consumes the load signals the router already aggregates from
replica STATS (``tony_serve_queue_depth`` / ``tony_prefill_queue_depth``
per replica, idle decode slots — the same numbers behind
``tony_router_replica_queue_depth``) and turns them into fleet actions:

- **scale up** when sustained queue depth per replica crosses
  ``up_queue_per_replica`` — ask the :class:`CapacityProvider` for more
  replicas and :meth:`~ServingRouter.add_replicas` them live;
- **scale down** when sustained utilization falls under
  ``down_utilization`` — pick the least-loaded replica,
  :meth:`~ServingRouter.drain` it (planned migration, zero dup/drop),
  retire it from the router, and release it back to the provider;
- **rolling upgrade**: stand the new-version tier up, drain the old
  tier replica by replica, retire it — sessions live-migrate with
  version-pinned placement, so no stream ever mixes weight generations.

Decisions are HYSTERETIC and rate-limited by design: a threshold must
hold for ``hysteresis_ticks`` consecutive ticks, and any action starts
a ``cooldown_ticks`` quiet period. The sim harness pins that the
controller does not flap on an oscillating load signal
(tests/test_fleet.py).

Capacity comes from a pluggable :class:`CapacityProvider`: the local
backend spawns/reaps real replica processes; a TPU-backed provider
returns slices to the pool instead. The provider only creates and
destroys capacity — all session safety (fence, migrate, tombstone)
lives in the router's drain path.

Controller series (default registry): ``tony_fleet_replicas``,
``tony_fleet_load_per_replica``, ``tony_fleet_scale_ups_total``,
``tony_fleet_scale_downs_total``, ``tony_fleet_upgrades_total``.
"""

from __future__ import annotations

import logging
import re
import subprocess
import threading
import time

from tony_tpu.runtime import metrics as metrics_mod

log = logging.getLogger(__name__)


class CapacityProvider:
    """Where replicas come from and where they go back to. ``grow``
    returns the new replicas' ``host:port`` addresses once they accept
    connections; ``release`` reaps them AFTER the router drained and
    retired them (the provider never sees live sessions)."""

    def grow(self, n: int) -> list:
        raise NotImplementedError

    def release(self, addrs) -> None:
        raise NotImplementedError


class SubprocessProvider(CapacityProvider):
    """Local capacity = real replica processes. ``argv`` launches ONE
    replica that prints its serving address on stdout (matched by
    ``addr_pattern``, default the ``serving on host:port`` line the
    stock servers log). ``release`` terminates the process behind the
    address."""

    def __init__(self, argv, addr_pattern: str = r"on ([\d.]+:\d+)",
                 spawn_timeout_s: float = 60.0) -> None:
        self.argv = list(argv)
        self.addr_re = re.compile(addr_pattern)
        self.spawn_timeout_s = spawn_timeout_s
        self._procs: dict = {}              # addr -> Popen

    def grow(self, n: int) -> list:
        addrs = []
        for _ in range(n):
            proc = subprocess.Popen(
                self.argv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            addr = None
            deadline = time.monotonic() + self.spawn_timeout_s
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                m = self.addr_re.search(line)
                if m:
                    addr = m.group(1)
                    break
            if addr is None:
                proc.terminate()
                raise RuntimeError(
                    f"replica process printed no address within "
                    f"{self.spawn_timeout_s}s: {self.argv}")
            self._procs[addr] = proc
            addrs.append(addr)
        return addrs

    def release(self, addrs) -> None:
        for addr in addrs:
            proc = self._procs.pop(addr, None)
            if proc is None:
                continue
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


class FleetController:
    """Close the metrics->capacity loop over a running router.

    ``tick()`` is one pure decision step (the sim harness drives it
    directly, deterministically); ``start()`` runs it on a timer
    thread. Thresholds:

    - ``up_queue_per_replica``: mean reported load per live replica
      that, sustained, triggers a scale-up of ``step`` replicas.
    - ``down_utilization``: active-sessions / decode-slots floor below
      which, sustained, one replica is drained and released.
    - ``hysteresis_ticks``: consecutive out-of-band ticks required
      before acting (a one-tick spike never scales).
    - ``cooldown_ticks``: quiet ticks after ANY action (scaling churn
      is worse than brief over/under-capacity: every scale-down is a
      migration storm someone must absorb).
    - ``min_replicas`` / ``max_replicas``: hard clamps.
    """

    def __init__(self, router, provider: CapacityProvider,
                 min_replicas: int = 1, max_replicas: int = 16,
                 up_queue_per_replica: float = 4.0,
                 down_utilization: float = 0.3,
                 hysteresis_ticks: int = 3, cooldown_ticks: int = 10,
                 step: int = 1, interval_s: float = 1.0,
                 drain_timeout_s: float = 120.0,
                 registry=None, warmer=None) -> None:
        self.router = router
        self.provider = provider
        self.warmer = warmer                # FleetWarmer or None (cold admit)
        self.last_warm: dict | None = None  # summary of the last warm pass
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_queue_per_replica = float(up_queue_per_replica)
        self.down_utilization = float(down_utilization)
        self.hysteresis_ticks = int(hysteresis_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self.step = int(step)
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._over = 0                      # consecutive over-threshold ticks
        self._under = 0                     # consecutive under-threshold ticks
        self._cooldown = 0
        self._thread: threading.Thread | None = None
        self._stopping = threading.Event()
        reg = registry or metrics_mod.get_default()
        self._replicas_g = reg.gauge(
            "tony_fleet_replicas",
            help="live replicas under fleet control")
        self._load_g = reg.gauge(
            "tony_fleet_load_per_replica",
            help="mean SLO-relevant load per live replica (busy slots "
                 "+ interactive/standard backlog; batch backlog "
                 "excluded for class-aware replicas) — the scale-up "
                 "signal")
        self._ups_c = reg.counter(
            "tony_fleet_scale_ups_total",
            help="scale-up actions taken (replicas added = actions x "
                 "step)")
        self._downs_c = reg.counter(
            "tony_fleet_scale_downs_total",
            help="scale-down actions taken (each = one drained, "
                 "retired, released replica)")
        self._upgrades_c = reg.counter(
            "tony_fleet_upgrades_total",
            help="rolling weight upgrades completed (old tier fully "
                 "drained and retired)")

    # -- one decision step ---------------------------------------------------
    def _observe(self) -> tuple:
        """(live replica count, mean load per replica, utilization) —
        read from the router's STATS aggregation, the same numbers the
        ``tony_router_replica_*`` gauges export.

        Class-aware replicas report per-class ``queue_depths``; for
        those the scale-up signal counts busy slots plus ONLY the
        latency-sensitive backlog (interactive + standard). A deep
        batch queue is deliberate oversubscription — it is what the
        batch tier is FOR — and must never page in capacity on its
        own. Classless replicas keep the aggregate ``reported_load``
        fallback, so mixed fleets and old engines behave exactly as
        before."""
        st = self.router.stats()
        reps = [r for r in st["replicas"].values() if r["up"]]
        n = len(reps)
        total = 0.0
        for r in reps:
            depths = r.get("queue_depths") or {}
            # reported_load = waiting + busy slots, and waiting is the
            # sum of the class depths — so subtracting the batch depth
            # leaves busy slots + interactive/standard backlog
            total += max(0, r["reported_load"] - depths.get("batch", 0))
        load = (total / n) if n else 0.0
        slots = st.get("slots", 0)
        util = (st.get("active", 0) / slots) if slots else 1.0
        return n, load, util

    def tick(self) -> str:
        """Run one decision step; returns the action taken:
        ``"up"``, ``"down"``, or ``"hold"``."""
        n, load, util = self._observe()
        self._replicas_g.set(n)
        self._load_g.set(load)
        if self._cooldown > 0:
            self._cooldown -= 1
            return "hold"
        self._over = self._over + 1 if load > self.up_queue_per_replica \
            else 0
        self._under = self._under + 1 if (
            util < self.down_utilization
            and load < self.up_queue_per_replica) else 0
        if self._over >= self.hysteresis_ticks and n < self.max_replicas:
            self._scale_up(min(self.step, self.max_replicas - n))
            return "up"
        if self._under >= self.hysteresis_ticks and n > self.min_replicas:
            self._scale_down()
            return "down"
        return "hold"

    def _reset(self) -> None:
        self._over = self._under = 0
        self._cooldown = self.cooldown_ticks

    def _scale_up(self, n: int) -> None:
        addrs = self.provider.grow(n)
        addrs = self._warm(addrs)
        if addrs:
            self.router.add_replicas(addrs)
        self._ups_c.inc()
        self._reset()
        log.info("fleet: scaled up by %d (%s)", n, addrs)

    def _warm(self, addrs) -> list:
        """Warm fresh capacity before it takes traffic. With no warmer
        every address is admitted cold (storage load already happened
        in the provider). With one, targets the warm pass could not
        bring up — peer ship failed AND the storage fallback failed —
        are released instead of admitted: a replica that never landed
        the weights would 503 every stream routed at it."""
        if self.warmer is None or not addrs:
            return list(addrs)
        self.last_warm = res = self.warmer.warm(list(addrs))
        failed = list(res.get("failed", ()))
        if failed:
            log.warning("fleet: releasing %d unwarmable replicas (%s)",
                        len(failed), failed)
            self.provider.release(failed)
        dead = set(failed)
        return [a for a in addrs if a not in dead]

    def _scale_down(self) -> None:
        st = self.router.stats()
        candidates = [(r["reported_load"], r["assigned"], addr)
                      for addr, r in st["replicas"].items()
                      if r["up"] and not r["draining"]]
        if len(candidates) <= self.min_replicas:
            return
        _, _, addr = min(candidates)
        res = self.router.drain(addr, timeout_s=self.drain_timeout_s)
        self.router.remove_replica(addr)
        self.provider.release([addr])
        self._downs_c.inc()
        self._reset()
        log.info("fleet: scaled down %s (drain: %s)", addr, res)

    # -- rolling weight upgrade ----------------------------------------------
    def rolling_upgrade(self, new_addrs, old_addrs=None,
                        role: str | None = None) -> dict:
        """Replace the fleet's weights generation without dropping a
        stream: connect ``new_addrs`` (warmed first via the fleet's
        ``warmer`` when one is configured — one storage load seeds the
        tier, peers fan the weights out — otherwise already serving
        the new weights), then drain and retire each OLD replica in
        turn.
        Version-pinned placement keeps existing sessions on their
        generation while any same-version replica survives, and the
        per-replica drains migrate them (zero dup/drop) as their tier
        shrinks. ``old_addrs`` defaults to every replica the router
        knew before the call. Returns per-replica drain summaries."""
        st = self.router.stats()
        if old_addrs is None:
            old_addrs = [a for a, r in st["replicas"].items() if r["up"]]
        old_addrs = [a for a in old_addrs if a not in set(new_addrs)]
        new_addrs = self._warm(list(new_addrs))
        self.router.add_replicas(new_addrs, role=role)
        results = {}
        for addr in old_addrs:
            results[addr] = self.router.drain(
                addr, timeout_s=self.drain_timeout_s)
            self.router.remove_replica(addr)
            self.provider.release([addr])
        self._upgrades_c.inc()
        return results

    # -- timer loop ----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="tony-fleet-controller", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stopping.wait(self.interval_s):
            try:
                self.tick()
            except Exception:               # noqa: BLE001 - keep ticking
                log.exception("fleet controller tick failed")

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
