"""Streaming serving client: one persistent connection, many in-flight
requests, server-pushed token deltas.

The client is transport-only (stdlib, no jax) — a gateway process or a
test can drive a remote serving host (or the router front-door, which
speaks the same protocol) without the model stack installed.

Usage::

    with StreamingClient("10.0.0.5", 7070) as c:
        rid = c.submit(prompt, max_new_tokens=64)
        for delta in c.deltas(rid):        # lists of ints, as pushed
            emit(delta)
        tokens, reason = c.result(rid)     # or: collect in one call

A reader thread demultiplexes frames by request id into per-request
event queues, so any number of threads can stream different requests
concurrently. ``submit(stream=False)`` + ``poll()`` is the long-poll
(request/response-per-chunk) mode — kept as the streaming bench's
contrast arm and for dumb clients.
"""

from __future__ import annotations

import itertools
import queue
import random
import socket
import threading

from tony_tpu.runtime import tracing
from tony_tpu.serving import protocol as P

#: ceiling on one busy-retry backoff sleep — the hint grows
#: exponentially per attempt but never past this (milliseconds)
BUSY_BACKOFF_CAP_MS = 5000


class ServingConnectionError(ConnectionError):
    """The serving connection failed (handshake, mid-stream loss, or a
    connection-scoped server ERROR)."""


class ServerBusy(ServingConnectionError):
    """The server shed this request under overload (the BUSY terminal
    frame): nothing was computed, nothing streamed — re-admit after
    ``retry_after_ms``. Raised only once any ``submit(retries=)``
    budget is exhausted; transparent re-admissions never surface."""

    def __init__(self, retry_after_ms: int) -> None:
        super().__init__(
            f"server busy; retry after {int(retry_after_ms)}ms")
        self.retry_after_ms = int(retry_after_ms)


class StreamingClient:
    def __init__(self, host: str, port: int,
                 connect_timeout: float = 10.0) -> None:
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        P.set_nodelay(self._sock)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._queues: dict[int, queue.Queue] = {}
        #: rid -> (client.request span, client.ttft span) — the
        #: client-side leg of the end-to-end request trace; the span
        #: context rides the ADMIT frame so the router's and engine's
        #: spans join the same trace
        self._spans: dict[int, tuple] = {}
        self._stats_q: queue.Queue = queue.Queue()
        #: rid -> [admit body, retries left, attempts made] — consulted
        #: by the reader thread when a BUSY lands; re-admission rides a
        #: one-shot timer thread so the reader never sleeps
        self._retries: dict[int, list] = {}
        self._next_rid = itertools.count(1)
        self._closed = False
        self._conn_error: str | None = None
        try:
            self._sock.sendall(P.MAGIC)
            hello = P.recv_frame(self._sock)
        except (OSError, P.ProtocolError) as e:
            self._sock.close()
            raise ServingConnectionError(f"handshake failed: {e}") from e
        if hello is None or hello[0] != P.HELLO:
            self._sock.close()
            raise ServingConnectionError("no HELLO from server")
        self.hello = P.unpack_json(hello[2])
        self._reader = threading.Thread(target=self._read_loop,
                                        name="tony-serve-client-reader",
                                        daemon=True)
        self._reader.start()

    # -- wire ---------------------------------------------------------------
    def _send(self, ftype: int, rid: int, payload: bytes = b"") -> None:
        with self._send_lock:
            if self._closed:
                raise ServingConnectionError(
                    self._conn_error or "client is closed")
            try:
                P.send_frame(self._sock, ftype, rid, payload)
            except OSError as e:
                raise ServingConnectionError(str(e)) from e

    def _read_loop(self) -> None:
        error = "connection closed by server"
        try:
            while True:
                frame = P.recv_frame(self._sock)
                if frame is None:
                    break
                ftype, rid, payload = frame
                if ftype == P.TOKENS:
                    self._end_span(rid, ttft_only=True)
                    self._dispatch(rid, ("tokens",
                                         P.unpack_tokens(payload)))
                elif ftype == P.RETIRED:
                    obj = P.unpack_json(payload)
                    self._end_span(rid,
                                   reason=obj.get("reason", "unknown"),
                                   tokens=obj.get("tokens", 0))
                    self._dispatch(rid, ("retired",
                                         obj.get("reason", "unknown"),
                                         obj.get("tokens", 0)))
                elif ftype == P.ERROR:
                    msg = P.unpack_json(payload).get("message", "error")
                    if rid == 0:
                        error = f"server error: {msg}"
                        break               # connection-scoped: fatal
                    self._end_span(rid, reason="error")
                    self._dispatch(rid, ("error", msg))
                elif ftype == P.BUSY:
                    obj = P.unpack_json(payload)
                    hint = int(obj.get("retry_after_ms", 0) or 0)
                    if not self._retry_busy(rid, hint):
                        self._end_span(rid, reason="busy")
                        self._dispatch(rid, ("busy", hint))
                elif ftype == P.STATS:
                    self._stats_q.put(P.unpack_json(payload))
                elif ftype == P.PREFIX:
                    self._dispatch(rid, ("prefix",
                                         P.unpack_json(payload)))
                elif ftype == P.DRAIN:
                    self._dispatch(rid, ("drain",
                                         P.unpack_json(payload)))
                # unknown server frames are ignored (forward compat) —
                # including MIGRATE acks: migrate() is fire-and-forget
                # (the migrated stream itself just keeps delivering on
                # its own rid)
        except (P.ProtocolError, OSError) as e:
            error = str(e)
        with self._lock:
            self._closed = True
            self._conn_error = error
            queues = list(self._queues.values())
        fatal = ("error", error)
        for q in queues:
            q.put(fatal)
        self._stats_q.put({"error": error})
        with self._lock:
            spans = list(self._spans)
        for rid in spans:
            self._end_span(rid, reason="connection_lost")

    def _end_span(self, rid: int, ttft_only: bool = False,
                  **attrs) -> None:
        with self._lock:
            pair = self._spans.get(rid)
            if pair is None:
                return
            if not ttft_only:
                del self._spans[rid]
        pair[1].end()                      # first TOKENS frame = TTFT
        if not ttft_only:
            pair[0].end(**attrs)

    def _dispatch(self, rid: int, event: tuple) -> None:
        with self._lock:
            q = self._queues.get(rid)
        if q is not None:
            q.put(event)

    def _retry_busy(self, rid: int, hint_ms: int) -> bool:
        """A BUSY landed for ``rid``: consume one retry if any remain.
        The re-admission is TRANSPARENT — same rid, same event queue,
        same spans (TTFT keeps counting from the original submit, which
        is what the caller experiences) — and rides a one-shot timer
        thread so the reader loop never sleeps through other streams'
        deltas. Returns False when the budget is spent (the BUSY
        surfaces to the consumer)."""
        with self._lock:
            st = self._retries.get(rid)
            if st is None or st[1] <= 0:
                return False
            st[1] -= 1
            attempt, body = st[2], st[0]
            st[2] += 1
        # capped exponential backoff on the server's hint, jittered
        # +/-25% so a shed burst does not re-arrive as a burst
        base = max(int(hint_ms), 1) * (2 ** attempt)
        delay = min(base, BUSY_BACKOFF_CAP_MS) / 1000.0
        delay *= 0.75 + 0.5 * random.random()

        def _readmit() -> None:
            try:
                self._send(P.ADMIT, rid, P.pack_json(body))
            except ServingConnectionError as e:
                self._end_span(rid, reason="send_failed")
                self._dispatch(rid, ("error", str(e)))

        t = threading.Timer(delay, _readmit)
        t.name = f"tony-client-retry-{rid}"
        t.daemon = True
        t.start()
        return True

    # -- request surface ----------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, stream: bool = True,
               rid: int | None = None,
               prefix_id: str | None = None,
               request_class: str | None = None,
               retries: int = 0) -> int:
        """Admit a request; returns its (client-chosen or auto) rid.
        ``prefix_id`` optionally names the shared prefix the prompt
        continues (prefix-aware routing/admission); routers also
        token-match unnamed prompts against their catalog, so it is
        never required. ``request_class`` names the QoS tier
        (``interactive``/``standard``/``batch``; None omits the field —
        old servers see the old wire and new servers default it to
        ``standard``). ``retries`` is the BUSY budget: that many
        transparent re-admissions with capped jittered backoff on the
        server's hint before :class:`ServerBusy` surfaces."""
        if rid is None:
            rid = next(self._next_rid)
        tr = tracing.get_tracer()
        sp = tr.start_span("client.request", rid=rid,
                           prompt_tokens=len(prompt))
        body = {"prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens), "stream": stream}
        if prefix_id is not None:
            body["prefix"] = str(prefix_id)
        if request_class is not None:
            # pass-through, not validated here: the server owns the
            # class vocabulary and answers an unknown one with a
            # request-scoped ERROR
            body["class"] = str(request_class)
        if sp.recording:
            # propagate the client's span context so the router's and
            # engine's spans join this trace (the end-to-end TTFT
            # decomposition)
            body["trace"] = sp.context
        with self._lock:
            if self._closed:
                sp.end(reason="closed")
                raise ServingConnectionError(
                    self._conn_error or "client is closed")
            self._queues[rid] = queue.Queue()
            if retries > 0:
                self._retries[rid] = [body, int(retries), 0]
            self._spans[rid] = (sp, tr.start_span("client.ttft",
                                                  parent=sp))
        try:
            self._send(P.ADMIT, rid, P.pack_json(body))
        except ServingConnectionError:
            self._end_span(rid, reason="send_failed")
            raise
        return rid

    def cancel(self, rid: int) -> None:
        self._send(P.CANCEL, rid)

    def next_event(self, rid: int, timeout: float | None = None):
        """Next event for ``rid``: ``("tokens", [ints])``,
        ``("retired", reason, n)``, or ``("error", message)``. Raises
        ``queue.Empty`` on timeout."""
        with self._lock:
            q = self._queues.get(rid)
        if q is None:
            raise KeyError(f"unknown request id {rid}")
        return q.get(timeout=timeout)

    def _abandon(self, rid: int) -> None:
        """A consumer walked away from a live request (timeout, early
        generator exit): best-effort CANCEL so the server frees the
        slot instead of generating into the void, then release the
        local queue."""
        try:
            self.cancel(rid)
        except ServingConnectionError:
            pass
        self._forget(rid)

    def _event_or_raise(self, rid: int, timeout: float | None):
        """next_event with the documented failure surface: a timeout
        (the stream went silent — ``timeout`` is the per-EVENT bound,
        not the whole request's) cancels the abandoned request and
        raises ``ServingConnectionError``, never a raw
        ``queue.Empty``."""
        try:
            return self.next_event(rid, timeout=timeout)
        except queue.Empty:
            self._abandon(rid)
            raise ServingConnectionError(
                f"no event for request {rid} within {timeout}s") from None

    def deltas(self, rid: int, timeout: float | None = 120.0):
        """Yield token deltas (lists of ints) as the server pushes them;
        returns on retirement, raises ``ServingConnectionError`` on
        error or on ``timeout`` seconds without any event. Abandoning
        the generator early (``break``/close) CANCELs the request. The
        terminal reason is left for :meth:`result` callers — this
        generator is the 'emit tokens to the user as they land'
        surface."""
        finished = False
        try:
            while True:
                ev = self._event_or_raise(rid, timeout)
                if ev[0] == "tokens":
                    yield ev[1]
                elif ev[0] == "retired":
                    finished = True
                    self._forget(rid)
                    return
                elif ev[0] == "busy":
                    finished = True         # terminal: nothing to cancel
                    self._forget(rid)
                    raise ServerBusy(ev[1])
                else:
                    finished = True
                    self._forget(rid)
                    raise ServingConnectionError(ev[1])
        finally:
            if not finished:
                self._abandon(rid)

    def result(self, rid: int, timeout: float | None = 120.0):
        """Block until ``rid`` retires; returns ``(tokens, reason)``.
        ``timeout`` bounds the wait per EVENT, not the whole request."""
        tokens: list[int] = []
        while True:
            ev = self._event_or_raise(rid, timeout)
            if ev[0] == "tokens":
                tokens.extend(ev[1])
            elif ev[0] == "retired":
                self._forget(rid)
                return tokens, ev[1]
            elif ev[0] == "busy":
                self._forget(rid)
                raise ServerBusy(ev[1])
            else:
                self._forget(rid)
                raise ServingConnectionError(ev[1])

    def poll(self, rid: int, timeout: float | None = 120.0):
        """Long-poll a ``stream=False`` request: one request/response
        round trip per call (the per-chunk baseline the streaming wire
        replaces). Returns ``(tokens, None)`` while live and
        ``([], reason)`` once retired."""
        self._send(P.POLL, rid)
        ev = self._event_or_raise(rid, timeout)
        if ev[0] == "tokens":
            return ev[1], None
        if ev[0] == "retired":
            self._forget(rid)
            return [], ev[1]
        self._forget(rid)
        if ev[0] == "busy":
            raise ServerBusy(ev[1])
        raise ServingConnectionError(ev[1])

    def prefix_op(self, op: str, timeout: float | None = 60.0,
                  **fields) -> dict:
        """One PREFIX-frame round trip (prefix-aware serving): replica
        ops ``install`` (``tokens=``, optional ``id=``), ``publish``
        (``id=``, ``target=`` — the peer's ``host:prefix_port``
        template lane) and ``list``; router ops ``register``
        (``tokens=``) and ``list``. Returns the reply object
        (``{"ok": bool, ...}`` — op failures are returned, not
        raised); raises ``ServingConnectionError`` only on transport
        loss."""
        rid = next(self._next_rid)
        with self._lock:
            if self._closed:
                raise ServingConnectionError(
                    self._conn_error or "client is closed")
            self._queues[rid] = queue.Queue()
        try:
            self._send(P.PREFIX, rid,
                       P.pack_json(dict(fields, op=op)))
            ev = self._event_or_raise(rid, timeout)
        finally:
            self._forget(rid)
        if ev[0] == "prefix":
            return ev[1]
        raise ServingConnectionError(
            ev[1] if ev[0] == "error" else f"unexpected reply {ev[0]}")

    def drain_replica(self, replica: str, timeout_s: float = 120.0,
                      timeout: float | None = None) -> dict:
        """Operator op against a ROUTER: fence ``replica`` and
        live-migrate every session off it (planned maintenance /
        rolling upgrades — see docs/serving.md §Operating the fleet).
        Blocks until the router reports the drain finished; returns its
        summary ``{"ok", "replica", "drained", "migrated", "wall_s",
        ...}``. ``timeout_s`` is the ROUTER's drain deadline;
        ``timeout`` (default ``timeout_s + 30``) is this call's local
        reply wait. Raises ``ServingConnectionError`` on transport loss
        or a rejected request (unknown frame on a plain replica, bad
        replica name)."""
        rid = next(self._next_rid)
        with self._lock:
            if self._closed:
                raise ServingConnectionError(
                    self._conn_error or "client is closed")
            self._queues[rid] = queue.Queue()
        if timeout is None:
            timeout = timeout_s + 30.0
        try:
            self._send(P.DRAIN, rid, P.pack_json(
                {"replica": replica, "timeout_s": timeout_s}))
            ev = self._event_or_raise(rid, timeout)
        finally:
            self._forget(rid)
        if ev[0] == "drain":
            return ev[1]
        raise ServingConnectionError(
            ev[1] if ev[0] == "error" else f"unexpected reply {ev[0]}")

    def migrate(self, rid: int) -> None:
        """Ask the router to live-migrate one of this client's OWN
        streams (``rid`` from :meth:`submit`) onto another replica —
        the single-session form of :meth:`drain_replica`.
        Fire-and-forget: on success the stream just continues on its
        rid with no duplicated or dropped tokens; if the session cannot
        move (already finishing, no eligible replica) it continues
        where it is."""
        self._send(P.MIGRATE, rid)

    def stats(self, timeout: float | None = 30.0) -> dict:
        """Server stats snapshot (the ``tony_serve_queue_depth`` gauge
        et al. — what the router places by)."""
        self._send(P.STATS, 0)
        out = self._stats_q.get(timeout=timeout)
        if "error" in out:
            raise ServingConnectionError(out["error"])
        return out

    def _forget(self, rid: int) -> None:
        with self._lock:
            self._queues.pop(rid, None)
            self._retries.pop(rid, None)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._reader.is_alive():
            self._reader.join(timeout=5)

    def __enter__(self) -> "StreamingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
