"""Network emulation for serving benchmarks/tests: a latency-injecting
TCP proxy.

:class:`LatencyProxy` forwards a local port to a target, delivering
each byte burst ``delay_s`` after it was read — in BOTH directions, so
one request/response round trip through the proxy costs ``2*delay_s``.
Crucially it models link LATENCY, not throughput: bursts are
timestamped on read and released by a separate writer thread, so
in-flight data overlaps (a stream of pushed token deltas pays the delay
once, pipelined, while a poll-per-chunk client pays it once per round
trip). That asymmetry is exactly what the streaming-vs-request/response
bench arm measures, deterministically, on loopback.

Shares the byte-pump shape of ``tony_tpu/proxy/server.py`` (the
gateway proxy), plus the delay queue.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

from tony_tpu.serving.protocol import set_nodelay

_BUF = 1 << 16


def _delayed_pump(src: socket.socket, dst: socket.socket,
                  delay_s: float) -> None:
    """Copy src→dst, releasing each burst ``delay_s`` after it was
    read. The writer thread sleeps per burst; reads continue in the
    meantime, so concurrent bursts' delays overlap (latency, not
    serialization)."""
    q: queue.Queue = queue.Queue()

    def writer() -> None:
        while True:
            item = q.get()
            if item is None:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                return
            deadline, data = item
            dt = deadline - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            try:
                dst.sendall(data)
            except OSError:
                return

    t = threading.Thread(target=writer, name="tony-netem-writer",
                         daemon=True)
    t.start()
    try:
        while True:
            data = src.recv(_BUF)
            if not data:
                break
            q.put((time.perf_counter() + delay_s, data))
    except OSError:
        pass
    q.put(None)
    t.join()


class LatencyProxy:
    """Listen locally, forward to ``remote_host:remote_port`` with
    ``delay_s`` of one-way latency injected per direction (round trip
    = ``2*delay_s``)."""

    def __init__(self, remote_host: str, remote_port: int,
                 delay_s: float, bind_host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.remote_host = remote_host
        self.remote_port = remote_port
        self.delay_s = delay_s
        self.bind_host = bind_host
        self.port = port
        self._server: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = threading.Event()
        self._conn_lock = threading.Lock()
        #: live (client, upstream) socket pairs — what sever() cuts
        self._conns: set = set()

    def start(self) -> int:
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((self.bind_host, self.port))
        server.listen(16)
        self.port = server.getsockname()[1]
        self._server = server
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tony-netem-accept",
            daemon=True)
        self._accept_thread.start()
        return self.port

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stopping.is_set():
            try:
                client, _ = self._server.accept()
            except OSError:
                break
            threading.Thread(target=self._handle, args=(client,),
                             name="tony-netem-conn",
                             daemon=True).start()

    def _handle(self, client: socket.socket) -> None:
        try:
            upstream = socket.create_connection(
                (self.remote_host, self.remote_port), timeout=10)
        except OSError:
            client.close()
            return
        # latency injection must not compound with Nagle batching
        for s in (client, upstream):
            set_nodelay(s)
        upstream.settimeout(None)
        pair = (client, upstream)
        with self._conn_lock:
            self._conns.add(pair)
        t = threading.Thread(target=_delayed_pump,
                             args=(client, upstream, self.delay_s),
                             name="tony-netem-pump", daemon=True)
        t.start()
        _delayed_pump(upstream, client, self.delay_s)
        t.join()
        with self._conn_lock:
            self._conns.discard(pair)
        for s in pair:
            try:
                s.close()
            except OSError:
                pass

    def sever(self) -> int:
        """Hard-cut every connection currently flowing through the
        proxy (both sides see EOF/reset) while the listener keeps
        accepting — a crash/partition of the REPLICA as seen by its
        peers, without killing the process behind it. The chaos
        harness's replica-loss injection. Returns the number of
        connections cut."""
        with self._conn_lock:
            pairs = list(self._conns)
        for pair in pairs:
            for s in pair:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        return len(pairs)

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
