"""Content-addressed weight + compiled-program distribution: the warm
scale-up path.

Autoscale reacts in seconds, but a fresh replica still pays a full
weight load from storage plus a complete XLA retrace before its first
token — at fleet scale, scale-up latency IS cold-start latency. This
module makes weights and compiled programs **content-addressed
artifacts** a new replica pulls peer-to-peer from an already-warm
replica over a TONYC1 byte-blob lane (``WEIGHT_CHANNEL``) instead of
re-reading storage:

- **Identity**: :func:`tree_digest` — sha256 over the canonical
  serialized weight tree (sorted flattened paths, ``kind\\0path\\0
  dtype\\0shape\\0payload`` entry framing — the same walk discipline as
  ``compute_stage_digest`` in ``tony_tpu/backend/tpu.py``: content
  only, no mtimes, no dict order). Two replicas that loaded the same
  checkpoint name it identically without coordination, and a single
  flipped byte anywhere in a shipped artifact changes the digest — the
  landing side recomputes and REFUSES a mismatch, never silently
  serves it.
- **Wire shape**: the shared kind-tagged blob codec
  (:mod:`tony_tpu.serving.blobcodec`, kind ``weights``) riding
  :meth:`~tony_tpu.channels.channel.ChannelSender.send_bytes` — so a
  multi-GB artifact ships as bounded chunks with seq-resume (a
  disconnect mid-ship resumes at the first unacked chunk), and no
  other lane can misread it.
- **Optional int8 wire quantization** (like kv-ship): f32/bf16 leaves
  ship as int8 + per-tensor scale. The digest is computed over the
  DEQUANTIZED tree — the exact values the receiver will serve — so
  both ends agree bit-for-bit on what landed or the transfer is
  refused. A quantized artifact is a DISTINCT weight version from its
  f32 original (different digest): see docs/serving.md for when NOT to
  quantize.
- **Fan-out** (:func:`warm_fanout`): each freshly-warmed replica
  immediately becomes a seeder, so N scale-up replicas warm in
  O(log N) ship waves; a seeder crash mid-ship drops that seeder and
  the orphaned target falls back to a storage load — warming never
  wedges the fleet.
- **Compiled programs**: :func:`pack_compile_cache` /
  :func:`install_compile_cache` ship the JAX persistent compilation
  cache directory the same way, so a scale-up replica lands
  pre-traced (``tony_compile_cache_hits_total``).

Hosting mirrors the prefix lane (:class:`~tony_tpu.serving.prefix.
PrefixHost`): :class:`WeightHost` is the mixin a serving-plane server
uses to hold a :class:`WeightStore`, land shipped artifacts on the
weights lane, advertise resident digests in HELLO/STATS, and publish
an artifact to a peer on command (the ``WEIGHTS`` frame ops). A
malformed or digest-mismatched artifact costs only itself: the
install thread logs, records a flight event, and keeps serving.

Observability: ``tony_weight_ship_seconds`` /
``tony_weight_ship_bytes_total`` (publication wall + payload),
``tony_weight_installs_total`` (artifacts landed resident),
``tony_compile_cache_hits_total`` (compiled-program artifacts served
from residency instead of a retrace).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
import threading
import time

import numpy as np

from tony_tpu.conf.keys import (DEFAULTS, WEIGHTS_CHUNK_BYTES_KEY,
                                WEIGHTS_COMPILE_CACHE_DIR_KEY,
                                WEIGHTS_QUANTIZE_WIRE_KEY)
from tony_tpu.channels.channel import (ChannelClosed, ChannelError,
                                       ChannelHub, ChannelSender)
from tony_tpu.serving import blobcodec
from tony_tpu.serving import protocol as P
from tony_tpu.serving.protocol import ProtocolError

log = logging.getLogger(__name__)

#: the channel lane weight artifacts ride (multiplexed by name on the
#: host's blob hub — a replica that also lands prefix templates keeps
#: them on their own lane; the kind tag makes a misrouted blob fail
#: loudly either way)
WEIGHT_CHANNEL = "weights"

#: path separator in flattened tree names; list indices are marked
#: ``#i`` so ``{"a": [x]}`` and ``{"a": {"#0": x}}`` cannot collide
#: silently (dict keys may not start with ``#``).
_SEP = "/"
_IDX = "#"


# ---------------------------------------------------------------------------
# Canonical tree form + content digest
# ---------------------------------------------------------------------------
def flatten_tree(tree, prefix: str = "") -> dict:
    """Flatten a nested params tree (dicts / lists / tuples of
    array-likes) to ``{path: np.ndarray}`` with deterministic
    ``/``-joined paths (``#i`` for sequence indices). The inverse is
    :func:`unflatten_tree`."""
    out: dict = {}
    if isinstance(tree, dict):
        for k in tree:
            if not isinstance(k, str) or _SEP in k or k.startswith(_IDX):
                raise ValueError(
                    f"weight tree key {k!r} is not flattenable (string "
                    f"keys without {_SEP!r}, not starting with {_IDX!r})")
            sub = prefix + _SEP + k if prefix else k
            out.update(flatten_tree(tree[k], sub))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            sub = f"{prefix}{_SEP}{_IDX}{i}" if prefix else f"{_IDX}{i}"
            out.update(flatten_tree(v, sub))
    else:
        if not prefix:
            prefix = _IDX + "0"
        out[prefix] = np.asarray(tree)
    return out


def unflatten_tree(flat: dict):
    """Rebuild the nested tree :func:`flatten_tree` serialized.
    Sequences come back as lists (the params trees here never rely on
    tuple-ness)."""
    root: dict = {}
    for path, arr in flat.items():
        parts = path.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def build(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.startswith(_IDX) for k in node):
            idx = sorted(node, key=lambda k: int(k[len(_IDX):]))
            if [int(k[len(_IDX):]) for k in idx] != list(range(len(idx))):
                raise ProtocolError(
                    f"weight tree has a gapped sequence: {sorted(node)}")
            return [build(node[k]) for k in idx]
        return {k: build(v) for k, v in node.items()}

    out = build(root)
    if isinstance(out, list) and len(out) == 1 and list(flat) == [
            _IDX + "0"]:
        return out[0]                       # bare-leaf round trip
    return out


def tree_digest(tree) -> str:
    """sha256 hex over the canonical serialized weight tree: entries
    walk in sorted flattened-path order, each framed ``buf\\0path\\0
    dtype\\0shape\\0`` + C-contiguous payload + ``\\0`` — content only
    (same discipline as the stage digest: independent of dict order,
    storage layout, or when the checkpoint was written). Accepts a
    nested tree or an already-flat ``{path: array}`` dict."""
    flat = tree if (isinstance(tree, dict) and tree and all(
        isinstance(v, np.ndarray) for v in tree.values())) \
        else flatten_tree(tree)
    h = hashlib.sha256()
    for path in sorted(flat):
        a = np.asarray(flat[path])
        if not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        shape = ",".join(str(d) for d in a.shape)
        h.update(f"buf\0{path}\0{a.dtype}\0{shape}\0".encode("utf-8"))
        h.update(a.tobytes())
        h.update(b"\0")
    return h.hexdigest()


def dir_digest(path: str) -> str:
    """sha256 hex over a directory's file contents (sorted relative
    paths, content-only — the ``compute_stage_digest`` walk discipline
    applied to a compilation-cache dir)."""
    h = hashlib.sha256()
    for rel in sorted(_walk_files(path)):
        h.update(f"file\0{rel}\0".encode("utf-8"))
        with open(os.path.join(path, rel), "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
        h.update(b"\0")
    return h.hexdigest()


def _walk_files(root: str) -> list:
    rels = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in filenames:
            full = os.path.join(dirpath, name)
            rels.append(os.path.relpath(full, root))
    return rels


# ---------------------------------------------------------------------------
# Artifact pack / unpack (digest-gated)
# ---------------------------------------------------------------------------
def _quantize(a: np.ndarray) -> tuple:
    """Symmetric per-tensor int8: -> (q int8 array, scale as exact
    python float). The kv-ship scheme, applied to a weight leaf."""
    f = np.asarray(a, np.float32)
    amax = float(np.max(np.abs(f))) if f.size else 0.0
    scale = np.float32(amax / 127.0) if amax > 0 else np.float32(0.0)
    if scale == 0:
        q = np.zeros(f.shape, np.int8)
    else:
        q = np.clip(np.rint(f / scale), -127, 127).astype(np.int8)
    return q, float(scale)


def _dequantize(q: np.ndarray, scale: float, dtype_name: str) \
        -> np.ndarray:
    dt = blobcodec.np_dtype(dtype_name)
    return (q.astype(np.float32) * np.float32(scale)).astype(dt)


def pack_weights(params, *, version: str | None = None,
                 quantize: bool | None = None) -> bytes:
    """Pack a params tree into ONE content-addressed weight artifact.
    The meta's ``digest`` names the AS-SERVED tree: the tree itself
    when unquantized, the dequantized tree when ``quantize=True`` (so
    the receiver can verify exactly what it will serve — and a
    quantized artifact is a distinct version from its f32 original).
    ``quantize=None`` takes the ``tony.weights.quantize-wire`` config
    default. Returns the packed blob; read the digest back with
    :func:`peek_weights_meta` or :func:`unpack_weights`."""
    if quantize is None:
        quantize = DEFAULTS[WEIGHTS_QUANTIZE_WIRE_KEY].lower() == "true"
    flat = flatten_tree(params)
    scales: dict = {}
    wire: dict = {}
    for path, a in flat.items():
        if quantize and (a.dtype.kind == "f"
                         or str(a.dtype) == "bfloat16"):
            q, scale = _quantize(a)
            scales[path] = [scale, str(a.dtype)]
            wire[path] = q
        else:
            wire[path] = a
    if quantize:
        served = {p: (_dequantize(wire[p], *scales[p])
                      if p in scales else wire[p]) for p in wire}
    else:
        served = flat
    meta = {"part": "weights", "digest": tree_digest(served),
            "quantized": bool(scales)}
    if version is not None:
        meta["version"] = str(version)
    if scales:
        meta["scales"] = scales
    return blobcodec.WEIGHTS.pack(meta, wire)


def peek_weights_meta(blob: bytes) -> dict:
    """Parse just the artifact meta (no digest verification — use for
    advertising / routing, never for landing)."""
    meta, _ = blobcodec.WEIGHTS.unpack(blob)
    return meta


def unpack_weights(blob: bytes) -> tuple:
    """Land a weight artifact -> (meta, params tree), REFUSING any
    blob whose recomputed as-served digest mismatches its claimed one
    (a flipped byte is a ProtocolError here, never silently served).
    Quantized artifacts are dequantized first; the returned tree is
    exactly the tree the digest names."""
    meta, bufs = blobcodec.WEIGHTS.unpack(blob)
    if meta.get("part") != "weights":
        raise ProtocolError(
            f"not a weight artifact (part={meta.get('part')!r})")
    claimed = meta.get("digest")
    if not isinstance(claimed, str) or len(claimed) != 64:
        raise ProtocolError(f"malformed weight digest: {claimed!r}")
    scales = meta.get("scales") or {}
    if not isinstance(scales, dict):
        raise ProtocolError(f"malformed scale table: {scales!r}")
    served: dict = {}
    for path, a in bufs.items():
        sc = scales.get(path)
        if sc is not None:
            if (not isinstance(sc, list) or len(sc) != 2
                    or not isinstance(sc[1], str)):
                raise ProtocolError(f"malformed scale entry: {sc!r}")
            if a.dtype != np.int8:
                raise ProtocolError(
                    f"scaled leaf {path!r} is {a.dtype}, expected int8")
            served[path] = _dequantize(a, float(sc[0]), sc[1])
        else:
            served[path] = a
    got = tree_digest(served)
    if got != claimed:
        raise ProtocolError(
            f"weight artifact REFUSED: landed digest {got[:12]}… != "
            f"claimed {claimed[:12]}… (corrupt or tampered transfer)")
    return meta, unflatten_tree(served)


def pack_compile_cache(cache_dir: str,
                       version: str | None = None) -> bytes:
    """Pack a JAX persistent-compilation-cache directory into one
    content-addressed artifact (files as raw uint8 buffers, digest
    over the sorted content walk) — shipped like weights, so a
    scale-up replica lands PRE-TRACED."""
    bufs: dict = {}
    for rel in _walk_files(cache_dir):
        key = rel.replace(os.sep, "/")
        if key.startswith("../") or key.startswith("/"):
            raise ValueError(f"compile-cache path escapes root: {rel!r}")
        with open(os.path.join(cache_dir, rel), "rb") as f:
            bufs[key] = np.frombuffer(f.read(), dtype=np.uint8)
    meta = {"part": "compile_cache", "digest": dir_digest(cache_dir)}
    if version is not None:
        meta["version"] = str(version)
    return blobcodec.WEIGHTS.pack(meta, bufs)


def _verify_compile_cache_entries(meta: dict, bufs: dict) -> None:
    """Digest-gate a compile-cache artifact's IN-MEMORY entry table —
    the same ``file\\0rel\\0`` + content + ``\\0`` sorted walk as
    :func:`dir_digest`, applied to the unpacked buffers (entry keys
    are ``/``-joined, which on POSIX is exactly the on-disk walk), so
    a corrupt artifact is refused at :meth:`WeightStore.put` instead
    of landing resident and re-seeding peer-to-peer."""
    claimed = meta.get("digest")
    if not isinstance(claimed, str) or len(claimed) != 64:
        raise ProtocolError(f"malformed compile-cache digest: "
                            f"{claimed!r}")
    h = hashlib.sha256()
    for rel in sorted(bufs):
        arr = bufs[rel]
        if arr.dtype != np.uint8 or arr.ndim != 1:
            raise ProtocolError(
                f"compile-cache entry {rel!r} is not a raw byte buffer")
        h.update(f"file\0{rel}\0".encode("utf-8"))
        h.update(arr.tobytes())
        h.update(b"\0")
    got = h.hexdigest()
    if got != claimed:
        raise ProtocolError(
            f"compile-cache artifact REFUSED: content digest "
            f"{got[:12]}… != claimed {claimed[:12]}… (corrupt or "
            f"tampered transfer)")


def install_compile_cache(blob: bytes, cache_dir: str) -> dict:
    """Land a compile-cache artifact into ``cache_dir`` (created if
    missing), digest-verified after the write — a mismatch removes
    nothing already resident but raises, so a corrupt transfer is
    never silently trusted as a trace cache. Returns the meta."""
    meta, bufs = blobcodec.WEIGHTS.unpack(blob)
    if meta.get("part") != "compile_cache":
        raise ProtocolError(
            f"not a compile-cache artifact (part={meta.get('part')!r})")
    claimed = meta.get("digest")
    if not isinstance(claimed, str) or len(claimed) != 64:
        raise ProtocolError(f"malformed compile-cache digest: "
                            f"{claimed!r}")
    os.makedirs(cache_dir, exist_ok=True)
    for rel, arr in bufs.items():
        if (not isinstance(rel, str) or rel.startswith("/")
                or ".." in rel.split("/")):
            raise ProtocolError(
                f"compile-cache entry escapes the cache dir: {rel!r}")
        if arr.dtype != np.uint8 or arr.ndim != 1:
            raise ProtocolError(
                f"compile-cache entry {rel!r} is not a raw byte buffer")
        full = os.path.join(cache_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "wb") as f:
            f.write(arr.tobytes())
    got = dir_digest(cache_dir)
    if got != claimed:
        raise ProtocolError(
            f"compile-cache artifact landed dirty: digest {got[:12]}… "
            f"!= claimed {claimed[:12]}… (pre-existing entries or a "
            f"corrupt transfer)")
    return meta


def attach_compile_cache(cache_dir: str | None = None) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` (so a
    landed artifact's traces are HITS, and local traces accrete into
    the next artifact). ``None`` takes the
    ``tony.weights.compile-cache-dir`` config default; empty means
    no cache is configured. Best-effort: returns False when jax is
    absent or too old to configure — pre-tracing is an optimization,
    never a boot dependency."""
    if cache_dir is None:
        cache_dir = DEFAULTS[WEIGHTS_COMPILE_CACHE_DIR_KEY]
    if not cache_dir:
        return False
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        return True
    except Exception as e:                  # noqa: BLE001 — optional
        log.warning("compile cache not attached (%s)", e)
        return False


# ---------------------------------------------------------------------------
# The resident store
# ---------------------------------------------------------------------------
class WeightStore:
    """Resident content-addressed artifacts, keyed by digest. Holds
    the PACKED blobs (what ships — no re-serialization per publish)
    plus their metas; an ``exporter`` callable lazily packs this
    host's own live params the first time someone asks for them."""

    def __init__(self, registry=None, exporter=None) -> None:
        from tony_tpu.runtime import metrics as metrics_mod
        reg = registry or metrics_mod.get_default()
        self._lock = threading.Lock()
        self._artifacts: dict = {}          # digest -> (meta, blob)
        self._exporter = exporter
        self._exported = False
        self._installs_c = reg.counter(
            "tony_weight_installs_total",
            help="weight / compiled-program artifacts landed resident "
                 "(digest-verified)")
        self._cc_hits_c = reg.counter(
            "tony_compile_cache_hits_total",
            help="compiled-program artifacts served from the content-"
                 "addressed store instead of a retrace (a scale-up "
                 "landing pre-traced, or a peer seeding from "
                 "residency)")

    def put(self, blob: bytes) -> str:
        """Make a packed artifact resident (digest read from its meta,
        VERIFIED — weight artifacts through the full as-served gate,
        compile-cache artifacts against their entry table); returns
        the digest. A corrupt blob can never land resident and be
        re-seeded peer-to-peer."""
        meta, bufs = blobcodec.WEIGHTS.unpack(blob)
        if meta.get("part") == "weights":
            meta, _tree = unpack_weights(blob)      # full digest gate
        elif meta.get("part") == "compile_cache":
            _verify_compile_cache_entries(meta, bufs)
        digest = meta.get("digest")
        if not isinstance(digest, str) or len(digest) != 64:
            raise ProtocolError(f"artifact has no digest: {meta!r}")
        with self._lock:
            self._artifacts[digest] = (meta, bytes(blob))
        self._installs_c.inc()
        return digest

    def get(self, digest: str) -> bytes:
        """The packed blob for ``digest`` (ValueError when not
        resident). A compile-cache hit counts — it is a retrace
        someone did not pay."""
        with self._lock:
            self._ensure_exported_locked()
            entry = self._artifacts.get(digest)
        if entry is None:
            raise ValueError(f"artifact {digest[:12]}… is not resident")
        if entry[0].get("part") == "compile_cache":
            self._cc_hits_c.inc()
        return entry[1]

    def meta(self, digest: str) -> dict:
        with self._lock:
            self._ensure_exported_locked()
            entry = self._artifacts.get(digest)
        if entry is None:
            raise ValueError(f"artifact {digest[:12]}… is not resident")
        return dict(entry[0])

    def digests(self) -> list:
        """Every artifact this host can SEED — triggers the lazy
        self-export (packing the live params) the first time. This is
        the seed-intent view: the WEIGHTS ``list``/``publish`` ops pay
        the pack here, exactly once, when a peer actually asks."""
        with self._lock:
            self._ensure_exported_locked()
            return sorted(self._artifacts)

    def resident_digests(self) -> list:
        """Digests already resident, WITHOUT triggering the lazy
        self-export. HELLO/STATS advertise through this: a client
        handshake must never synchronously pack (and then pin) a
        multi-GB host copy of the params under the store lock — the
        precomputed ``weights_digest`` field advertises seedability;
        the export runs when a peer sends an actual seed op."""
        with self._lock:
            return sorted(self._artifacts)

    def _ensure_exported_locked(self) -> None:
        if self._exported or self._exporter is None:
            return
        self._exported = True               # once, even on failure
        try:
            blob = self._exporter()
        except Exception as e:              # noqa: BLE001 — advisory
            log.warning("weight export failed; serving without a "
                        "seedable artifact: %s", e)
            return
        if blob is None:
            return
        meta, _ = blobcodec.WEIGHTS.unpack(blob)
        digest = meta.get("digest")
        if isinstance(digest, str) and len(digest) == 64:
            self._artifacts[digest] = (meta, bytes(blob))


# ---------------------------------------------------------------------------
# Hosting: the weights lane + WEIGHTS frame ops (mirrors PrefixHost)
# ---------------------------------------------------------------------------
class WeightHost:
    """Mixin: a serving-plane server that holds a :class:`WeightStore`
    and can be WARMED over the weights lane. Call
    ``_init_weight_host(registry, exporter=, hub=)`` in ``__init__``
    (pass the prefix hub to share one blob port),
    ``_start_weight_host()`` / ``_stop_weight_host()`` around the
    serving lifecycle, and route ``WEIGHTS`` frames to
    :meth:`_handle_weights_frame`."""

    def _init_weight_host(self, registry, exporter=None,
                          hub: ChannelHub | None = None) -> None:
        self._weight_reg = registry
        self._weight_hub_owned = hub is None
        self._weight_hub = hub if hub is not None else ChannelHub(
            port=0, capacity=4, registry=registry)
        self.weight_store = WeightStore(registry, exporter=exporter)
        self._weight_install_thread: threading.Thread | None = None
        self._weight_ship_h = registry.histogram(
            "tony_weight_ship_seconds",
            help="weight/compile-cache artifact publication wall per "
                 "ship (pack lookup + chunked channel send + the "
                 "peer's ack)")
        self._weight_ship_bytes_c = registry.counter(
            "tony_weight_ship_bytes_total",
            help="weight/compile-cache artifact payload bytes "
                 "published to peer replicas")

    @property
    def weight_port(self) -> int:
        """The weights lane's bound port (HELLO-advertised)."""
        return self._weight_hub.port

    def _start_weight_host(self) -> None:
        if self._weight_hub_owned:
            self._weight_hub.start()
        self._weight_install_thread = threading.Thread(
            target=self._weight_install_loop, name="tony-weight-install",
            daemon=True)
        self._weight_install_thread.start()

    def _stop_weight_host(self) -> None:
        if self._weight_hub_owned:
            self._weight_hub.stop()
        if self._weight_install_thread is not None:
            self._weight_install_thread.join(timeout=10)

    # -- the install thread (artifact ships land here) ----------------------
    def _weight_install_loop(self) -> None:
        receiver = self._weight_hub.receiver(WEIGHT_CHANNEL)
        while True:
            try:
                # the 0.25 bounds only the idle poll for a blob to
                # START; once a manifest lands, reassembly runs under
                # recv_bytes' own generous per-chunk deadline — a
                # multi-GB artifact backpressuring through the hub is
                # never aborted mid-transfer by this poll cadence
                blob = receiver.recv_bytes(timeout=0.25)
            except ChannelClosed:
                return                  # hub stopped: lane is dead
            except ChannelError:
                continue                # idle poll (or a dead seeder
                #                         mid-blob); re-check liveness
            except ProtocolError as e:
                log.warning("weights lane: non-artifact frame dropped: "
                            "%s", e)
                continue
            try:
                digest = self.weight_store.put(blob)
                log.info("weight artifact %s… resident via ship "
                         "(%d bytes)", digest[:12], len(blob))
            except Exception as e:      # noqa: BLE001 — thread survival
                # a bad artifact costs only itself: warming is an
                # optimization, and a dead install thread would
                # silently make this replica forever unseedable
                log.warning("weights lane: artifact refused: %s", e)
                from tony_tpu.runtime import tracing
                tracing.get_flight().record("weight_artifact_refused",
                                            error=str(e)[:500])

    # -- publication --------------------------------------------------------
    def publish_weights(self, digest: str, target: str,
                        timeout_s: float = 120.0,
                        chunk_bytes: int | None = None) -> int:
        """Ship the resident artifact ``digest`` to ``target`` (a
        peer's ``host:weight_port`` weights lane) as chunked,
        delivery-confirmed, seq-resumable channel frames; returns the
        blob size. ``chunk_bytes=None`` takes the
        ``tony.weights.chunk-bytes`` config default. Raises
        ``ValueError`` (not resident) or
        :class:`~tony_tpu.channels.channel.ChannelError` (peer
        unreachable)."""
        if chunk_bytes is None:
            chunk_bytes = int(DEFAULTS[WEIGHTS_CHUNK_BYTES_KEY])
        blob = self.weight_store.get(digest)
        t0 = time.perf_counter()
        sender = ChannelSender(target, WEIGHT_CHANNEL, window=8,
                               registry=self._weight_reg)
        try:
            sender.send_bytes(blob, sync=True, timeout=timeout_s,
                              chunk_bytes=chunk_bytes)
        finally:
            sender.close(drain=False)
        self._weight_ship_h.observe(time.perf_counter() - t0)
        self._weight_ship_bytes_c.inc(len(blob))
        return len(blob)

    # -- the WEIGHTS frame ops (conn reader threads) ------------------------
    def _handle_weights_frame(self, conn, rid: int,
                              payload: bytes) -> None:
        """``WEIGHTS`` op dispatch. Op failures are REQUEST-scoped —
        a fleet controller naming a dead target must not cost the
        connection, let alone the replica."""
        obj = P.unpack_json(payload)    # structural garbage: conn-scoped
        op = obj.get("op")
        try:
            if op == "publish":
                digest = obj.get("digest")
                target = obj.get("target")
                if not isinstance(digest, str) \
                        or not isinstance(target, str):
                    raise ValueError("publish needs 'digest' and "
                                     "'target'")
                n = self.publish_weights(
                    digest, target,
                    timeout_s=float(obj.get("timeout_s", 120.0)))
                body = {"ok": True, "digest": digest, "bytes": n}
            elif op == "list":
                # seed intent: triggers the lazy self-export
                body = {"ok": True,
                        "resident": self.weight_store.digests()}
            elif op == "resident":
                # residency poll (warmers confirming a landing): never
                # triggers the export — polling a TARGET must not make
                # it pack its own params
                body = {"ok": True,
                        "resident":
                            self.weight_store.resident_digests()}
            else:
                body = {"ok": False,
                        "error": f"unknown weights op {op!r}"}
        except (ValueError, KeyError, ChannelError, ProtocolError) as e:
            body = {"ok": False, "error": str(e)}
        conn.send(P.WEIGHTS, rid, P.pack_json(body))


# ---------------------------------------------------------------------------
# Peer-to-peer pull (the cold replica's boot path)
# ---------------------------------------------------------------------------
def weights_rpc(addr: str, body: dict, timeout_s: float = 30.0) -> dict:
    """One WEIGHTS control round-trip against a replica's serving
    port: handshake, send the op, return the reply body (and the
    replica's HELLO under ``"_hello"``)."""
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)),
                                  timeout=timeout_s) as sock:
        sock.sendall(P.MAGIC)
        hello = P.recv_frame(sock)
        if hello is None or hello[0] != P.HELLO:
            raise ChannelError(f"replica {addr}: no HELLO")
        hello_body = P.unpack_json(hello[2])
        P.send_frame(sock, P.WEIGHTS, 1, P.pack_json(body))
        deadline = time.monotonic() + timeout_s
        while True:
            sock.settimeout(max(0.1, deadline - time.monotonic()))
            frame = P.recv_frame(sock)
            if frame is None:
                raise ChannelError(f"replica {addr} closed mid-op")
            if frame[0] == P.WEIGHTS:
                out = P.unpack_json(frame[2])
                out["_hello"] = hello_body
                return out


def _reachable_host(peer: str, default: str = "127.0.0.1") -> str:
    """The local address the kernel routes TOWARD ``peer`` from — what
    a puller must advertise as its weights-lane host. A hard-coded
    loopback would have a remote seeder ship the artifact to its own
    127.0.0.1 instead of the puller. The UDP connect assigns the
    outbound interface without sending a packet; on failure (peer
    unresolvable) fall back to ``default`` — the pull will fail
    loudly anyway."""
    host, _, port = peer.rpartition(":")
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((host or peer, int(port) if port.isdigit() else 1))
        return s.getsockname()[0]
    except OSError:
        return default
    finally:
        s.close()


def pull_weights(seeder: str, digest: str | None = None,
                 timeout_s: float = 120.0, registry=None,
                 advertise_host: str | None = None) -> tuple:
    """The cold replica's warm boot path: stand up a one-shot weights
    lane, ask ``seeder`` (a warm replica's serving address) to publish
    its artifact here, land it digest-verified, and return
    ``(meta, params tree)``. ``digest=None`` takes the seeder's first
    advertised resident artifact. ``advertise_host=None`` derives the
    address the seeder should ship to from the route toward it
    (:func:`_reachable_host`); pass it explicitly when the puller sits
    behind NAT/a proxy. Raises ChannelError (seeder unreachable /
    refused / timed out) or ProtocolError (artifact refused at the
    digest gate) — callers fall back to a storage load."""
    from tony_tpu.runtime import metrics as metrics_mod
    reg = registry or metrics_mod.MetricsRegistry()
    hub = ChannelHub(port=0, capacity=4, registry=reg)
    hub.start()
    try:
        receiver = hub.receiver(WEIGHT_CHANNEL)
        if digest is None:
            listed = weights_rpc(seeder, {"op": "list"},
                                 timeout_s=min(30.0, timeout_s))
            resident = listed.get("resident") or []
            if not resident:
                raise ChannelError(
                    f"seeder {seeder} has no resident artifact")
            digest = resident[0]
        if advertise_host is None:
            advertise_host = _reachable_host(seeder)
        target = f"{advertise_host}:{hub.port}"
        res = weights_rpc(seeder, {"op": "publish", "digest": digest,
                                   "target": target,
                                   "timeout_s": timeout_s},
                          timeout_s=timeout_s)
        if not res.get("ok"):
            raise ChannelError(
                f"seeder {seeder} refused publish: {res.get('error')}")
        blob = receiver.recv_bytes(timeout=timeout_s)
        meta, tree = unpack_weights(blob)
        if meta.get("digest") != digest:
            raise ProtocolError(
                f"seeder shipped {meta.get('digest')!r}, asked for "
                f"{digest!r}")
        return meta, tree
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# Self-organizing fan-out
# ---------------------------------------------------------------------------
def warm_fanout(targets, ship, *, seeders=(), fallback=None,
                max_parallel: int | None = None) -> dict:
    """Warm ``targets`` in O(log N) ship waves: each wave pairs every
    available seeder with one pending target and ships in parallel;
    every freshly-warmed target immediately joins the seeder pool for
    the next wave. ``ship(src, dst)`` raises on failure (a crashed
    seeder): the seeder is dropped from the pool and the target stays
    pending. When the pool runs dry — including at the start, when no
    warm peer exists — ``fallback(dst)`` (a storage load) mints a new
    seeder; a fallback that itself raises moves THAT target to
    ``failed`` (never out of this function — the fleet controller's
    release path owns failed targets); with no fallback at all, the
    remaining targets are reported ``failed``. Warming never wedges:
    every wave either makes progress or consumes a failure.

    Returns ``{"waves", "warmed", "fallback", "failed", "ships"}``
    (warmed = targets shipped peer-to-peer; fallback = targets
    storage-loaded; ships = successful peer ships)."""
    pending = list(targets)
    pool = list(seeders)
    warmed: list = []
    fell_back: list = []
    failed: list = []
    ships = 0
    waves = 0
    while pending:
        if not pool:
            dst = pending.pop(0)
            if fallback is None:
                failed.append(dst)
                failed.extend(pending)
                break
            waves += 1
            try:
                fallback(dst)
            except Exception as e:          # noqa: BLE001 — per-target
                # a failed storage load costs only its target: report
                # it failed and keep warming the rest ("warming never
                # wedges" covers the fallback path too)
                log.warning("warm fan-out: storage fallback for %s "
                            "failed: %s", dst, e)
                failed.append(dst)
                continue
            fell_back.append(dst)
            pool.append(dst)
            continue
        waves += 1
        pairs = list(zip(pool, pending))
        if max_parallel is not None:
            pairs = pairs[:max_parallel]
        outcomes: dict = {}

        def _one(src, dst):
            try:
                ship(src, dst)
                outcomes[dst] = None
            except Exception as e:          # noqa: BLE001 — per-pair
                outcomes[dst] = e

        threads = [threading.Thread(target=_one, args=pair,
                                    name="tony-warm-fanout", daemon=True)
                   for pair in pairs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for src, dst in pairs:
            err = outcomes.get(dst, RuntimeError("ship never ran"))
            if err is None:
                pending.remove(dst)
                warmed.append(dst)
                pool.append(dst)
                ships += 1
            else:
                # a failed ship condemns the SEEDER (crash mid-ship),
                # not the target: the target retries next wave off a
                # surviving or fallback-minted seeder
                log.warning("warm fan-out: ship %s -> %s failed: %s",
                            src, dst, err)
                if src in pool:
                    pool.remove(src)
    return {"waves": waves, "warmed": warmed, "fallback": fell_back,
            "failed": failed, "ships": ships}


class FleetWarmer:
    """What :class:`~tony_tpu.serving.fleet.FleetController` calls to
    warm freshly-grown replicas BEFORE routing traffic at them.
    ``warm(targets)`` returns the :func:`warm_fanout` summary.
    Implementations: :class:`ChannelWarmer` (real replicas, WEIGHTS
    ops over the serving port), ``SimWarmer`` in
    :mod:`tony_tpu.serving.simfleet` (deterministic chaos/bench)."""

    def warm(self, targets) -> dict:
        raise NotImplementedError


class ChannelWarmer(FleetWarmer):
    """Warm real replicas by commanding peer-to-peer artifact ships:
    each ship asks the source replica (WEIGHTS ``publish`` op on its
    serving port) to stream the ``digest`` artifact to the target's
    weights lane, then confirms the target reports it resident.
    ``seeders`` are serving addresses already holding the artifact;
    ``fallback`` (optional) is invoked with a target address when no
    seeder survives — typically a storage-load command."""

    def __init__(self, digest: str, seeders, fallback=None,
                 timeout_s: float = 120.0) -> None:
        self.digest = digest
        self.seeders = list(seeders)
        self.fallback = fallback
        self.timeout_s = timeout_s

    def _ship(self, src: str, dst: str) -> None:
        # "resident" (not "list"): probing the TARGET must not make it
        # lazily pack its own params just to answer a residency check
        hello = weights_rpc(dst, {"op": "resident"},
                            timeout_s=self.timeout_s)
        if self.digest in (hello.get("resident") or []):
            return                          # already warm
        wp = hello["_hello"].get("weight_port")
        if not wp:
            raise ChannelError(f"target {dst} advertises no weights "
                               f"lane")
        host = dst.rsplit(":", 1)[0]
        res = weights_rpc(src, {"op": "publish", "digest": self.digest,
                                "target": f"{host}:{wp}",
                                "timeout_s": self.timeout_s},
                          timeout_s=self.timeout_s)
        if not res.get("ok"):
            raise ChannelError(
                f"seeder {src} refused publish: {res.get('error')}")
        deadline = time.monotonic() + self.timeout_s
        while time.monotonic() < deadline:
            listed = weights_rpc(dst, {"op": "resident"},
                                 timeout_s=10.0)
            if self.digest in (listed.get("resident") or []):
                return
            time.sleep(0.05)
        raise ChannelError(
            f"target {dst} never reported {self.digest[:12]}… "
            f"resident")

    def warm(self, targets) -> dict:
        return warm_fanout(list(targets), self._ship,
                           seeders=self.seeders,
                           fallback=self.fallback)
