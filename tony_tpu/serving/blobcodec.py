"""The ONE JSON-header+raw-buffers blob codec every byte-blob lane
shares, kind-tagged so no lane can silently misread another's blobs.

Three kinds ride it today: KV row shipments (``kv_row``,
prefill → decode handoff), shared-prefix templates
(``prefix_template``, replica warming), and weight artifacts
(``weights``, the scale-up warm path). Each lane holds a
:class:`BlobCodec` bound to its kind: :meth:`BlobCodec.pack` stamps
the kind into the header, :meth:`BlobCodec.unpack` parses the blob
STRUCTURALLY first (so truncation / corrupt lengths / unknown dtypes
surface as their own precise errors) and refuses a parse-clean blob
whose kind belongs to another lane — a weight artifact routed onto the
template lane fails loudly at the kind gate, never lands as a
"template".

Wire layout (little-endian)::

    head_len   4 bytes  u32    JSON header length
    header     head_len bytes  {"v": 1, "meta": {..., "kind": ...},
                                "bufs": [{"name", "dtype", "shape"}...]}
    payload    concatenated C-contiguous buffer bytes, in header order

Buffers serialize in sorted-name order — deterministic wire bytes for
identical inputs, which is what lets a content digest over the packed
form name the artifact (see ``tony_tpu/serving/weightstore.py``).
dtype resolution falls back to ``ml_dtypes`` for bfloat16 et al., so
this module stays importable without jax.

Anything structurally off raises the serving wire's
:class:`~tony_tpu.serving.protocol.ProtocolError`.
"""

from __future__ import annotations

import json
import math
import struct

import numpy as np

from tony_tpu.serving.protocol import ProtocolError

_HLEN = struct.Struct("<I")

#: sanity cap on the JSON header alone (buffer entries are dozens of
#: bytes each; megabytes of "header" is a corrupt length prefix)
MAX_HEADER_BYTES = 1 << 20

#: the registered lane kinds (adding a kind here is what entitles a
#: lane to the wire shape — an UNREGISTERED kind is refused everywhere,
#: so a typo'd producer cannot mint a kind no consumer owns)
KV_ROW_KIND = "kv_row"
TEMPLATE_KIND = "prefix_template"
WEIGHTS_KIND = "weights"
KINDS = (KV_ROW_KIND, TEMPLATE_KIND, WEIGHTS_KIND)


def np_dtype(name: str) -> np.dtype:
    """Resolve a dtype string, including the ml_dtypes extensions
    (bfloat16 et al.) plain numpy cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError) as e:
        raise ProtocolError(f"unknown shipment dtype {name!r}") from e


def pack_blob(meta: dict, bufs: dict) -> bytes:
    """-> one blob (header + raw buffers). ``bufs``: {name: ndarray};
    arrays are serialized C-contiguous in sorted-name order
    (deterministic wire bytes for identical inputs)."""
    entries, blobs = [], []
    for name in sorted(bufs):
        a = np.asarray(bufs[name])
        shape = list(a.shape)          # before ascontiguousarray: it
        if not a.flags["C_CONTIGUOUS"]:   # promotes 0-d to 1-d
            a = np.ascontiguousarray(a)
        entries.append({"name": name, "dtype": str(a.dtype),
                        "shape": shape})
        blobs.append(a.tobytes())
    head = json.dumps({"v": 1, "meta": meta, "bufs": entries},
                      separators=(",", ":")).encode("utf-8")
    return _HLEN.pack(len(head)) + head + b"".join(blobs)


def unpack_blob(blob: bytes) -> tuple[dict, dict]:
    """Parse a blob -> (meta, {name: ndarray}), structural validation
    only (kind gating is the codec's job). Arrays view the blob's
    memory (frombuffer — no copy); callers that outlive the blob hold
    a reference through the arrays automatically."""
    if len(blob) < _HLEN.size:
        raise ProtocolError("shipment shorter than its header prefix")
    (hlen,) = _HLEN.unpack_from(blob, 0)
    if hlen > MAX_HEADER_BYTES or _HLEN.size + hlen > len(blob):
        raise ProtocolError(f"implausible shipment header length {hlen}")
    try:
        head = json.loads(blob[_HLEN.size:_HLEN.size + hlen]
                          .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"malformed shipment header: {e}") from e
    if not isinstance(head, dict) or not isinstance(head.get("meta"),
                                                    dict):
        raise ProtocolError(f"shipment header is not an object: {head!r}")
    entries = head.get("bufs")
    if not isinstance(entries, list):
        raise ProtocolError("shipment header missing buffer table")
    bufs: dict = {}
    off = _HLEN.size + hlen
    for e in entries:
        if (not isinstance(e, dict) or not isinstance(e.get("name"), str)
                or not isinstance(e.get("dtype"), str)
                or not isinstance(e.get("shape"), list)
                or not all(isinstance(d, int) and not isinstance(d, bool)
                           and d >= 0 for d in e["shape"])):
            raise ProtocolError(f"malformed buffer entry: {e!r}")
        dt = np_dtype(e["dtype"])
        # python-int math: np.prod would WRAP on adversarial shapes
        # ([2**32, 2**32] -> 0), sneaking a bogus buffer past the
        # bounds check into a reshape crash
        count = math.prod(e["shape"])
        n = count * dt.itemsize
        if off + n > len(blob):
            raise ProtocolError(
                f"shipment truncated: buffer {e['name']!r} promises "
                f"{n} bytes past the blob end")
        bufs[e["name"]] = np.frombuffer(
            blob, dtype=dt, count=count,
            offset=off).reshape(e["shape"])
        off += n
    if off != len(blob):
        raise ProtocolError(
            f"shipment carries {len(blob) - off} trailing bytes beyond "
            f"its buffer table")
    return head["meta"], bufs


class BlobCodec:
    """One lane's binding to the shared wire shape: packs with the
    lane's kind stamped into the meta, unpacks with the kind gated.

    ``allow_untagged`` grandfathers blobs whose meta carries NO kind
    (the pre-kind kv-row wire shape) — a blob tagged with a DIFFERENT
    kind is always refused, tagged or not."""

    def __init__(self, kind: str, *, allow_untagged: bool = False) -> None:
        if kind not in KINDS:
            raise ValueError(f"unregistered blob kind {kind!r}; "
                             f"expected one of {KINDS}")
        self.kind = kind
        self.allow_untagged = allow_untagged

    def pack(self, meta: dict, bufs: dict) -> bytes:
        out = dict(meta)
        out["kind"] = self.kind
        return pack_blob(out, bufs)

    def unpack(self, blob: bytes) -> tuple[dict, dict]:
        meta, bufs = unpack_blob(blob)
        kind = meta.get("kind")
        if kind != self.kind and not (kind is None and
                                      self.allow_untagged):
            raise ProtocolError(
                f"blob kind {kind!r} does not belong on the "
                f"{self.kind!r} lane")
        return meta, bufs


#: the three lane bindings (kv rows tolerate untagged legacy metas;
#: the newer lanes never shipped untagged and do not)
KV_ROW = BlobCodec(KV_ROW_KIND, allow_untagged=True)
PREFIX_TEMPLATE = BlobCodec(TEMPLATE_KIND)
WEIGHTS = BlobCodec(WEIGHTS_KIND)
