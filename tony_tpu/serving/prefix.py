"""Prefix-aware serving: the shared-prefix catalog and the template-ship
plane.

Production traffic is prefix-heavy — system prompts, few-shot
templates, multi-turn chat — and a prefix computed on one replica is
pure waste to recompute on another. This module holds the jax-free
pieces every layer shares:

- **Identity**: a prefix is named by an id — any caller-chosen string,
  or :func:`fingerprint` (a content hash of the token sequence), so
  two processes that never spoke agree on the name of the same prefix.
- **Matching**: :func:`match_prefix` finds the LONGEST registered
  prefix that is a proper token-boundary prefix of a prompt — the
  router's fallback when an ADMIT names no prefix id, and the engine's
  resolution against its resident store.
- **Hosting** (:class:`PrefixHost`): the mixin a serving-plane server
  (colocated :class:`~tony_tpu.serving.server.ServingServer`, the
  disaggregated :class:`~tony_tpu.serving.disagg.PrefillServer`) uses
  to be warmable: a :class:`~tony_tpu.channels.channel.ChannelHub`
  lane (``PREFIX_CHANNEL``) receiving template blobs
  (``kvship.pack_template`` wire shape), an install thread that lands
  them into the host's resident store, the ``PREFIX`` frame ops
  (install / publish / list), and :meth:`PrefixHost.publish_prefix` —
  a warm replica ships its resident template to a cold one in ONE
  channel send instead of the cold replica recomputing the prefill.

A malformed or mismatched template blob (wrong vocab, wrong layer
count, truncated) costs only ITSELF: the install thread logs, records
a flight event, and keeps serving — template warming is an
optimization and must never be able to kill a replica.
"""

from __future__ import annotations

import hashlib
import logging
import struct
import threading

import numpy as np

from tony_tpu.channels.channel import (ChannelClosed, ChannelError,
                                       ChannelHub, ChannelSender)
from tony_tpu.serving import kvship
from tony_tpu.serving import protocol as P

log = logging.getLogger(__name__)

#: the channel lane template blobs ride (one hub port per replica,
#: multiplexed by name — a replica that also lands KV shipments keeps
#: them on their own lane)
PREFIX_CHANNEL = "prefix"


def fingerprint(tokens) -> str:
    """Content-derived prefix id: a 16-hex-digit sha256 over the token
    sequence packed as little-endian u32s. Two processes that tokenized
    the same system prompt name it identically without coordination."""
    packed = struct.pack(f"<{len(tokens)}I",
                         *(int(t) & 0xFFFFFFFF for t in tokens))
    return hashlib.sha256(packed).hexdigest()[:16]


def match_prefix(prompt, catalog) -> str | None:
    """Longest token-boundary match: the id of the longest catalog
    entry that is a PROPER prefix of ``prompt`` (strictly shorter — a
    prompt that IS the prefix leaves no suffix to run through the
    model), or None. ``catalog``: {prefix_id: token list} or an
    iterable of ``(prefix_id, tokens)`` pairs. The ONE copy of the
    matching invariant — the router's catalog fallback and both
    engines' resident-store resolution all come through here. The
    candidate list is snapshotted first: catalogs/stores are grown
    concurrently (register ops, template-install threads) and dict
    iteration during an insert raises."""
    items = catalog.items() if isinstance(catalog, dict) else catalog
    best = None
    best_len = 0
    n = len(prompt)
    for pid, toks in list(items):
        k = len(toks)
        if k <= best_len or k >= n:
            continue
        if list(prompt[:k]) == list(toks):
            best, best_len = pid, k
    return best


class PrefixHost:
    """Mixin: a serving-plane server that hosts resident prefix
    templates and can be WARMED over the template-ship lane (see module
    docstring). The concrete class implements the store:

    - ``install_prefix(tokens, prefix_id=None) -> str | None`` —
      compute the template locally and make it resident (None = the
      host degraded, e.g. a rolling-cache layout);
    - ``install_prefix_template(meta, bufs) -> str`` — land an
      unpacked shipped template (raises ``ValueError`` /
      ``ProtocolError`` on a mismatched one);
    - ``resident_prefixes() -> list[str]``;
    - ``_prefix_blob(prefix_id) -> bytes`` — pack a resident entry for
      publication (raises ``ValueError`` when not resident).

    and calls ``_init_prefix_host(registry)`` in ``__init__``,
    ``_start_prefix_host()`` in ``start()``, ``_stop_prefix_host()``
    in ``stop()``/``kill()``, and routes ``PREFIX`` frames to
    :meth:`_handle_prefix_frame`."""

    def _init_prefix_host(self, registry) -> None:
        self._prefix_reg = registry
        self._prefix_hub = ChannelHub(port=0, capacity=4,
                                      registry=registry)
        self._prefix_install_thread: threading.Thread | None = None
        self._prefix_installs_c = registry.counter(
            "tony_prefix_installs_total",
            help="prefix templates made resident (computed locally or "
                 "landed from a template ship)")
        self._prefix_ships_c = registry.counter(
            "tony_prefix_ships_total",
            help="prefix template blobs published to peer replicas")
        self._prefix_ship_bytes_c = registry.counter(
            "tony_prefix_ship_bytes_total",
            help="prefix template payload bytes published to peers")

    @property
    def prefix_port(self) -> int:
        """The template-ship lane's bound port (HELLO-advertised)."""
        return self._prefix_hub.port

    def _start_prefix_host(self) -> None:
        self._prefix_hub.start()
        self._prefix_install_thread = threading.Thread(
            target=self._prefix_install_loop, name="tony-prefix-install",
            daemon=True)
        self._prefix_install_thread.start()

    def _stop_prefix_host(self) -> None:
        self._prefix_hub.stop()
        if self._prefix_install_thread is not None:
            self._prefix_install_thread.join(timeout=10)

    # -- the install thread (template ships land here) ----------------------
    def _prefix_install_loop(self) -> None:
        receiver = self._prefix_hub.receiver(PREFIX_CHANNEL)
        while True:
            try:
                blob = receiver.recv_bytes(timeout=0.25)
            except ChannelClosed:
                return                  # hub stopped: lane is dead
            except ChannelError:
                continue                # timeout; re-check liveness
            except P.ProtocolError as e:
                log.warning("prefix lane: non-template frame dropped: %s",
                            e)
                continue
            try:
                meta, bufs = kvship.unpack_template(blob)
                pid = self.install_prefix_template(meta, bufs)
                self._prefix_installs_c.inc()
                log.info("prefix %s resident via template ship "
                         "(%d bytes, %d tokens)", pid, len(blob),
                         len(meta["tokens"]))
            except Exception as e:      # noqa: BLE001 — thread survival
                # a bad template costs only itself: warming is an
                # optimization, and a dead install thread would
                # silently make this replica forever cold
                log.warning("prefix lane: template install rejected: %s",
                            e)
                from tony_tpu.runtime import tracing
                tracing.get_flight().record("prefix_template_rejected",
                                            error=str(e)[:500])

    # -- publication --------------------------------------------------------
    def publish_prefix(self, prefix_id: str, target: str,
                       timeout_s: float = 30.0) -> int:
        """Ship the resident template ``prefix_id`` to ``target`` (a
        peer's ``host:prefix_port`` template lane) in ONE
        delivery-confirmed channel send; returns the blob size. The
        peer warms without running a single prefill forward for the
        prefix. Raises ``ValueError`` (not resident) or
        :class:`~tony_tpu.channels.channel.ChannelError` (peer
        unreachable)."""
        blob = self._prefix_blob(prefix_id)
        sender = ChannelSender(target, PREFIX_CHANNEL, window=2,
                               registry=self._prefix_reg)
        try:
            sender.send_bytes(blob, sync=True, timeout=timeout_s)
        finally:
            sender.close(drain=False)
        self._prefix_ships_c.inc()
        self._prefix_ship_bytes_c.inc(len(blob))
        return len(blob)

    # -- the PREFIX frame ops (conn reader threads) -------------------------
    def _handle_prefix_frame(self, conn, rid: int, payload: bytes) -> None:
        """``PREFIX`` op dispatch. Op failures are REQUEST-scoped
        (``{"ok": false, "error": ...}`` back on the same rid) — an
        operator fat-fingering a publish target must not cost the
        connection, let alone the replica."""
        obj = P.unpack_json(payload)    # structural garbage: conn-scoped
        op = obj.get("op")
        try:
            if op == "install":
                tokens = obj.get("tokens")
                if (not isinstance(tokens, list) or not tokens
                        or not all(isinstance(t, int)
                                   and not isinstance(t, bool)
                                   for t in tokens)):
                    raise ValueError("install needs a non-empty token "
                                     "list")
                pid = self.install_prefix(tokens,
                                          prefix_id=obj.get("id"))
                if pid is None:
                    body = {"ok": False,
                            "error": "replica degraded prefix-blind "
                                     "(rolling-cache layout)"}
                else:
                    self._prefix_installs_c.inc()
                    body = {"ok": True, "id": pid,
                            "resident": self.resident_prefixes()}
            elif op == "publish":
                pid = obj.get("id")
                target = obj.get("target")
                if not isinstance(pid, str) or not isinstance(target, str):
                    raise ValueError("publish needs 'id' and 'target'")
                n = self.publish_prefix(pid, target)
                body = {"ok": True, "id": pid, "bytes": n}
            elif op == "list":
                body = {"ok": True,
                        "resident": self.resident_prefixes()}
            else:
                body = {"ok": False, "error": f"unknown prefix op {op!r}"}
        except (ValueError, KeyError, ChannelError, P.ProtocolError) as e:
            body = {"ok": False, "error": str(e)}
        conn.send(P.PREFIX, rid, P.pack_json(body))
