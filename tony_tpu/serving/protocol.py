"""TONYS1 streaming serving protocol: the persistent token-push wire.

One persistent TCP connection multiplexes many in-flight requests in
BOTH directions — the client pushes admissions and cancels, the server
pushes token deltas the moment the engine consumes them — replacing a
request/response round trip per chunk (the pre-streaming tunnel paid
~70-100 ms of transport per chunk AND per admission; see
docs/serving.md "Streaming serving"). The framing keeps the
self-describing discipline of the TONY1 record format
(``tony_tpu/io/framed.py``): a magic preamble so a stray peer fails
fast, an explicit length prefix so a reader can never lose sync, and a
JSON HELLO carrying the server's shape so clients need no out-of-band
schema.

Connection handshake::

    client -> server   magic  b"TONYS1\\0"
    server -> client   HELLO frame, JSON payload {"v": 1, "slots": N}

Frame layout (everything little-endian)::

    length   4 bytes  u32   bytes that FOLLOW (type + rid + payload)
    type     1 byte   u8    frame type (below)
    rid      8 bytes  u64   request id (0 = connection-scoped)
    payload  length-9 bytes

Frame types:

====== ============ ========= =====================================
 type   direction    payload   meaning
====== ============ ========= =====================================
ADMIT   c -> s       JSON      ``{"prompt": [ints], "max_new_tokens":
                               n, "stream": bool}`` — submit request
                               ``rid``. ``stream=false`` buffers
                               deltas server-side for POLL (the
                               request/response contrast arm).
CANCEL  c -> s       (empty)   cancel ``rid`` (idempotent).
POLL    c -> s       (empty)   long-poll ``rid``: the server answers
                               with one TOKENS frame as soon as it
                               has buffered deltas, or RETIRED once
                               the request is done and drained.
TOKENS  s -> c       u32[]     a token DELTA for ``rid`` (packed
                               little-endian u32s, in order).
RETIRED s -> c       JSON      ``{"reason": "eos"|"budget"|
                               "cancelled"|"stopped", "tokens": n}``
                               — terminal, exactly once per request.
ERROR   s -> c       JSON      ``{"message": str}``. ``rid != 0``:
                               that request failed (terminal for it).
                               ``rid == 0``: connection-scoped — the
                               server closes the connection after
                               sending it (a protocol violation never
                               kills the server, only the offending
                               connection).
STATS   c -> s       (empty)   request a stats snapshot;
        s -> c       JSON      answered with a STATS frame carrying
                               at least ``queue_depth`` (the
                               ``tony_serve_queue_depth`` gauge),
                               ``active``, ``slots`` — the router's
                               placement + health signal.
HELLO   s -> c       JSON      connection preamble (see above).
====== ============ ========= =====================================

Everything here is transport-only (stdlib, no jax): importable by thin
clients, the router, and tests alike.
"""

from __future__ import annotations

import json
import socket
import struct

MAGIC = b"TONYS1\0"

ADMIT = 1
CANCEL = 2
POLL = 3
TOKENS = 4
RETIRED = 5
ERROR = 6
STATS = 7
HELLO = 8
#: s -> c (prefill tier, disaggregated serving): request ``rid``'s
#: prefill finished and its KV package shipped to the decode gang named
#: in the JSON payload ({"decode": "host:port", ...}); the router moves
#: the session's ownership from the prefill link to the decode link on
#: this frame (a prefill replica dying AFTER it no longer affects the
#: stream).
HANDOFF = 9
#: c -> s (decode tier, disaggregated serving): this connection is the
#: DELTA SINK — the decode server pushes every KV-adopted row's TOKENS/
#: RETIRED frames here (rids are the shipper's, globally unique per
#: router). Last BIND wins; empty payload.
BIND = 10
#: c -> s then s -> c (prefix-aware serving): a JSON prefix-catalog op
#: and its reply on the same rid. Replica-side ops: ``install`` (make a
#: prefix resident by computing its K/V template locally), ``publish``
#: (ship a resident template to a peer replica's template lane — the
#: warm path), ``list``. Router-side ops: ``register`` (add a prefix to
#: the matching catalog), ``list``. Replies are
#: ``{"ok": bool, ...}`` — op failures are request-scoped, never
#: connection-scoped.
PREFIX = 11
#: c -> router then router -> c (fleet operations): ask the router to
#: DRAIN one replica — ``{"replica": "host:port", "timeout_s": n?}``
#: fences new placements there and live-migrates every session off it
#: (see :meth:`ServingRouter.drain`). The reply rides the same rid once
#: the drain settles: ``{"ok": bool, "replica": ..., "migrated": n,
#: "wall_s": s}``. Runs on a background thread — a drain never blocks
#: the operator connection's other frames.
DRAIN = 12
#: c -> router then router -> c (fleet operations): migrate ONE of the
#: caller's own sessions (``rid``) off its current replica. Reply is
#: ``{"ok": bool}`` on the same rid; the session's token stream is
#: unaffected either way (zero dup/drop — the coordinated-migration
#: contract).
MIGRATE = 13
#: c -> replica then replica -> c (warm scale-up, tony_tpu/serving/
#: weightstore.py): content-addressed weight / compiled-program
#: artifact ops — ``{"op": "publish", "digest", "target"}`` commands
#: this replica to ship a resident artifact to a peer's weights lane;
#: ``{"op": "list"}`` returns the resident digests. Replies are
#: ``{"ok": bool, ...}`` — op failures are request-scoped, never
#: connection-scoped.
WEIGHTS = 14
#: s -> c (QoS-tiered serving): explicit overload shed — the server
#: refuses to queue request ``rid`` and the client should retry after
#: the JSON payload's ``retry_after_ms`` hint. Terminal for ``rid``
#: (exactly one of TOKENS.../RETIRED, ERROR, or BUSY ends a request),
#: and a statement about LOAD, not about the request: the identical
#: ADMIT is expected to succeed once pressure clears, which is why it
#: is a distinct frame rather than an ERROR. Only ``standard``/
#: ``batch`` admissions are shed; ``interactive`` ones queue.
BUSY = 15

FRAME_NAMES = {ADMIT: "ADMIT", CANCEL: "CANCEL", POLL: "POLL",
               TOKENS: "TOKENS", RETIRED: "RETIRED", ERROR: "ERROR",
               STATS: "STATS", HELLO: "HELLO", HANDOFF: "HANDOFF",
               BIND: "BIND", PREFIX: "PREFIX", DRAIN: "DRAIN",
               MIGRATE: "MIGRATE", WEIGHTS: "WEIGHTS", BUSY: "BUSY"}

#: the serving plane's request classes, best SLO first: ``interactive``
#: jumps queues and may preempt batch rows, ``standard`` is the classic
#: FIFO tier (and what a class-less ADMIT means), ``batch`` yields to
#: everyone and absorbs preemption/shedding under overload.
QOS_CLASSES = ("interactive", "standard", "batch")

#: sanity bound on one frame's body (type + rid + payload). A prompt of
#: a million tokens is ~4 MB; anything past this is a corrupt length
#: prefix, not a request.
MAX_FRAME_BYTES = 1 << 24

_LEN = struct.Struct("<I")
_HDR = struct.Struct("<BQ")          # type, rid
_TOK = struct.Struct("<I")

#: bytes of (type, rid) header inside every frame body — what
#: :func:`frame_header` adds to a payload length before checking its
#: limit; exported so other planes' size guards can mirror the check.
BODY_HEADER_BYTES = _HDR.size


class ProtocolError(ValueError):
    """Malformed wire data. Connection-scoped by convention: handlers
    report it (an ERROR frame where possible) and close THAT connection;
    it must never propagate out of a server's per-connection handler."""


def set_nodelay(sock: socket.socket) -> None:
    """Disable Nagle batching. Token-delta frames are tens of bytes;
    coalescing them behind an unacked segment adds up to ~40 ms of
    artificial inter-token latency per delta."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass                       # non-TCP transports (tests, AF_UNIX)


def recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """Read exactly ``n`` bytes. Returns None on clean EOF at a frame
    boundary (byte 0); raises ProtocolError on EOF mid-read (a peer
    that died mid-frame).

    Accumulates via ``recv_into`` on one preallocated buffer: the old
    ``bytes``-list + join path copied every chunk twice, which starts to
    matter once frames carry megabyte tensor payloads (the inter-gang
    channel plane reuses this reader)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            k = sock.recv_into(view[got:])
        except OSError as e:
            if got:
                raise ProtocolError(f"connection lost mid-frame: {e}")
            return None
        if not k:
            if got:
                raise ProtocolError("truncated frame (EOF mid-frame)")
            return None
        got += k
    return bytes(buf)


#: payloads at or above this many bytes skip the concatenated-copy encode
#: and go out as header + payload writes (two sendalls). Below it, one
#: sendall keeps small control frames in a single segment under
#: TCP_NODELAY (framing on the wire is unchanged either way).
LARGE_PAYLOAD_BYTES = 1 << 16


def frame_header(ftype: int, rid: int, payload_len: int,
                 limit: int = MAX_FRAME_BYTES) -> bytes:
    """Length prefix + (type, rid) header for a frame whose payload will
    be written separately — the zero-copy send path and the channel
    plane's TENSOR frames build on this. ``limit`` lets a plane with
    legitimately bigger frames (tensor microbatches) raise the sanity
    cap without loosening the serving wire's."""
    body_len = _HDR.size + payload_len
    if body_len > limit:
        raise ProtocolError(f"frame too large: {body_len} bytes")
    return _LEN.pack(body_len) + _HDR.pack(ftype, rid)


def _payload_nbytes(payload) -> int:
    """Byte length of a frame payload. ``len()`` on a non-byte
    memoryview counts ELEMENTS (a float32 view would understate by 4x
    and corrupt the length prefix) — nbytes is the wire truth."""
    return payload.nbytes if isinstance(payload, memoryview) \
        else len(payload)


def encode_frame(ftype: int, rid: int,
                 payload: bytes | memoryview = b"") -> bytes:
    return frame_header(ftype, rid, _payload_nbytes(payload)) \
        + bytes(payload)


def send_frame(sock: socket.socket, ftype: int, rid: int,
               payload: bytes | memoryview = b"") -> None:
    """Write one frame. Large payloads (tensor-sized) are sent as
    header-then-payload without an intermediate concatenated copy —
    ``payload`` may be a ``memoryview`` straight over a device buffer's
    host copy (any element format; byte length is taken from
    ``nbytes``); small control frames keep the single-sendall
    behavior."""
    n = _payload_nbytes(payload)
    if n >= LARGE_PAYLOAD_BYTES:
        sock.sendall(frame_header(ftype, rid, n))
        sock.sendall(payload)
    else:
        sock.sendall(encode_frame(ftype, rid, payload))


def recv_frame(sock: socket.socket, max_bytes: int = MAX_FRAME_BYTES):
    """Read one frame; returns ``(type, rid, payload)`` or None on clean
    EOF. Raises ProtocolError on truncation or an implausible length
    prefix — the reader can then close without ever losing sync.
    ``max_bytes`` mirrors :func:`frame_header`'s ``limit``.

    The (type, rid) header and the payload are read separately so the
    payload is handed back exactly as received — no full-body slice
    copy for megabyte tensor frames."""
    head = recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length < _HDR.size or length > max_bytes:
        raise ProtocolError(f"implausible frame length {length}")
    hdr = recv_exact(sock, _HDR.size)
    if hdr is None:
        raise ProtocolError("truncated frame (EOF after length prefix)")
    ftype, rid = _HDR.unpack(hdr)
    if length == _HDR.size:
        return ftype, rid, b""
    payload = recv_exact(sock, length - _HDR.size)
    if payload is None:
        raise ProtocolError("truncated frame (EOF after length prefix)")
    return ftype, rid, payload


def read_magic(sock: socket.socket) -> bool:
    """Consume and verify the connection preamble; False on anything
    else (including clean EOF)."""
    try:
        got = recv_exact(sock, len(MAGIC))
    except ProtocolError:
        return False
    return got == MAGIC


def pack_json(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode("utf-8")


def unpack_json(payload: bytes) -> dict:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"malformed JSON payload: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(f"payload is not an object: {obj!r}")
    return obj


def pack_tokens(tokens) -> bytes:
    return b"".join(_TOK.pack(int(t) & 0xFFFFFFFF) for t in tokens)


def unpack_tokens(payload: bytes) -> list[int]:
    if len(payload) % _TOK.size:
        raise ProtocolError(
            f"TOKENS payload of {len(payload)} bytes is not a whole "
            f"number of u32s")
    return [t[0] for t in _TOK.iter_unpack(payload)]


def parse_trace_ctx(payload_or_obj) -> dict | None:
    """Extract the OPTIONAL ``trace`` context from an ADMIT payload:
    ``{"trace": {"tid": <hex>, "sid": <hex>}}`` — the client's (or the
    router's forwarded) span context, so a request's engine-side spans
    join the submitter's trace. Tracing is never load-bearing: anything
    missing or malformed is simply ``None`` (the request still serves;
    the engine head-samples a fresh trace instead)."""
    try:
        obj = payload_or_obj if isinstance(payload_or_obj, dict) \
            else unpack_json(payload_or_obj)
        ctx = obj.get("trace")
        if (isinstance(ctx, dict)
                and isinstance(ctx.get("tid"), str)
                and isinstance(ctx.get("sid"), str)
                and 0 < len(ctx["tid"]) <= 64
                and 0 < len(ctx["sid"]) <= 64):
            return {"tid": ctx["tid"], "sid": ctx["sid"]}
    except ProtocolError:
        pass
    return None


def parse_decode_target(obj: dict) -> str | None:
    """Extract the OPTIONAL disaggregated-serving ``decode`` target
    from a parsed ADMIT object: ``{"decode": "host:port"}`` names the
    decode gang's channel-hub endpoint the prefill tier must ship this
    request's KV package to. None when absent/malformed — a prefill
    server treats a target-less ADMIT as request-scoped error, a
    colocated server ignores the field entirely."""
    addr = obj.get("decode")
    if isinstance(addr, str) and 0 < len(addr) <= 256:
        host, _, port = addr.rpartition(":")
        # a target that cannot dial (no host, non-numeric port) must be
        # rejected HERE as malformed — downstream it would detonate in
        # the channel sender on the prefill tier's worker thread
        if host and port.isdigit() and 0 < int(port) < 65536:
            return addr
    return None


def parse_prefix_id(payload_or_obj) -> str | None:
    """Extract the OPTIONAL ``prefix`` id from an ADMIT payload:
    ``{"prefix": "<id>"}`` names the shared-prefix template the prompt
    continues, so the router can place the session where that prefix's
    KV is already resident and the engine can admit only the suffix
    through the model. Never load-bearing: absent/malformed is simply
    ``None`` (the request still serves, prefix-blind), and a replica
    that does not hold the named template falls back to a full
    prefill — outputs are token-identical either way."""
    try:
        obj = payload_or_obj if isinstance(payload_or_obj, dict) \
            else unpack_json(payload_or_obj)
        pid = obj.get("prefix")
        if isinstance(pid, str) and 0 < len(pid) <= 128:
            return pid
    except ProtocolError:
        pass
    return None


def parse_rng(payload_or_obj) -> tuple[int, int] | None:
    """Extract the OPTIONAL ``rng`` pin from an ADMIT payload:
    ``{"rng": {"stream": s, "off": k}}`` fixes the request's rng STREAM
    index (instead of the engine's local submission counter) and marks
    ``k`` stream positions as already consumed. This is what makes a
    planned migration token-identical under SAMPLING: the router pins
    every session to a fleet-unique stream, and a re-placement that
    folds ``k`` already-streamed tokens into the prompt tells the new
    replica to draw its first sample from position ``k`` — the same
    key, the same offset, the same token the old replica would have
    drawn. Never load-bearing for plain clients: absent/malformed is
    ``None`` (the engine assigns its own stream, off 0)."""
    try:
        obj = payload_or_obj if isinstance(payload_or_obj, dict) \
            else unpack_json(payload_or_obj)
        rng = obj.get("rng")
        if isinstance(rng, dict):
            stream, off = rng.get("stream"), rng.get("off", 0)
            if (isinstance(stream, int) and not isinstance(stream, bool)
                    and isinstance(off, int)
                    and not isinstance(off, bool) and off >= 0):
                return stream, off
    except ProtocolError:
        pass
    return None


def parse_class(payload_or_obj) -> str:
    """Extract the OPTIONAL ``class`` field from an ADMIT payload:
    ``{"class": "interactive"|"standard"|"batch"}`` names the request's
    QoS tier. ABSENT means ``standard`` — an old class-less wire
    behaves exactly as before — but unlike the other optional-field
    helpers a PRESENT-but-invalid value raises ``ValueError``: a client
    that asked for a class it misspelled must hear "no" (request-scoped
    error), not silently serve at a different tier than it believes it
    bought."""
    obj = payload_or_obj if isinstance(payload_or_obj, dict) \
        else unpack_json(payload_or_obj)
    cls = obj.get("class")
    if cls is None:
        return "standard"
    if cls not in QOS_CLASSES:
        raise ValueError(
            f"unknown request class {cls!r} (expected one of "
            f"{', '.join(QOS_CLASSES)})")
    return cls


def parse_admit(payload: bytes) -> tuple[list[int], int, bool]:
    """Validate an ADMIT payload -> (prompt, max_new_tokens, stream).
    Anything structurally off is a ProtocolError (connection-scoped),
    NOT a crash in the engine. The optional ``trace`` context rides
    alongside (see :func:`parse_trace_ctx`)."""
    obj = unpack_json(payload)
    prompt = obj.get("prompt")
    max_new = obj.get("max_new_tokens")
    stream = obj.get("stream", True)
    if (not isinstance(prompt, list)
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in prompt)):
        raise ProtocolError("ADMIT prompt must be a list of ints")
    if isinstance(max_new, bool) or not isinstance(max_new, int):
        raise ProtocolError("ADMIT max_new_tokens must be an int")
    if not isinstance(stream, bool):
        raise ProtocolError("ADMIT stream must be a bool")
    return prompt, max_new, stream
