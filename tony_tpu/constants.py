"""Framework-wide constants: env-var names, file names, job names, chaos hooks.

TPU-native analog of the reference's ``Constants.java`` (reference:
tony-core/src/main/java/com/linkedin/tony/Constants.java:1-101). Same role —
the single table of magic strings shared by client, coordinator and executor —
but the exported runtime environment targets ``jax.distributed`` on TPU pod
slices instead of TF_CONFIG/CUDA.
"""

# ---------------------------------------------------------------------------
# Job / task naming (Constants.java: am/worker/ps/notebook/driver)
# ---------------------------------------------------------------------------
COORDINATOR_JOB_NAME = "am"        # kept as "am" for config compat with the reference
WORKER_JOB_NAME = "worker"
PS_JOB_NAME = "ps"
CHIEF_JOB_NAME = "chief"
NOTEBOOK_JOB_NAME = "notebook"
DRIVER_JOB_NAME = "driver"
EVALUATOR_JOB_NAME = "evaluator"

# ---------------------------------------------------------------------------
# Core task env vars (Constants.java: JOB_NAME/TASK_INDEX/TASK_NUM/...)
# ---------------------------------------------------------------------------
JOB_NAME = "JOB_NAME"
TASK_INDEX = "TASK_INDEX"
TASK_NUM = "TASK_NUM"
SESSION_ID = "SESSION_ID"
ATTEMPT_NUMBER = "ATTEMPT_NUMBER"
CLUSTER_SPEC = "CLUSTER_SPEC"
IS_CHIEF = "IS_CHIEF"

# Set in the environment of a preprocess / single-node job run inside the
# coordinator (reference: Constants.java:39, doPreprocessingJob:717).
PREPROCESSING_JOB = "PREPROCESSING_JOB"

# Control-plane auth (the ClientToAMToken analog, reference:
# TFClientSecurityInfo / TonyApplicationMaster.java:442-452): a per-job
# shared secret generated at submission, carried to the coordinator and every
# executor via this env var, and attached to every RPC as gRPC metadata.
TONY_SECRET = "TONY_SECRET"
# Short-lived GCS access token for the job's scoped service account
# (tony.gcs.service-account): rides env only, honored by every GcsStorage
# subprocess as CLOUDSDK_AUTH_ACCESS_TOKEN.
TONY_GCS_TOKEN = "TONY_GCS_TOKEN"
# Path to a file holding the CURRENT token — re-read per storage call, so
# client-pushed renewals reach user processes that forked before the
# renewal (env can't change after fork; a file can).
TONY_GCS_TOKEN_FILE = "TONY_GCS_TOKEN_FILE"
AUTH_METADATA_KEY = "tony-auth"
TONY_SECRET_FILE = ".tony-secret"

# Control-plane TLS (the HTTPS-keystore/kerberos analog, reference:
# TonyConfigurationKeys.java:55-68): per-job self-signed cert generated at
# submission (rpc/tls.py), staged like the secret; env vars carry the staged
# file PATHS to the coordinator (key + cert) and executors (cert only).
TONY_TLS_CERT = "TONY_TLS_CERT"
TONY_TLS_KEY = "TONY_TLS_KEY"
TONY_TLS_CERT_FILE = ".tony-tls.crt"
TONY_TLS_KEY_FILE = ".tony-tls.key"

# Profiling (tony.task.profile.* → executor env → runtime.maybe_start):
# first-class per-host jax.profiler capture (SURVEY.md §5 calls this out as
# the TPU-native addition over the reference's TensorBoard-URL-only
# observability).
TONY_PROFILE_ENABLED = "TONY_PROFILE_ENABLED"
TONY_PROFILE_DIR = "TONY_PROFILE_DIR"

# Distributed tracing + flight recorder (tony.trace.* / the flight
# recorder → executor/coordinator env → runtime/tracing.py). The SPOOL
# file is the bridge from the fork-exec'd user process to the
# coordinator: the user process's tracer mirrors finished spans to it,
# the executor tails it onto heartbeats. CTX is the job root trace
# context ("tid:sid") so every process's coarse spans hang off one job
# trace; PROC labels the process in exported traces.
TONY_TRACE_SPOOL = "TONY_TRACE_SPOOL"
TONY_TRACE_PROC = "TONY_TRACE_PROC"
TONY_TRACE_CTX = "TONY_TRACE_CTX"
TONY_TRACE_SAMPLE_RATE = "TONY_TRACE_SAMPLE_RATE"
TONY_TRACE_RING = "TONY_TRACE_RING"
TONY_FLIGHT_DIR = "TONY_FLIGHT_DIR"
TONY_FLIGHT_RING = "TONY_FLIGHT_RING"

# Goodput ledger (runtime/goodput.py). Same bridge shape as the trace
# spool: the fork-exec'd user process publishes its cumulative ledger
# snapshot to this file (atomic rename, last-write-wins) and the executor
# merges it into the host ledger it ships on heartbeats.
TONY_GOODPUT_SPOOL = "TONY_GOODPUT_SPOOL"

# Pseudo job-name under which the coordinator surfaces the tracking
# (TensorBoard / notebook) URL in get_task_urls — the analog of the YARN
# application tracking URL the reference sets reflectively
# (TonyApplicationMaster.java:890-906).
TRACKING_URL_TASK_NAME = "tracking"
# Port reserved by the executor for a notebook job's HTTP server; exported
# so the user command can bind it (e.g. jupyter lab --port=$NOTEBOOK_PORT).
NOTEBOOK_PORT = "NOTEBOOK_PORT"

# TensorFlow adapter (Constants.java: TF_CONFIG, TB_PORT)
TF_CONFIG = "TF_CONFIG"
TB_PORT = "TB_PORT"

# PyTorch adapter (Constants.java:29-33 RANK/WORLD/INIT_METHOD)
RANK = "RANK"
WORLD = "WORLD"
INIT_METHOD = "INIT_METHOD"

# JAX adapter — the TPU-native first-class runtime. The direct analog of the
# reference's TF_CONFIG assembly (TaskExecutor.java:131-141): everything a
# process needs for jax.distributed.initialize() plus mesh/topology metadata.
JAX_COORDINATOR_ADDRESS = "TONY_JAX_COORDINATOR_ADDRESS"
JAX_PROCESS_ID = "TONY_JAX_PROCESS_ID"
JAX_NUM_PROCESSES = "TONY_JAX_NUM_PROCESSES"
# Cluster-spec generation the user process was launched under: bumped by
# the coordinator on every elastic shrink/regrow, so a resumed user
# process can tell "same gang, new world size" apart from a coordinator
# retry (ATTEMPT_NUMBER) and a session re-run (SESSION_ID).
CLUSTER_EPOCH = "TONY_CLUSTER_EPOCH"
TPU_TOPOLOGY = "TONY_TPU_TOPOLOGY"
TPU_CHIPS_PER_HOST = "TONY_TPU_CHIPS_PER_HOST"
MESH_SPEC = "TONY_MESH_SPEC"           # JSON: {"axes": {...}, "dcn_axes": {...}, "slice_spec": {...}}
SLICE_ID = "TONY_SLICE_ID"             # this host's gang index within its job type
NUM_SLICES = "TONY_NUM_SLICES"         # gangs backing this job type (tony.{job}.slices)
# libtpu's multi-slice (DCN collectives) contract, exported alongside the
# TONY_* pair for JAX-framework multi-slice job types: libtpu reads these to
# set up the cross-slice transport (the same env GKE/queued-resources
# multislice deployments inject).
MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"

# Cross-slice MPMD pipeline (tony.pipeline.stages + per-gang PROGRAMS):
# the executor exports this gang's stage identity and the inter-gang
# channel endpoints the coordinator's channel registry assigned, so the
# trainer can stand up its tensor channels (tony_tpu.channels) without
# any coordinator RPC on the data path.
PIPELINE_STAGE = "TONY_PIPELINE_STAGE"            # this gang's stage id
PIPELINE_NUM_STAGES = "TONY_PIPELINE_NUM_STAGES"
PIPELINE_RANK = "TONY_PIPELINE_RANK"              # rank within the stage
CHANNEL_PORT = "TONY_CHANNEL_PORT"                # own hub's listen port
CHANNEL_PREV = "TONY_CHANNEL_PREV"                # upstream peer hub host:port
CHANNEL_NEXT = "TONY_CHANNEL_NEXT"                # downstream peer hub host:port
PIPELINE_INTERLEAVE = "TONY_PIPELINE_INTERLEAVE"  # virtual stages per gang
CHANNEL_COMPRESSION = "TONY_CHANNEL_COMPRESSION"  # wire codec (none/bf16/int8)

# Data-feed handshake (replaces the reference's PY4J_GATEWAY_PORT,
# Constants.java / TaskExecutor.java:87 — pure-Python executor needs no py4j).
DATA_FEED_SPEC = "TONY_DATA_FEED_SPEC"

# ---------------------------------------------------------------------------
# File names (Constants.java: tony-final.xml, tony_src.zip, venv.zip)
# ---------------------------------------------------------------------------
TONY_FINAL_XML = "tony-final.xml"
TONY_XML = "tony.xml"
TONY_SITE_XML = "tony-site.xml"
TONY_SRC_ZIP = "tony_src.zip"
TONY_VENV_ZIP = "venv.zip"
TONY_VENV_DIR = "venv"
TONY_JOB_DIR_PREFIX = ".tony"          # staging dir per-application
TONY_LOG_DIR = "logs"
# Coordinator-published job-dir files (the application-report channel the
# reference got from YARN). Defined here so the TPU backend can exclude
# these per-run volatile files from its content-addressed stage digest
# without importing the coordinator module.
COORDINATOR_ADDR_FILE = "coordinator.addr"
FINAL_STATUS_FILE = "final-status.json"


def task_log_stem(task_id: str) -> str:
    """Log-file stem for a task id ("worker:0" → "worker-0") — the ONE
    definition shared by every writer (backends, coordinator task URLs)
    and reader (`tony logs`)."""
    return task_id.replace(":", "-")
CORE_SITE_CONF = "core-site.xml"

# History file suffixes (HistoryFileUtils.java:11-32)
HISTFILE_SUFFIX = "jhist"
INPROGRESS_SUFFIX = "inprogress"

# ---------------------------------------------------------------------------
# Chaos-test env hooks (Constants.java:73-78). These are read by PRODUCTION
# code, exactly as in the reference — the E2E suite drives failure paths
# through them (TestTonyE2E.java:86-117,179-207).
# ---------------------------------------------------------------------------
TEST_AM_CRASH = "TEST_AM_CRASH"                              # coordinator suicides after start
TEST_WORKER_TERMINATION = "TEST_WORKER_TERMINATION"          # coordinator kills workers when chief registers
TEST_TASK_EXECUTOR_HANG = "TEST_TASK_EXECUTOR_HANG"          # executor sleeps 20s then exits
TEST_TASK_EXECUTOR_NUM_HB_MISS = "TEST_TASK_EXECUTOR_NUM_HB_MISS"  # heartbeater skips N pings
TEST_TASK_EXECUTOR_SKEW = "TEST_TASK_EXECUTOR_SKEW"          # "job#idx#ms" sleep after training
TEST_PREEMPT_SLICE = "TEST_PREEMPT_SLICE"                    # TPU-only: simulate slice preemption
# Deterministic gang-loss injection for the local backend (the elastic
# suite's kill-gang-at-step hook): ';'-separated one-shot clauses of
# "task_id[,task_id...][@marker_path]". Without a marker the listed tasks
# are SIGKILLed (and reported preempted) as soon as they run; with one,
# the kill fires when the marker file exists — trainers touch the marker
# from a step hook, making "kill gang G at step K" exactly reproducible.
TEST_PREEMPT_TASKS = "TEST_PREEMPT_TASKS"
# Coordinator-kill chaos for the local backend (the crash-recovery
# suite's kill-coordinator-at-step hook): the value is a marker-file
# path; when the marker exists the backend SIGKILLs the COORDINATOR
# process (the local backend runs inside it) exactly once — a sentinel
# file ("<marker>.fired") survives the kill so the restarted
# coordinator does not re-fire. Trainers touch the marker from a step
# hook, making "kill the coordinator at step K" exactly reproducible.
TEST_KILL_COORDINATOR = "TEST_KILL_COORDINATOR"

# ---------------------------------------------------------------------------
# Exit codes / misc
# ---------------------------------------------------------------------------
EXIT_SUCCESS = 0
EXIT_FAILURE = -1
# Executor suicide after sustained heartbeat-send failures (75 = BSD
# EX_TEMPFAIL; the reference loses this by exiting -1, TaskExecutor.java:
# 264-268). A user process could also exit 75, so triage additionally
# checks delivery channel: a result that ARRIVED over RPC proves
# executor->coordinator connectivity and is never labeled a loss.
EXIT_LOST_COORDINATOR = 75
# Trainer suicide after a COLLECTIVE/distributed-runtime failure (gang
# peers vanished under it): run_training raises GangLostError, trainers
# exit with this code, and the executor holds the report briefly —
# an elastic resync directive usually arrives within a heartbeat, in
# which case the executor relaunches the trainer against the new world
# instead of reporting a failure at all.
EXIT_GANG_LOST = 76
COORDINATOR_RPC_PORT_RANGE = (10000, 15000)  # ApplicationRpcServer.java:36

# Framework adapters (MLFramework enum, TonyConfigurationKeys.java:8-11,
# extended with JAX as the TPU-first default).
FRAMEWORK_JAX = "jax"
FRAMEWORK_TENSORFLOW = "tensorflow"
FRAMEWORK_PYTORCH = "pytorch"
SUPPORTED_FRAMEWORKS = (FRAMEWORK_JAX, FRAMEWORK_TENSORFLOW, FRAMEWORK_PYTORCH)
